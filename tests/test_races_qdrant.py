"""Adversarial interleaving tests, batch 3: the Qdrant compat plane and
search-index persistence (VERDICT r4 #7 — corpus depth).

Covered interleaving classes:
- alias rename/flips racing point searches and upserts through the
  alias (never a 404 for a continuously-valid alias; upserts land in
  exactly one of the flip targets)
- collection create/delete churn racing searches on a stable sibling
- wire cache generation vs concurrent upserts (search results through
  the gRPC-cached layer never go backwards after an acked upsert)
- index save (debounced snapshot writer) racing mutations: the
  persisted snapshot always loads and re-serves a consistent index
- the grpc.aio wire itself: concurrent Upsert convoys (BatchCoalescer),
  Search bytes riding the shared WireCache, and alias flips — all
  through a real server and channel; plus the named regression that
  EVERY write surface (point ops, Cypher writes, bulk clears)
  invalidates cached Search response bytes
"""

import threading
import time

import numpy as np
import pytest

from nornicdb_tpu.api.qdrant import QdrantCompat, QdrantError
from nornicdb_tpu.storage import MemoryEngine


def _vec(i, dims=16):
    rng = np.random.default_rng(i)
    v = rng.standard_normal(dims)
    return (v / np.linalg.norm(v)).tolist()


def _mk(dims=16):
    q = QdrantCompat(MemoryEngine())
    q.create_collection("stable", {"size": dims, "distance": "Cosine"})
    q.upsert_points("stable", [
        {"id": i, "vector": _vec(i)} for i in range(50)
    ])
    return q


class TestAliasFlipRaces:
    def test_alias_flip_storm_searches_never_404(self):
        """An alias continuously flipped between two live collections:
        searches THROUGH the alias must always succeed and return
        points belonging to one of the two targets — never a 404, never
        a mixture."""
        q = _mk()
        q.create_collection("blue", {"size": 16, "distance": "Cosine"})
        q.create_collection("green", {"size": 16, "distance": "Cosine"})
        q.upsert_points("blue", [
            {"id": 100 + i, "vector": _vec(100 + i)} for i in range(20)])
        q.upsert_points("green", [
            {"id": 200 + i, "vector": _vec(200 + i)} for i in range(20)])
        q.update_aliases([{"create": {"alias": "live", "collection": "blue"}}])
        errors = []
        stop = threading.Event()

        def flipper():
            targets = ["green", "blue"]
            for i in range(200):
                q.update_aliases([
                    {"delete": {"alias": "live"}},
                    {"create": {"alias": "live",
                                "collection": targets[i % 2]}},
                ])

        def searcher():
            while not stop.is_set():
                try:
                    hits = q.search_points("live", _vec(1), limit=5)
                except QdrantError as e:
                    errors.append(("search", str(e)))
                    return
                ids = {h["id"] for h in hits}
                if ids and not (
                    all(100 <= i < 120 for i in ids)
                    or all(200 <= i < 220 for i in ids)
                ):
                    errors.append(("mixed", ids))
                    return

        st = [threading.Thread(target=searcher) for _ in range(2)]
        ft = threading.Thread(target=flipper)
        for t in st:
            t.start()
        ft.start()
        ft.join()
        stop.set()
        for t in st:
            t.join()
        assert errors == []

    def test_upserts_through_flipping_alias_land_exactly_once(self):
        """Writers upsert through the alias while it flips; every acked
        point must exist in exactly one of the two targets."""
        q = _mk()
        q.create_collection("blue", {"size": 16, "distance": "Cosine"})
        q.create_collection("green", {"size": 16, "distance": "Cosine"})
        q.update_aliases([{"create": {"alias": "w", "collection": "blue"}}])
        acked = []
        lock = threading.Lock()

        def flipper():
            targets = ["green", "blue"]
            for i in range(100):
                q.update_aliases([
                    {"delete": {"alias": "w"}},
                    {"create": {"alias": "w",
                                "collection": targets[i % 2]}},
                ])

        def writer(t):
            for i in range(50):
                pid = 1000 + t * 100 + i
                q.upsert_points("w", [{"id": pid, "vector": _vec(pid)}])
                with lock:
                    acked.append(pid)

        ft = threading.Thread(target=flipper)
        wts = [threading.Thread(target=writer, args=(t,)) for t in range(2)]
        ft.start()
        for t in wts:
            t.start()
        ft.join()
        for t in wts:
            t.join()
        blue = {p["id"] for p in q.scroll_points(
            "blue", limit=10_000)["points"]}
        green = {p["id"] for p in q.scroll_points(
            "green", limit=10_000)["points"]}
        for pid in acked:
            in_blue = pid in blue
            in_green = pid in green
            assert in_blue or in_green, f"acked point {pid} vanished"
            assert not (in_blue and in_green), f"point {pid} duplicated"


class TestCollectionChurnVsSearch:
    def test_create_delete_churn_isolated(self):
        q = _mk()
        errors = []
        stop = threading.Event()

        def churner(t):
            for i in range(40):
                name = f"tmp{t}"
                try:
                    q.create_collection(name, {"size": 16,
                                               "distance": "Cosine"})
                    q.upsert_points(name, [{"id": 1, "vector": _vec(1)}])
                    q.delete_collection(name)
                except QdrantError as e:
                    if "already exists" not in str(e) \
                            and "not found" not in str(e):
                        errors.append(str(e))

        def searcher():
            while not stop.is_set():
                try:
                    hits = q.search_points("stable", _vec(3), limit=5)
                    if len(hits) == 0:
                        errors.append("stable search went empty")
                        return
                except QdrantError as e:  # pragma: no cover
                    errors.append(str(e))
                    return

        st = threading.Thread(target=searcher)
        cts = [threading.Thread(target=churner, args=(t,))
               for t in range(3)]
        st.start()
        for t in cts:
            t.start()
        for t in cts:
            t.join()
        stop.set()
        st.join()
        assert errors == []
        assert len(q.search_points("stable", _vec(3), limit=5)) == 5


class TestWireCacheVsUpserts:
    def test_search_results_never_regress_after_acked_upsert(self):
        """Readers repeat one query while a writer adds points ever
        closer to the query vector. Once a reader has seen point N in
        the top-1, no later read may revert to an older point — a
        cached entry surviving its generation bump would do exactly
        that."""
        q = _mk()
        target = np.asarray(_vec(999))
        acked = [0]  # highest point index whose upsert has RETURNED
        errors = []
        saw_new = [0]
        stop = threading.Event()

        # orthonormal complement of the target: point i sits at angle
        # theta_i, strictly decreasing in i, so similarity to the
        # target is strictly increasing — monotone by construction
        u = np.asarray(_vec(555))
        u = u - target * float(target @ u)
        u = u / np.linalg.norm(u)

        def writer():
            for i in range(1, 40):
                theta = 1.0 / (i + 1.0)
                v = (np.cos(theta) * target + np.sin(theta) * u).tolist()
                q.upsert_points("stable", [{"id": 5000 + i, "vector": v}])
                acked[0] = i  # publish AFTER the ack returned
                time.sleep(0.002)

        def reader():
            # the contract under test: a request that STARTS after
            # upsert i acked must observe at least point i — a cached
            # entry surviving its generation bump would serve older
            while not stop.is_set():
                floor = acked[0]
                hits = q.search_points("stable", target.tolist(), limit=1)
                if not hits:
                    continue
                top = hits[0]["id"]
                n = top - 5000 if top >= 5000 else 0
                if n < floor:
                    errors.append((floor, n))
                    return
                if n:
                    saw_new[0] = max(saw_new[0], n)

        wt = threading.Thread(target=writer)
        rts = [threading.Thread(target=reader) for _ in range(2)]
        wt.start()
        for t in rts:
            t.start()
        wt.join()
        stop.set()
        for t in rts:
            t.join()
        assert errors == [], f"stale cached result after ack: {errors}"
        assert saw_new[0] > 0  # the race actually exercised the path


class TestIndexPersistenceRaces:
    def test_save_racing_mutations_always_loads_consistent(self, tmp_path):
        """SearchService snapshot writers race indexers/removers; every
        snapshot written must load into a service that answers searches
        consistently with SOME prefix of the mutation stream."""
        from nornicdb_tpu.embed.embedder import HashEmbedder
        from nornicdb_tpu.search.service import SearchService
        from nornicdb_tpu.storage.types import Node

        store = MemoryEngine()
        svc = SearchService(storage=store, embedder=HashEmbedder(dims=16),
                            persist_dir=str(tmp_path), save_debounce_s=0.0)
        ids = []
        for i in range(100):
            node = Node(id=f"d{i}", labels=["Doc"],
                        properties={"text": f"document {i} topic {i % 5}"})
            store.create_node(node)
            svc.index_node(node)
            ids.append(node.id)
        stop = threading.Event()
        save_errors = []

        def saver():
            while not stop.is_set():
                try:
                    svc.save_indexes()
                except Exception as exc:  # pragma: no cover
                    save_errors.append(repr(exc))

        def mutator(t):
            for i in range(50):
                nid = f"m{t}_{i}"
                node = Node(id=nid, labels=["Doc"],
                            properties={"text": f"mutant {t} {i}"})
                store.create_node(node)
                svc.index_node(node)
                if i % 3 == 0:
                    svc.remove_node(nid)
                    store.delete_node(nid)

        st = threading.Thread(target=saver)
        mts = [threading.Thread(target=mutator, args=(t,))
               for t in range(3)]
        st.start()
        for t in mts:
            t.start()
        for t in mts:
            t.join()
        stop.set()
        st.join()
        assert save_errors == []
        svc.save_indexes()
        svc.close()

        # a fresh service must load the snapshot and serve
        svc2 = SearchService(storage=store, embedder=HashEmbedder(dims=16),
                             persist_dir=str(tmp_path))
        assert svc2.load_indexes()
        hits = svc2.search("document topic", limit=10, mode="text")
        assert hits
        for h in hits:
            assert store.has_node(h["id"])
        svc2.close()


# -- grpc.aio wire-level races (the serving path itself) -----------------


class _AioStack:
    """One DB + aio GrpcServer + raw channel helpers, torn down fully."""

    def __init__(self, dims=16):
        import grpc

        import nornicdb_tpu
        from nornicdb_tpu.api.grpc_server import GrpcServer
        from nornicdb_tpu.api.proto import qdrant_pb2 as q

        self.q = q
        self.dims = dims
        self.db = nornicdb_tpu.open(auto_embed=False)
        self.srv = GrpcServer(self.db, port=0).start()
        self.channel = grpc.insecure_channel(self.srv.address)
        self.grpc = grpc

    def call(self, method, request, response_cls):
        return self.channel.unary_unary(
            method,
            request_serializer=lambda r: r.SerializeToString(),
            response_deserializer=response_cls.FromString,
        )(request)

    def create(self, name):
        req = self.q.CreateCollection(collection_name=name)
        req.vectors_config.params.size = self.dims
        req.vectors_config.params.distance = self.q.Cosine
        self.call("/qdrant.Collections/Create", req,
                  self.q.CollectionOperationResponse)

    def upsert(self, name, pid, vec, channel=None):
        up = self.q.UpsertPoints(collection_name=name)
        p = up.points.add()
        p.id.num = pid
        p.vectors.vector.data.extend(vec)
        ch = channel or self.channel
        return ch.unary_unary(
            "/qdrant.Points/Upsert",
            request_serializer=lambda r: r.SerializeToString(),
            response_deserializer=self.q.PointsOperationResponse.FromString,
        )(up)

    def close(self):
        self.channel.close()
        self.srv.stop()
        self.db.close()


class TestAioWireRaces:
    def test_concurrent_aio_upserts_land_exactly_once(self):
        """N client threads push disjoint point ranges through the aio
        Upsert path concurrently — the convoy coalescer merges them into
        batched applies, but every acked point must exist exactly once
        and the final count must be exact."""
        s = _AioStack()
        try:
            s.create("conc")
            n_threads, per = 8, 40
            errors = []

            def writer(t):
                import grpc as _grpc

                ch = _grpc.insecure_channel(s.srv.address)
                try:
                    for i in range(per):
                        pid = t * 1000 + i
                        resp = s.upsert("conc", pid, _vec(pid), channel=ch)
                        if resp.result.status != s.q.Completed:
                            errors.append(("status", pid))
                except Exception as exc:  # pragma: no cover
                    errors.append(repr(exc))
                finally:
                    ch.close()

            ts = [threading.Thread(target=writer, args=(t,))
                  for t in range(n_threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert errors == []
            resp = s.call("/qdrant.Points/Count",
                          s.q.CountPoints(collection_name="conc"),
                          s.q.CountResponse)
            assert resp.result.count == n_threads * per
            # convoys actually formed (not a degenerate 1-by-1 path)
            co = s.db.qdrant_compat._upsert_coalescer
            assert co.batched_items == n_threads * per
            # spot-check retrievability of a few acked points
            get = s.q.GetPoints(collection_name="conc")
            for pid in (0, 3039, 7039):
                get.ids.add().num = pid
            resp = s.call("/qdrant.Points/Get", get, s.q.GetResponse)
            assert len(resp.result) == 3
        finally:
            s.close()

    def test_wrong_dim_search_rejected_not_convoy_poisoning(self):
        """A wrong-dimension Search must come back INVALID_ARGUMENT (the
        compat layer validates before the shared microbatcher) and must
        not fail concurrent well-formed searches coalesced with it."""
        s = _AioStack()
        try:
            s.create("dims")
            for i in range(10):
                s.upsert("dims", i, _vec(i))
            good = s.q.SearchPoints(collection_name="dims",
                                    vector=_vec(1), limit=3)
            bad = s.q.SearchPoints(collection_name="dims",
                                   vector=[1.0, 0.0], limit=3)  # 2 != 16
            errors = []

            def good_reader():
                for _ in range(60):
                    resp = s.call("/qdrant.Points/Search", good,
                                  s.q.SearchResponse)
                    if len(resp.result) != 3:
                        errors.append("good search degraded")
                        return

            def bad_reader():
                import grpc as _grpc

                for _ in range(60):
                    try:
                        s.call("/qdrant.Points/Search", bad,
                               s.q.SearchResponse)
                        errors.append("bad search accepted")
                        return
                    except _grpc.RpcError as e:
                        if e.code() != _grpc.StatusCode.INVALID_ARGUMENT:
                            errors.append(("code", str(e.code())))
                            return

            ts = [threading.Thread(target=good_reader),
                  threading.Thread(target=bad_reader)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert errors == []
        finally:
            s.close()

    def test_wire_search_never_regresses_after_acked_upsert(self):
        """Readers repeat ONE request-bytes Search through the aio wire
        cache while a writer upserts ever-closer points through the same
        server. Once upsert i has acked, no later wire read may serve a
        pre-i cached response — the wire cache's generation validation
        under real RPC concurrency."""
        s = _AioStack()
        try:
            s.create("stable")
            for i in range(20):
                s.upsert("stable", i, _vec(i))
            target = np.asarray(_vec(999))
            u = np.asarray(_vec(555))
            u = u - target * float(target @ u)
            u = u / np.linalg.norm(u)
            sr = s.q.SearchPoints(collection_name="stable",
                                  vector=target.tolist(), limit=1)
            sr_bytes = sr.SerializeToString()
            acked = [0]
            errors = []
            saw_new = [0]
            stop = threading.Event()

            def writer():
                for i in range(1, 40):
                    theta = 1.0 / (i + 1.0)
                    v = (np.cos(theta) * target + np.sin(theta) * u).tolist()
                    s.upsert("stable", 5000 + i, v)
                    acked[0] = i  # publish AFTER the RPC returned
                    time.sleep(0.002)

            def reader():
                import grpc as _grpc

                ch = _grpc.insecure_channel(s.srv.address)
                stub = ch.unary_unary(
                    "/qdrant.Points/Search",
                    request_serializer=lambda b: b,
                    response_deserializer=s.q.SearchResponse.FromString)
                try:
                    while not stop.is_set():
                        floor = acked[0]
                        resp = stub(sr_bytes)
                        if not resp.result:
                            continue
                        top = int(resp.result[0].id.num)
                        n = top - 5000 if top >= 5000 else 0
                        if n < floor:
                            errors.append((floor, n))
                            return
                        if n:
                            saw_new[0] = max(saw_new[0], n)
                finally:
                    ch.close()

            wt = threading.Thread(target=writer)
            rts = [threading.Thread(target=reader) for _ in range(3)]
            wt.start()
            for t in rts:
                t.start()
            wt.join()
            stop.set()
            for t in rts:
                t.join()
            assert errors == [], f"stale wire-cached result: {errors}"
            assert saw_new[0] > 0  # the race actually exercised the path
        finally:
            s.close()

    def test_alias_flip_storm_through_aio_wire(self):
        """UpdateAliases RPCs continuously flip an alias between two
        collections while readers Search through the alias with ONE
        fixed request-bytes payload: responses must always parse, never
        404, and never mix the two targets — an alias flip must
        invalidate cached response bytes (blue/green swap contract)."""
        s = _AioStack()
        try:
            s.create("blue")
            s.create("green")
            for i in range(20):
                s.upsert("blue", 100 + i, _vec(100 + i))
                s.upsert("green", 200 + i, _vec(200 + i))

            def set_alias(target, drop_first=False):
                # one atomic ChangeAliases batch: delete+create apply
                # under a single lock acquisition server-side, so the
                # alias never has a "does not exist" window
                req = s.q.ChangeAliases()
                if drop_first:
                    a = req.actions.add()
                    a.delete_alias.alias_name = "live"
                a = req.actions.add()
                a.create_alias.alias_name = "live"
                a.create_alias.collection_name = target
                s.call("/qdrant.Collections/UpdateAliases", req,
                       s.q.CollectionOperationResponse)

            set_alias("blue")
            sr = s.q.SearchPoints(collection_name="live",
                                  vector=_vec(1), limit=5)
            sr_bytes = sr.SerializeToString()
            errors = []
            stop = threading.Event()

            def flipper():
                targets = ["green", "blue"]
                for i in range(150):
                    set_alias(targets[i % 2], drop_first=True)

            def searcher():
                import grpc as _grpc

                ch = _grpc.insecure_channel(s.srv.address)
                stub = ch.unary_unary(
                    "/qdrant.Points/Search",
                    request_serializer=lambda b: b,
                    response_deserializer=s.q.SearchResponse.FromString)
                try:
                    while not stop.is_set():
                        try:
                            resp = stub(sr_bytes)
                        except _grpc.RpcError as e:
                            errors.append(("rpc", str(e)))
                            return
                        ids = {int(p.id.num) for p in resp.result}
                        if ids and not (
                            all(100 <= i < 120 for i in ids)
                            or all(200 <= i < 220 for i in ids)
                        ):
                            errors.append(("mixed", ids))
                            return
                finally:
                    ch.close()

            sts = [threading.Thread(target=searcher) for _ in range(2)]
            ft = threading.Thread(target=flipper)
            for t in sts:
                t.start()
            ft.start()
            ft.join()
            stop.set()
            for t in sts:
                t.join()
            assert errors == []
        finally:
            s.close()


class TestEveryWriteSurfaceInvalidatesWireCache:
    """Named regression: a Search response cached at the WIRE level
    (raw response bytes keyed by request bytes) must be invalidated by
    every write surface — gRPC point ops, Cypher writes arriving over
    any other surface, and bulk clears. A miss on any of these serves
    stale bytes for the whole TTL."""

    def test_cached_search_invalidated_by_every_write_surface(self):
        s = _AioStack(dims=4)
        try:
            s.create("inv")
            s.upsert("inv", 1, [1.0, 0.0, 0.0, 0.0])
            sr = s.q.SearchPoints(collection_name="inv",
                                  vector=[1.0, 0.0, 0.0, 0.0], limit=1)

            def top():
                resp = s.call("/qdrant.Points/Search", sr,
                              s.q.SearchResponse)
                return [int(p.id.num) for p in resp.result]

            # prime + verify the bytes really are cached
            assert top() == [1]
            hits_before = s.srv.wire_cache.stats()["hits"]
            assert top() == [1]
            assert s.srv.wire_cache.stats()["hits"] == hits_before + 1

            # (1) gRPC point op: a closer point must surface immediately
            s.upsert("inv", 2, [1.0, 0.0, 0.0, 0.0])
            s.upsert("inv", 3, [0.9, 0.1, 0.0, 0.0])
            # exact-match tie: id 1 or 2 acceptable, but the response
            # must have been recomputed (id 3 exists in top-3)
            sr3 = s.q.SearchPoints(collection_name="inv",
                                   vector=[1.0, 0.0, 0.0, 0.0], limit=3)
            resp = s.call("/qdrant.Points/Search", sr3, s.q.SearchResponse)
            assert {int(p.id.num) for p in resp.result} == {1, 2, 3}

            # (2) point delete via gRPC
            dp = s.q.DeletePoints(collection_name="inv")
            dp.points.points.ids.add().num = 1
            s.call("/qdrant.Points/Delete", dp, s.q.PointsOperationResponse)
            assert 1 not in set(top())

            # (3) Cypher write over another surface: retarget point 2's
            # vector away from the query — the mutation listener must
            # invalidate the wire cache through the same generation
            assert top() == [2]
            s.db.cypher("MATCH (n) WHERE n._point_id = 2 "
                        "SET n._vector = [0.0, 0.0, 0.0, 1.0]")
            assert top() == [3]

            # (4) Cypher DETACH DELETE (GDPR-style erase)
            s.db.cypher("MATCH (n) WHERE n._point_id = 3 DETACH DELETE n")
            assert 3 not in set(top())

            # (5) bulk clear: drop + recreate the collection
            s.call("/qdrant.Collections/Delete",
                   s.q.DeleteCollection(collection_name="inv"),
                   s.q.CollectionOperationResponse)
            s.create("inv")
            resp = s.call("/qdrant.Points/Search", sr, s.q.SearchResponse)
            assert list(resp.result) == []
        finally:
            s.close()
