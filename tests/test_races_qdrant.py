"""Adversarial interleaving tests, batch 3: the Qdrant compat plane and
search-index persistence (VERDICT r4 #7 — corpus depth).

Covered interleaving classes:
- alias rename/flips racing point searches and upserts through the
  alias (never a 404 for a continuously-valid alias; upserts land in
  exactly one of the flip targets)
- collection create/delete churn racing searches on a stable sibling
- wire cache generation vs concurrent upserts (search results through
  the gRPC-cached layer never go backwards after an acked upsert)
- index save (debounced snapshot writer) racing mutations: the
  persisted snapshot always loads and re-serves a consistent index
"""

import threading
import time

import numpy as np
import pytest

from nornicdb_tpu.api.qdrant import QdrantCompat, QdrantError
from nornicdb_tpu.storage import MemoryEngine


def _vec(i, dims=16):
    rng = np.random.default_rng(i)
    v = rng.standard_normal(dims)
    return (v / np.linalg.norm(v)).tolist()


def _mk(dims=16):
    q = QdrantCompat(MemoryEngine())
    q.create_collection("stable", {"size": dims, "distance": "Cosine"})
    q.upsert_points("stable", [
        {"id": i, "vector": _vec(i)} for i in range(50)
    ])
    return q


class TestAliasFlipRaces:
    def test_alias_flip_storm_searches_never_404(self):
        """An alias continuously flipped between two live collections:
        searches THROUGH the alias must always succeed and return
        points belonging to one of the two targets — never a 404, never
        a mixture."""
        q = _mk()
        q.create_collection("blue", {"size": 16, "distance": "Cosine"})
        q.create_collection("green", {"size": 16, "distance": "Cosine"})
        q.upsert_points("blue", [
            {"id": 100 + i, "vector": _vec(100 + i)} for i in range(20)])
        q.upsert_points("green", [
            {"id": 200 + i, "vector": _vec(200 + i)} for i in range(20)])
        q.update_aliases([{"create": {"alias": "live", "collection": "blue"}}])
        errors = []
        stop = threading.Event()

        def flipper():
            targets = ["green", "blue"]
            for i in range(200):
                q.update_aliases([
                    {"delete": {"alias": "live"}},
                    {"create": {"alias": "live",
                                "collection": targets[i % 2]}},
                ])

        def searcher():
            while not stop.is_set():
                try:
                    hits = q.search_points("live", _vec(1), limit=5)
                except QdrantError as e:
                    errors.append(("search", str(e)))
                    return
                ids = {h["id"] for h in hits}
                if ids and not (
                    all(100 <= i < 120 for i in ids)
                    or all(200 <= i < 220 for i in ids)
                ):
                    errors.append(("mixed", ids))
                    return

        st = [threading.Thread(target=searcher) for _ in range(2)]
        ft = threading.Thread(target=flipper)
        for t in st:
            t.start()
        ft.start()
        ft.join()
        stop.set()
        for t in st:
            t.join()
        assert errors == []

    def test_upserts_through_flipping_alias_land_exactly_once(self):
        """Writers upsert through the alias while it flips; every acked
        point must exist in exactly one of the two targets."""
        q = _mk()
        q.create_collection("blue", {"size": 16, "distance": "Cosine"})
        q.create_collection("green", {"size": 16, "distance": "Cosine"})
        q.update_aliases([{"create": {"alias": "w", "collection": "blue"}}])
        acked = []
        lock = threading.Lock()

        def flipper():
            targets = ["green", "blue"]
            for i in range(100):
                q.update_aliases([
                    {"delete": {"alias": "w"}},
                    {"create": {"alias": "w",
                                "collection": targets[i % 2]}},
                ])

        def writer(t):
            for i in range(50):
                pid = 1000 + t * 100 + i
                q.upsert_points("w", [{"id": pid, "vector": _vec(pid)}])
                with lock:
                    acked.append(pid)

        ft = threading.Thread(target=flipper)
        wts = [threading.Thread(target=writer, args=(t,)) for t in range(2)]
        ft.start()
        for t in wts:
            t.start()
        ft.join()
        for t in wts:
            t.join()
        blue = {p["id"] for p in q.scroll_points(
            "blue", limit=10_000)["points"]}
        green = {p["id"] for p in q.scroll_points(
            "green", limit=10_000)["points"]}
        for pid in acked:
            in_blue = pid in blue
            in_green = pid in green
            assert in_blue or in_green, f"acked point {pid} vanished"
            assert not (in_blue and in_green), f"point {pid} duplicated"


class TestCollectionChurnVsSearch:
    def test_create_delete_churn_isolated(self):
        q = _mk()
        errors = []
        stop = threading.Event()

        def churner(t):
            for i in range(40):
                name = f"tmp{t}"
                try:
                    q.create_collection(name, {"size": 16,
                                               "distance": "Cosine"})
                    q.upsert_points(name, [{"id": 1, "vector": _vec(1)}])
                    q.delete_collection(name)
                except QdrantError as e:
                    if "already exists" not in str(e) \
                            and "not found" not in str(e):
                        errors.append(str(e))

        def searcher():
            while not stop.is_set():
                try:
                    hits = q.search_points("stable", _vec(3), limit=5)
                    if len(hits) == 0:
                        errors.append("stable search went empty")
                        return
                except QdrantError as e:  # pragma: no cover
                    errors.append(str(e))
                    return

        st = threading.Thread(target=searcher)
        cts = [threading.Thread(target=churner, args=(t,))
               for t in range(3)]
        st.start()
        for t in cts:
            t.start()
        for t in cts:
            t.join()
        stop.set()
        st.join()
        assert errors == []
        assert len(q.search_points("stable", _vec(3), limit=5)) == 5


class TestWireCacheVsUpserts:
    def test_search_results_never_regress_after_acked_upsert(self):
        """Readers repeat one query while a writer adds points ever
        closer to the query vector. Once a reader has seen point N in
        the top-1, no later read may revert to an older point — a
        cached entry surviving its generation bump would do exactly
        that."""
        q = _mk()
        target = np.asarray(_vec(999))
        acked = [0]  # highest point index whose upsert has RETURNED
        errors = []
        saw_new = [0]
        stop = threading.Event()

        # orthonormal complement of the target: point i sits at angle
        # theta_i, strictly decreasing in i, so similarity to the
        # target is strictly increasing — monotone by construction
        u = np.asarray(_vec(555))
        u = u - target * float(target @ u)
        u = u / np.linalg.norm(u)

        def writer():
            for i in range(1, 40):
                theta = 1.0 / (i + 1.0)
                v = (np.cos(theta) * target + np.sin(theta) * u).tolist()
                q.upsert_points("stable", [{"id": 5000 + i, "vector": v}])
                acked[0] = i  # publish AFTER the ack returned
                time.sleep(0.002)

        def reader():
            # the contract under test: a request that STARTS after
            # upsert i acked must observe at least point i — a cached
            # entry surviving its generation bump would serve older
            while not stop.is_set():
                floor = acked[0]
                hits = q.search_points("stable", target.tolist(), limit=1)
                if not hits:
                    continue
                top = hits[0]["id"]
                n = top - 5000 if top >= 5000 else 0
                if n < floor:
                    errors.append((floor, n))
                    return
                if n:
                    saw_new[0] = max(saw_new[0], n)

        wt = threading.Thread(target=writer)
        rts = [threading.Thread(target=reader) for _ in range(2)]
        wt.start()
        for t in rts:
            t.start()
        wt.join()
        stop.set()
        for t in rts:
            t.join()
        assert errors == [], f"stale cached result after ack: {errors}"
        assert saw_new[0] > 0  # the race actually exercised the path


class TestIndexPersistenceRaces:
    def test_save_racing_mutations_always_loads_consistent(self, tmp_path):
        """SearchService snapshot writers race indexers/removers; every
        snapshot written must load into a service that answers searches
        consistently with SOME prefix of the mutation stream."""
        from nornicdb_tpu.embed.embedder import HashEmbedder
        from nornicdb_tpu.search.service import SearchService
        from nornicdb_tpu.storage.types import Node

        store = MemoryEngine()
        svc = SearchService(storage=store, embedder=HashEmbedder(dims=16),
                            persist_dir=str(tmp_path), save_debounce_s=0.0)
        ids = []
        for i in range(100):
            node = Node(id=f"d{i}", labels=["Doc"],
                        properties={"text": f"document {i} topic {i % 5}"})
            store.create_node(node)
            svc.index_node(node)
            ids.append(node.id)
        stop = threading.Event()
        save_errors = []

        def saver():
            while not stop.is_set():
                try:
                    svc.save_indexes()
                except Exception as exc:  # pragma: no cover
                    save_errors.append(repr(exc))

        def mutator(t):
            for i in range(50):
                nid = f"m{t}_{i}"
                node = Node(id=nid, labels=["Doc"],
                            properties={"text": f"mutant {t} {i}"})
                store.create_node(node)
                svc.index_node(node)
                if i % 3 == 0:
                    svc.remove_node(nid)
                    store.delete_node(nid)

        st = threading.Thread(target=saver)
        mts = [threading.Thread(target=mutator, args=(t,))
               for t in range(3)]
        st.start()
        for t in mts:
            t.start()
        for t in mts:
            t.join()
        stop.set()
        st.join()
        assert save_errors == []
        svc.save_indexes()
        svc.close()

        # a fresh service must load the snapshot and serve
        svc2 = SearchService(storage=store, embedder=HashEmbedder(dims=16),
                             persist_dir=str(tmp_path))
        assert svc2.load_indexes()
        hits = svc2.search("document topic", limit=10, mode="text")
        assert hits
        for h in hits:
            assert store.has_node(h["id"])
        svc2.close()
