"""DB facade integration tests: the full embedded-API surface
(reference: pkg/nornicdb public API, db.go:1951-2378)."""

import time

import pytest

import nornicdb_tpu
from nornicdb_tpu.embed import HashEmbedder


class TestFacade:
    def test_store_recall_roundtrip(self):
        db = nornicdb_tpu.open(embedder=HashEmbedder(dims=64))
        try:
            db.store("TPUs multiply matrices fast", node_id="a")
            db.store("cooking pasta in salted water", node_id="b")
            db.search.embedder = db._embedder
            db.search.build_indexes()
            res = db.recall("multiply matrices")
            assert res and res[0]["id"] == "a"
        finally:
            db.close()

    def test_auto_embed_pipeline(self):
        db = nornicdb_tpu.open(embedder=HashEmbedder(dims=64), auto_embed=True)
        try:
            db.search  # instantiate so on_embedded indexes into it
            db.store("graph databases store nodes", node_id="g")
            db.flush()  # drains the embed queue
            node = db.storage.get_node("g")
            assert node.embedding is not None
            assert "g" in db.search.vectors
            res = db.recall("graph nodes")
            assert res and res[0]["id"] == "g"
        finally:
            db.close()

    def test_remember_tracks_access(self):
        db = nornicdb_tpu.open()
        try:
            db.store("x", node_id="n")
            db.remember("n")
            db.remember("n")
            assert db.temporal.stats("n").count == 2
        finally:
            db.close()

    def test_auto_link_on_store(self):
        db = nornicdb_tpu.open()
        try:
            db.search  # wire search
            db.store("first", node_id="a", embedding=[1.0, 0.0])
            db.search.index_node(db.storage.get_node("a"))
            db.store("second", node_id="b", embedding=[0.99, 0.05], auto_link=True)
            edges = db.storage.get_node_edges("b")
            assert any(e.properties.get("inferred") for e in edges)
        finally:
            db.close()

    def test_cypher_and_storage_share_view(self):
        db = nornicdb_tpu.open()
        try:
            db.store("hello", node_id="h", labels=["Memory"])
            r = db.cypher("MATCH (m:Memory) RETURN m.content")
            assert r.rows == [["hello"]]
        finally:
            db.close()

    def test_durable_facade_with_async(self, tmp_path):
        db = nornicdb_tpu.open(str(tmp_path), async_writes=True)
        try:
            db.store("persist me", node_id="p")
            db.flush()
        finally:
            db.close()
        db2 = nornicdb_tpu.open(str(tmp_path))
        try:
            assert db2.storage.get_node("p").properties["content"] == "persist me"
        finally:
            db2.close()
