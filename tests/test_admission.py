"""Admission control (ISSUE 15): deadline budgets, priority lanes,
SLO-driven shedding.

The acceptance contracts pinned here:

- a rider already past its deadline budget FAILS FAST (one
  degrade-ledger record, one ``shed`` journal event, both trace-linked
  — exactly once each) instead of occupying a device slot;
- a rider whose remaining budget would expire inside the gather window
  triggers an immediate smaller dispatch;
- multi-lane backlogs seal in priority order (interactive > replay >
  background) with an aging promotion;
- honest backpressure: REST 429 carries ``Retry-After`` derived from
  the lane drain rate, gRPC maps to ``RESOURCE_EXHAUSTED`` with
  ``grpc-retry-pushback-ms`` trailing metadata, probe routes are never
  shed;
- the broker rider timeout consults the REQUEST deadline (a generous
  client deadline is not truncated to ``NORNICDB_WIRE_TIMEOUT_S``, a
  tight one is not held open);
- deadline propagation is visible end-to-end in one trace — budget at
  ingress, at the ring crossing, at the dispatch decision — including
  across a 2-worker WirePlane;
- a background rebuild kicked mid-load does not move interactive p99
  past the PR 3 overhead budget;
- ``/admin/scheduler`` serves the actuator state, mirrored in
  ``/admin/telemetry`` and SLO flight dumps.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from nornicdb_tpu import admission as adm
from nornicdb_tpu import obs
from nornicdb_tpu.obs import audit
from nornicdb_tpu.obs import events as obs_events
from nornicdb_tpu.search.microbatch import BatchCoalescer, MicroBatcher


@pytest.fixture(autouse=True)
def _fresh_controller():
    adm.CONTROLLER.reset()
    yield
    adm.CONTROLLER.reset()


def _shed_ledger_records():
    return [r for r in audit.LEDGER.snapshot(limit=500)
            if r.get("to_tier") == "shed"]


def _shed_events():
    return obs_events.event_snapshot(limit=500, kind="shed")


# ---------------------------------------------------------------------------
# deadline context
# ---------------------------------------------------------------------------


class TestDeadlineContext:
    def test_mint_prefers_explicit_budget(self):
        now = 1000.0
        dl, explicit = adm.mint_deadline("grpc", 0.25, now=now)
        assert dl == 1000.25 and explicit is True

    def test_default_derives_from_slo_objective(self):
        # grpc objective threshold 100ms x factor 120 = 12s default
        now = 1000.0
        dl, explicit = adm.mint_deadline("grpc", None, now=now)
        assert explicit is False
        assert 1000.0 < dl <= now + adm.cfg()["deadline_defaults_s"]["*"]
        assert dl == now + adm.cfg()["deadline_defaults_s"]["grpc"]

    def test_header_parse_garbage_degrades_to_default(self):
        d_bad, exp_bad = adm.parse_deadline_header("not-a-number",
                                                   "http")
        d_none, exp_none = adm.parse_deadline_header(None, "http")
        assert abs(d_bad - d_none) < 1.0  # both the default budget
        assert exp_bad is False and exp_none is False
        d, explicit = adm.parse_deadline_header("250", "http")
        assert explicit is True
        assert 0.0 < d - time.time() <= 0.3

    def test_scope_binds_and_restores(self):
        assert adm.deadline() is None
        dl = time.time() + 1.0
        with adm.request_scope("http", dl):
            assert adm.deadline() == dl
            assert adm.remaining() <= 1.0
        assert adm.deadline() is None

    def test_lane_scope_nests(self):
        assert adm.lane() == adm.LANE_INTERACTIVE
        with adm.lane_scope(adm.LANE_BACKGROUND):
            assert adm.lane() == adm.LANE_BACKGROUND
            with adm.lane_scope(adm.LANE_REPLAY):
                assert adm.lane() == adm.LANE_REPLAY
            assert adm.lane() == adm.LANE_BACKGROUND
        assert adm.lane() == adm.LANE_INTERACTIVE

    def test_lane_rank_aging_promotion(self):
        assert adm.lane_rank(adm.LANE_INTERACTIVE) == 0
        assert adm.lane_rank(adm.LANE_REPLAY) == 1
        assert adm.lane_rank(adm.LANE_BACKGROUND) == 2
        # an aged background rider seals like interactive
        aged = adm.cfg()["lane_max_wait_s"] + 0.1
        assert adm.lane_rank(adm.LANE_BACKGROUND, waited_s=aged) == 0

    def test_select_batch_weighted_minimum_share(self):
        """Lanes competing for one batch: interactive dominates by
        priority, but background is GUARANTEED its weighted minimum
        share (NORNICDB_LANE_WEIGHTS) — weighted queuing, not pure
        starvation-prone priority."""
        class It:
            def __init__(self, i, lane):
                self.i, self.lane, self.t_enq = i, lane, time.time()

        now = time.time()
        pending = [It(i, adm.LANE_INTERACTIVE) for i in range(100)] \
            + [It(100 + i, adm.LANE_BACKGROUND) for i in range(20)]
        batch, rest = adm.select_batch(pending, 16, now)
        assert len(batch) == 16
        lanes = [it.lane for it in batch]
        # weights 16:1 over a 16-slot batch: background still lands
        # its floor-1 guaranteed slot; the rest is interactive
        assert lanes.count(adm.LANE_BACKGROUND) >= 1
        assert lanes.count(adm.LANE_INTERACTIVE) >= 14
        assert len(rest) == len(pending) - 16
        # FIFO within each lane
        it_ids = [it.i for it in batch
                  if it.lane == adm.LANE_INTERACTIVE]
        assert it_ids == sorted(it_ids)

    def test_request_scope_binds_resolved_lane(self):
        """The ingress scope counts the request on the lane the shed
        verdict used — a write flood registers as background
        pressure, not interactive."""
        dl = time.time() + 1.0
        with adm.request_scope("grpc", dl,
                               lane_name=adm.LANE_BACKGROUND,
                               explicit=True):
            assert adm.lane() == adm.LANE_BACKGROUND
            assert adm.deadline_explicit() is True
            assert adm.CONTROLLER.inflight(adm.LANE_BACKGROUND) == 1
            assert adm.CONTROLLER.inflight(adm.LANE_INTERACTIVE) == 0
        assert adm.CONTROLLER.inflight(adm.LANE_BACKGROUND) == 0


# ---------------------------------------------------------------------------
# deadline-aware MicroBatcher dispatch
# ---------------------------------------------------------------------------


def _echo_batcher(**kw):
    calls = []

    def search_batch(queries, k):
        calls.append(len(queries))
        return [[("id", 1.0)]] * len(queries)

    mb = MicroBatcher(search_batch, surface="t-adm", **kw)
    return mb, calls


class TestMicroBatcherDeadline:
    def test_expired_rider_fails_fast_exactly_once(self):
        mb, calls = _echo_batcher()
        led0 = len(_shed_ledger_records())
        ev0 = len(_shed_events())
        with obs.trace("wire", method="t-adm-dead") as root:
            with adm.deadline_scope(time.time() - 0.01):
                with pytest.raises(adm.DeadlineExceeded):
                    mb.search([0.1, 0.2], 3)
        # never dispatched, never queued a device slot
        assert calls == []
        assert mb.queue_depth() == 0
        led = _shed_ledger_records()[: len(_shed_ledger_records()) - led0]
        led = _shed_ledger_records()
        assert len(led) - led0 == 1
        rec = led[0]
        assert rec["reason"] == "deadline"
        assert rec["trace_id"] == root.trace_id
        evs = _shed_events()
        assert len(evs) - ev0 == 1
        assert evs[-1]["trace_id"] == root.trace_id
        assert evs[-1]["reason"] == "deadline"

    def test_expired_in_queue_fails_fast_without_dispatch(self):
        mb, calls = _echo_batcher()
        release = threading.Event()

        def slow_batch(queries, k):
            release.wait(timeout=5.0)
            calls.append(len(queries))
            return [[("id", 1.0)]] * len(queries)

        mb._search_batch = slow_batch
        errs = []

        def leader():
            try:
                mb.search([1.0, 0.0], 1)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        t_lead = threading.Thread(target=leader)
        t_lead.start()
        for _ in range(100):
            if mb._busy:
                break
            time.sleep(0.005)
        assert mb._busy

        def rider():
            with adm.deadline_scope(time.time() + 0.05):
                try:
                    mb.search([0.0, 1.0], 1)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

        t_ride = threading.Thread(target=rider)
        t_ride.start()
        t_ride.join(timeout=3.0)
        assert not t_ride.is_alive(), "rider stuck past its deadline"
        release.set()
        t_lead.join(timeout=5.0)
        # the rider failed fast with DeadlineExceeded; the leader served
        assert any(isinstance(e, adm.DeadlineExceeded) for e in errs)
        assert calls == [1]  # only the leader's row dispatched

    def test_tight_budget_skips_gather_window(self):
        """A rider whose remaining budget would expire inside the
        gather window dispatches immediately (smaller batch NOW)."""
        mb, calls = _echo_batcher(gather_window_s=0.25)
        mb._last_batch = 4  # pretend the last batch was concurrent
        with adm.deadline_scope(time.time() + 0.1):
            t0 = time.time()
            mb.search([0.5, 0.5], 1)
            elapsed = time.time() - t0
        # without the deadline the leader would wait the full 250ms
        # window; with it the dispatch is immediate
        assert elapsed < 0.2, elapsed
        assert calls == [1]
        fam = obs.REGISTRY.get("nornicdb_deadline_early_dispatch_total")
        child = fam.children().get(("t-adm",))
        assert child is not None and child.value >= 1

    def test_lane_priority_orders_multi_lane_backlog(self):
        order = []
        release = threading.Event()
        first = threading.Event()

        def batch(queries, k):
            if not first.is_set():
                first.set()
                release.wait(timeout=5.0)
            else:
                order.append(int(queries[0][0]))
            return [[("id", 1.0)]] * len(queries)

        mb = MicroBatcher(batch, max_batch=1, surface="t-adm-lane")
        done = []

        def go(row, lane):
            def run():
                with adm.lane_scope(lane):
                    mb.search([float(row), 0.0], 1)
                done.append(row)

            t = threading.Thread(target=run)
            t.start()
            return t

        threads = [go(0, adm.LANE_INTERACTIVE)]  # becomes the leader
        first.wait(timeout=5.0)
        # backlog while the leader is busy: background first in ARRIVAL
        # order, interactive second — priority must invert arrival
        threads.append(go(1, adm.LANE_BACKGROUND))
        time.sleep(0.05)
        threads.append(go(2, adm.LANE_INTERACTIVE))
        time.sleep(0.05)
        release.set()
        for t in threads:
            t.join(timeout=5.0)
        assert order[0] == 2, order  # interactive sealed first
        assert 1 in order

    def test_coalescer_expired_item_fails_fast(self):
        co = BatchCoalescer(lambda items: items, surface="t-adm-co")
        with adm.deadline_scope(time.time() - 0.01):
            with pytest.raises(adm.DeadlineExceeded):
                co.submit("x")
        assert co.queue_depth() == 0
        assert co.batches == 0


# ---------------------------------------------------------------------------
# honest-backpressure conformance: REST 429 + gRPC RESOURCE_EXHAUSTED
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def shed_serving():
    import grpc

    import nornicdb_tpu
    from nornicdb_tpu.api.grpc_server import GrpcServer
    from nornicdb_tpu.api.http_server import HttpServer
    from nornicdb_tpu.api.proto import qdrant_pb2 as q

    db = nornicdb_tpu.open(auto_embed=False)
    emb = db._embedder
    for i in range(8):
        db.store(f"shed doc {i}", node_id=f"sh{i}",
                 embedding=emb.embed(f"shed doc {i}"))
    grpc_srv = GrpcServer(db, port=0).start()
    http = HttpServer(db, port=0).start()
    ch = grpc.insecure_channel(grpc_srv.address)

    def call(method, request, resp_cls, **kw):
        return ch.unary_unary(
            method,
            request_serializer=lambda r: r.SerializeToString(),
            response_deserializer=resp_cls.FromString)(request, **kw)

    req = q.CreateCollection(collection_name="shed")
    req.vectors_config.params.size = 8
    req.vectors_config.params.distance = q.Cosine
    call("/qdrant.Collections/Create", req, q.CollectionOperationResponse)
    up = q.UpsertPoints(collection_name="shed")
    for i in range(8):
        p = up.points.add()
        p.id.num = i
        p.vectors.vector.data.extend([float((i >> j) & 1)
                                      for j in range(8)])
    call("/qdrant.Points/Upsert", up, q.PointsOperationResponse)
    yield {"db": db, "http": http, "call": call, "q": q,
           "grpc": grpc_srv}
    ch.close()
    grpc_srv.stop()
    http.stop()
    db.close()


def _force_posture(monkeypatch, posture):
    monkeypatch.setattr(adm.CONTROLLER, "refresh",
                        lambda now=None, force=False: posture)
    monkeypatch.setattr(adm.CONTROLLER, "posture", posture)


class TestHonestBackpressure:
    def test_rest_429_carries_retry_after(self, shed_serving,
                                          monkeypatch):
        _force_posture(monkeypatch, "shed_hard")
        led0 = len(_shed_ledger_records())
        ev0 = len(_shed_events())
        body = json.dumps({"query": "shed doc", "limit": 3}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{shed_serving['http'].port}"
            f"/nornicdb/search", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        e = ei.value
        assert e.code == 429
        ra = e.headers.get("Retry-After")
        assert ra is not None and int(ra) >= 1
        payload = json.loads(e.read())
        assert "ResourceExhausted" in payload["errors"][0]["code"]
        # exactly ONE ledger record and ONE journal event, trace-linked
        led = _shed_ledger_records()
        assert len(led) - led0 == 1
        assert led[0]["reason"] == "shed"
        assert led[0].get("trace_id")
        evs = _shed_events()
        assert len(evs) - ev0 == 1
        assert evs[-1]["trace_id"] == led[0]["trace_id"]

    def test_http_lane_classification(self):
        from nornicdb_tpu.api.http_server import _shed_lane_for

        # qdrant point READS stay interactive (gRPC parity)
        assert _shed_lane_for(
            "POST", "/collections/c/points/search") \
            == adm.LANE_INTERACTIVE
        assert _shed_lane_for(
            "POST", "/collections/c/points/scroll") \
            == adm.LANE_INTERACTIVE
        assert _shed_lane_for(
            "POST", "/collections/c/points/count") \
            == adm.LANE_INTERACTIVE
        # point WRITES ride background
        assert _shed_lane_for("PUT", "/collections/c/points") \
            == adm.LANE_BACKGROUND
        assert _shed_lane_for(
            "POST", "/collections/c/points/delete") \
            == adm.LANE_BACKGROUND
        # probes exempt
        assert _shed_lane_for("GET", "/readyz") is None
        assert _shed_lane_for("GET", "/admin/scheduler") is None

    def test_cached_hit_served_under_shed(self, shed_serving,
                                          monkeypatch):
        """A byte-fresh wire-cache hit is pure goodput: it is served
        even under shed_hard — only MISSES pass the controller."""
        body = json.dumps({"query": "shed doc cached-hit",
                           "limit": 2}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{shed_serving['http'].port}"
            f"/nornicdb/search", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200  # populate the wire cache
        _force_posture(monkeypatch, "shed_hard")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200  # hit: never shed
        # a fresh body (miss) under the same posture sheds
        miss = urllib.request.Request(
            f"http://127.0.0.1:{shed_serving['http'].port}"
            f"/nornicdb/search",
            data=json.dumps({"query": "shed doc miss-path",
                             "limit": 2}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(miss, timeout=5)
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After")

    def test_probe_routes_never_shed(self, shed_serving, monkeypatch):
        _force_posture(monkeypatch, "shed_hard")
        port = shed_serving["http"].port
        for path in ("/health", "/readyz", "/metrics"):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}",
                        timeout=5) as resp:
                    assert resp.status in (200, 503)
            except urllib.error.HTTPError as e:
                assert e.code == 503  # readyz degraded is fine; not 429

    def test_grpc_resource_exhausted_with_pushback(self, shed_serving,
                                                   monkeypatch):
        import grpc

        _force_posture(monkeypatch, "shed_hard")
        q = shed_serving["q"]
        sr = q.SearchPoints(collection_name="shed",
                            vector=[0.9] * 8, limit=3)
        with pytest.raises(grpc.RpcError) as ei:
            shed_serving["call"]("/qdrant.Points/Search", sr,
                                 q.SearchResponse)
        e = ei.value
        assert e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        md = dict(e.trailing_metadata() or ())
        assert int(md["grpc-retry-pushback-ms"]) >= 1000

    def test_degrade_posture_sheds_background_not_interactive(
            self, shed_serving, monkeypatch):
        import grpc

        _force_posture(monkeypatch, "degrade")
        q = shed_serving["q"]
        # interactive read passes
        sr = q.SearchPoints(collection_name="shed",
                            vector=[0.7] * 8, limit=3)
        resp = shed_serving["call"]("/qdrant.Points/Search", sr,
                                    q.SearchResponse)
        assert len(resp.result) >= 1
        # background write (upsert convoy lane) sheds
        up = q.UpsertPoints(collection_name="shed")
        p = up.points.add()
        p.id.num = 99
        p.vectors.vector.data.extend([0.5] * 8)
        with pytest.raises(grpc.RpcError) as ei:
            shed_serving["call"]("/qdrant.Points/Upsert", up,
                                 q.PointsOperationResponse)
        assert ei.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED

    def test_admission_tier_gate_forces_brute(self, monkeypatch):
        _force_posture(monkeypatch, "degrade")
        assert not audit.admission_allows("vector_walk_f32")
        assert not audit.admission_allows("vector_pq")
        assert not audit.admission_allows("graph_chain_device")
        assert audit.admission_allows("vector_brute_f32")
        assert audit.admission_allows("hybrid_brute_f32")
        assert audit.admission_allows("host")
        assert audit.admission_allows("cached")

    def test_cagra_degrades_to_brute_under_admission_hold(
            self, monkeypatch):
        from nornicdb_tpu.search.cagra import CagraIndex

        rng = np.random.default_rng(4)
        vecs = rng.standard_normal((600, 16)).astype(np.float32)
        idx = CagraIndex(min_n=256)
        idx.add_batch([(f"v{i}", vecs[i]) for i in range(len(vecs))])
        assert idx.build()
        _force_posture(monkeypatch, "degrade")
        led0 = audit.LEDGER.recorded
        res = idx.search_batch(vecs[:2], 5)
        assert len(res) == 2 and res[0][0][0] == "v0"
        recs = [r for r in audit.LEDGER.snapshot(limit=20)
                if r["reason"] == "admission"]
        assert audit.LEDGER.recorded > led0
        assert recs and recs[0]["from_tier"].startswith("vector_walk")


# ---------------------------------------------------------------------------
# broker: the rider timeout consults the request deadline
# ---------------------------------------------------------------------------


class TestBrokerDeadline:
    def _broker(self, dispatch, **kw):
        from nornicdb_tpu.search.broker import (
            BrokerClient,
            DispatchBroker,
        )

        broker = DispatchBroker(dispatch, targets=kw.pop("targets", {}),
                                n_workers=1, slots=8,
                                gather_window_s=0.0).start()
        spec = broker.client_spec(0, cross_process=False)
        spec.update(kw)
        return broker, BrokerClient(spec)

    def test_tight_deadline_not_held_open(self):
        from nornicdb_tpu.search.broker import BrokerTimeout

        def slow(key, queries, k):
            time.sleep(1.0)
            return [[("id", 1.0)]] * len(queries)

        broker, client = self._broker(slow)
        try:
            t0 = time.time()
            with adm.deadline_scope(time.time() + 0.3):
                with pytest.raises(BrokerTimeout):
                    client.vec_search("k", np.ones(4, np.float32), 1)
            elapsed = time.time() - t0
            # the flat NORNICDB_WIRE_TIMEOUT_S default is 15s; the
            # rider honored its 300ms budget instead
            assert elapsed < 1.0, elapsed
        finally:
            time.sleep(1.1)  # let the dispatch finish before teardown
            client.close()
            broker.stop()

    def test_generous_deadline_not_truncated(self):
        def slow(key, queries, k):
            time.sleep(0.5)
            return [[("id", 1.0)]] * len(queries)

        broker, client = self._broker(slow, timeout_s=0.2)
        try:
            # flat rider timeout 200ms would fail this op; the 5s
            # request budget overrides it
            with adm.deadline_scope(time.time() + 5.0):
                doc = client.vec_search("k", np.ones(4, np.float32), 1)
            assert doc["hits"]
        finally:
            client.close()
            broker.stop()

    def test_default_budget_clamps_to_flat_timeout(self):
        """A server-minted DEFAULT budget (30s http) must not extend
        the flat rider timeout — dead-plane detection stays at
        NORNICDB_WIRE_TIMEOUT_S; only explicit client budgets may
        extend it."""
        from nornicdb_tpu.search.broker import BrokerTimeout

        def slow(key, queries, k):
            time.sleep(0.8)
            return [[("id", 1.0)]] * len(queries)

        broker, client = self._broker(slow, timeout_s=0.2)
        try:
            with adm.request_scope("http", time.time() + 30.0,
                                   explicit=False):
                t0 = time.time()
                with pytest.raises(BrokerTimeout):
                    client.vec_search("k", np.ones(4, np.float32), 1)
                assert time.time() - t0 < 0.6  # flat 0.2s, not 30s
        finally:
            time.sleep(0.9)  # let the dispatch finish before teardown
            client.close()
            broker.stop()

    def test_expired_budget_never_posts(self):
        calls = []

        def dispatch(key, queries, k):
            calls.append(1)
            return [[("id", 1.0)]] * len(queries)

        broker, client = self._broker(dispatch)
        try:
            with adm.deadline_scope(time.time() - 0.01):
                with pytest.raises(adm.DeadlineExceeded):
                    client.vec_search("k", np.ones(4, np.float32), 1)
            assert calls == []
        finally:
            client.close()
            broker.stop()

    def test_plane_sheds_expired_rider_at_claim(self):
        """A rider that expires between post and claim is answered
        with an explicit DeadlineExceeded by the plane — the worker
        maps it; it never occupies a device dispatch."""
        from nornicdb_tpu.search.broker import BrokerRemoteError

        calls = []
        gate = threading.Event()

        def dispatch(key, queries, k):
            calls.append(len(queries))
            gate.wait(timeout=5.0)
            return [[("id", 1.0)]] * len(queries)

        broker, client = self._broker(dispatch)
        try:
            # rider A occupies the key's busy gate
            errs = []

            def first():
                try:
                    client.vec_search("k", np.ones(4, np.float32), 1)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            t = threading.Thread(target=first)
            t.start()
            for _ in range(200):
                if calls:
                    break
                time.sleep(0.005)
            assert calls
            # rider B posts with a 150ms budget; the busy gate holds it
            # POSTED past expiry, then a timer releases the gate so the
            # next round claims B — the plane must shed it at claim
            # with an explicit DeadlineExceeded, never dispatch it
            releaser = threading.Timer(0.4, gate.set)
            releaser.start()
            with adm.deadline_scope(time.time() + 0.15):
                with pytest.raises(BrokerRemoteError) as ei:
                    client.vec_search("k", np.ones(4, np.float32), 1,
                                      timeout_s=3.0)
            assert ei.value.type_name == "DeadlineExceeded"
            t.join(timeout=5.0)
            assert not errs, errs
            # the expired rider never widened a device dispatch
            assert all(c == 1 for c in calls), calls
        finally:
            gate.set()
            client.close()
            broker.stop()


# ---------------------------------------------------------------------------
# acceptance: deadline visible at ingress, ring crossing, dispatch
# ---------------------------------------------------------------------------


def _span_index(doc, out=None):
    out = {} if out is None else out
    out.setdefault(doc["name"], []).append(doc.get("attrs", {}))
    for c in doc.get("children", ()):
        _span_index(c, out)
    return out


class TestDeadlinePropagation:
    def test_single_process_trace_carries_budget(self, shed_serving):
        q = shed_serving["q"]
        sr = q.SearchPoints(collection_name="shed",
                            vector=[0.3, 0.8] + [0.1] * 6, limit=3)
        shed_serving["call"]("/qdrant.Points/Search", sr,
                             q.SearchResponse, timeout=2.0)
        roots = [t for t in obs.TRACES.snapshot(limit=50)
                 if t["attrs"].get("method") == "/qdrant.Points/Search"
                 and "deadline_ms" in t["attrs"]]
        assert roots, "no traced Search carried a deadline"
        # the client sent a 2s gRPC deadline: the minted budget honors
        # it (not the 12s surface default). Neighbor tests leave
        # default-budget Search roots in the shared ring, so assert on
        # ANY root carrying the client's 2s budget.
        assert any(0 < t["attrs"]["deadline_ms"] <= 2100
                   for t in roots), [
            t["attrs"]["deadline_ms"] for t in roots]

    def test_two_worker_wire_plane_end_to_end(self, tmp_path):
        import grpc

        import nornicdb_tpu
        from nornicdb_tpu.api.proto import qdrant_pb2 as q
        from nornicdb_tpu.api.wire_plane import WirePlane

        db = nornicdb_tpu.open(auto_embed=False)
        plane = None
        try:
            rng = np.random.default_rng(7)
            pvecs = rng.normal(size=(16, 8)).astype(np.float32)
            db.qdrant_compat.create_collection(
                "dl", {"size": 8, "distance": "Cosine"})
            db.qdrant_compat.upsert_points("dl", [
                {"id": i, "vector": [float(x) for x in pvecs[i]],
                 "payload": {"i": i}} for i in range(16)])
            plane = WirePlane(db, workers=2, mode="thread").start()
            ch = grpc.insecure_channel(plane.grpc_address)
            stub = ch.unary_unary(
                "/qdrant.Points/Search",
                request_serializer=lambda r: r.SerializeToString(),
                response_deserializer=q.SearchResponse.FromString)
            resp = stub(q.SearchPoints(
                collection_name="dl",
                vector=[float(x) for x in pvecs[5]], limit=3),
                timeout=3.0)
            assert int(resp.result[0].id.num) == 5
            ch.close()
            roots = [t for t in obs.TRACES.snapshot(limit=50)
                     if t["attrs"].get("method")
                     == "/qdrant.Points/Search"
                     and "deadline_ms" in t["attrs"]]
            assert roots, "no ingress root carried the budget"
            chained = None
            for t in roots:
                idx = _span_index(t)
                if "ring.claim" in idx and "device.dispatch" in idx:
                    chained = idx
                    break
            assert chained is not None, [
                list(_span_index(t)) for t in roots]
            # budget at the ring crossing and at the dispatch decision
            claim = chained["ring.claim"][0]
            disp = chained["device.dispatch"][0]
            assert claim.get("deadline_ms", 0) > 0
            assert disp.get("deadline_ms", 0) > 0
            assert disp["deadline_ms"] <= claim["deadline_ms"] + 1.0
            assert claim.get("lane") == "interactive"
        finally:
            if plane is not None:
                plane.stop()
            db.close()


# ---------------------------------------------------------------------------
# background rebuild cannot convoy interactive traffic
# ---------------------------------------------------------------------------


class TestBackgroundLanes:
    def test_rebuild_mid_load_keeps_interactive_p99(self):
        """Satellite pin: a CAGRA background rebuild kicked mid-load
        does not move interactive p99 past the PR 3 overhead budget
        (2x + 1ms, with the base floored at 2ms — sub-ms baselines on
        a contended CI box are dominated by scheduler jitter, not by
        the convoy this test guards against)."""
        from nornicdb_tpu.search.cagra import CagraIndex

        rng = np.random.default_rng(11)
        vecs = rng.standard_normal((4000, 32)).astype(np.float32)
        idx = CagraIndex(min_n=100_000)  # brute serves; rebuild manual
        idx.add_batch([(f"v{i}", vecs[i]) for i in range(len(vecs))])
        mb = MicroBatcher(idx.search_batch, surface="t-adm-bg")
        qs = vecs[rng.integers(0, len(vecs), 64)]

        def p99(n=200):
            lat = []
            for i in range(n):
                t0 = time.perf_counter()
                mb.search(qs[i % len(qs)], 5)
                lat.append(time.perf_counter() - t0)
            return float(np.percentile(np.asarray(lat), 99))

        mb.search(qs[0], 5)  # warm the compile cache
        base = p99()
        # kick a REAL background build (the background-lane thread)
        idx.min_n = 256
        idx._kick_background_rebuild()
        during = p99()
        with idx._rebuild_flag_lock:
            rebuilding = idx._rebuilding
        budget = 2.0 * max(base, 0.002) + 0.001
        assert during <= budget, (base, during, budget, rebuilding)

    def test_background_writers_ride_the_background_lane(self):
        """The rebuild threads' coalescer rides carry the background
        lane: observed directly via the lane contextvar inside the
        rebuild thread."""
        from nornicdb_tpu.search.cagra import CagraIndex

        seen = {}
        rng = np.random.default_rng(3)
        vecs = rng.standard_normal((400, 8)).astype(np.float32)
        idx = CagraIndex(min_n=256)
        idx.add_batch([(f"v{i}", vecs[i]) for i in range(len(vecs))])
        orig_build = idx.build

        def spy_build():
            seen["lane"] = adm.lane()
            return orig_build()

        idx.build = spy_build
        idx._kick_background_rebuild()
        for _ in range(400):
            with idx._rebuild_flag_lock:
                if not idx._rebuilding:
                    break
            time.sleep(0.01)
        assert seen.get("lane") == adm.LANE_BACKGROUND

    def test_upsert_convoy_rides_background_lane(self):
        import nornicdb_tpu

        db = nornicdb_tpu.open(auto_embed=False)
        try:
            compat = db.qdrant_compat
            compat.create_collection("lanes", {"size": 4,
                                               "distance": "Cosine"})
            seen = {}
            orig = compat._upsert_coalescer.submit

            def spy(value):
                seen["lane"] = adm.lane()
                return orig(value)

            compat._upsert_coalescer.submit = spy
            compat.upsert_points_coalesced(
                "lanes", [{"id": 1, "vector": [0.1] * 4}])
            assert seen["lane"] == adm.LANE_BACKGROUND
        finally:
            db.close()


# ---------------------------------------------------------------------------
# /admin/scheduler + telemetry + flight dump
# ---------------------------------------------------------------------------


class TestSchedulerSurface:
    def test_summary_schema(self):
        with adm.request_scope("http", time.time() + 1.0):
            doc = adm.scheduler_summary()
        assert doc["posture"] in ("admit", "degrade", "shed",
                                  "shed_hard")
        assert set(doc["lanes"]) == {"interactive", "replay",
                                     "background"}
        for lane_doc in doc["lanes"].values():
            assert {"inflight", "drain_qps", "wait_ms",
                    "weight"} <= set(lane_doc)
        assert "defaults_ms" in doc["deadline"]
        assert "misses" in doc["deadline"]
        assert "total" in doc["shed"] and "by" in doc["shed"]
        assert doc["limits"]["max_wait_ms"] > 0

    def test_admin_endpoints_serve_scheduler(self, shed_serving):
        port = shed_serving["http"].port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/admin/scheduler",
                timeout=5) as resp:
            doc = json.loads(resp.read())
        assert doc["posture"] in ("admit", "degrade", "shed",
                                  "shed_hard")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/admin/telemetry",
                timeout=5) as resp:
            tel = json.loads(resp.read())
        assert tel["scheduler"]["posture"] == doc["posture"]
        assert set(tel["scheduler"]["lanes"]) == set(doc["lanes"])

    def test_flight_dump_carries_scheduler_block(self, tmp_path):
        from nornicdb_tpu.obs.slo import SloEngine

        eng = SloEngine(dump_dir=str(tmp_path / "fl"),
                        dump_interval_s=300.0)
        path = eng.dump(reason="manual")
        lines = [json.loads(ln) for ln in open(path, encoding="utf-8")]
        sched = [ln for ln in lines if ln["kind"] == "scheduler"]
        assert len(sched) == 1
        assert sched[0]["summary"]["posture"] in (
            "admit", "degrade", "shed", "shed_hard")

    def test_shedding_observed_wait_control_loop(self):
        """Unit: sustained measured waits past the bound flip the
        posture to shed and interactive arrivals get ShedError; the
        wait decays and the posture heals."""
        adm.CONTROLLER.reset()
        now = time.time()
        for _ in range(50):
            adm.CONTROLLER.note_wait(adm.LANE_INTERACTIVE, 0.5, now=now)
        posture = adm.CONTROLLER.refresh(now=now, force=True)
        assert posture in ("shed", "shed_hard")
        with pytest.raises(adm.ShedError) as ei:
            adm.CONTROLLER.check("t-surface", adm.LANE_INTERACTIVE,
                                 now=now)
        assert ei.value.retry_after_s >= 1.0
        # posture transition journaled
        evs = obs_events.event_snapshot(limit=50, kind="posture")
        assert evs and evs[-1]["reason"] in ("shed", "shed_hard")
        # ...and heals once the wait has decayed (halves per second)
        later = now + 30.0
        healed = adm.CONTROLLER.refresh(now=later, force=True)
        assert healed == "admit"
        adm.CONTROLLER.check("t-surface", adm.LANE_INTERACTIVE,
                             now=later)
