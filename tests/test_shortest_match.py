"""MATCH-position shortestPath with unbound endpoints (VERDICT r3
follow-up; reference: shortest_path.go served through the MATCH
planner — the LDBC/neo4j-docs form ``MATCH p = shortestPath(...)``)."""

import pytest

import nornicdb_tpu


@pytest.fixture(scope="module")
def db():
    d = nornicdb_tpu.open(auto_embed=False)
    d.cypher("CREATE (a:P {n:'a'}), (b:P {n:'b'}), (c:P {n:'c'}), "
             "(d:Q {n:'d'})")
    d.cypher("MATCH (a:P {n:'a'}), (b:P {n:'b'}) CREATE (a)-[:K]->(b)")
    d.cypher("MATCH (b:P {n:'b'}), (c:P {n:'c'}) CREATE (b)-[:K]->(c)")
    d.cypher("MATCH (a:P {n:'a'}), (c:P {n:'c'}) CREATE (a)-[:L]->(c)")
    d.cypher("MATCH (c:P {n:'c'}), (d:Q {n:'d'}) CREATE (c)-[:K]->(d)")
    yield d
    d.close()


class TestMatchShortestPath:
    def test_typed_path(self, db):
        r = db.cypher("MATCH p = shortestPath("
                      "(a:P {n:'a'})-[:K*]->(c:P {n:'c'})) "
                      "RETURN length(p)")
        assert r.rows == [[2]]

    def test_untyped_takes_shortcut(self, db):
        r = db.cypher("MATCH p = shortestPath("
                      "(a:P {n:'a'})-[*]->(c:P {n:'c'})) RETURN length(p)")
        assert r.rows == [[1]]

    def test_unbound_source_scans_candidates(self, db):
        r = db.cypher("MATCH p = shortestPath((x:P)-[:K*]->(d:Q)) "
                      "RETURN x.n, length(p) ORDER BY x.n")
        assert r.rows == [["a", 3], ["b", 2], ["c", 1]]

    def test_all_shortest_paths(self, db):
        r = db.cypher("MATCH p = allShortestPaths("
                      "(a:P {n:'a'})-[*]->(c:P {n:'c'})) RETURN length(p)")
        assert r.rows == [[1]]

    def test_no_route_yields_no_rows(self, db):
        r = db.cypher("MATCH p = shortestPath("
                      "(d:Q)-[:K*]->(a:P {n:'a'})) RETURN p")
        assert r.rows == []

    def test_path_nodes_exposed(self, db):
        r = db.cypher("MATCH p = shortestPath("
                      "(a:P {n:'a'})-[:K*]->(c:P {n:'c'})) "
                      "RETURN [n IN nodes(p) | n.n]")
        assert r.rows == [[["a", "b", "c"]]]

    def test_expression_form_still_works(self, db):
        r = db.cypher("MATCH (a:P {n:'a'}), (c:P {n:'c'}) "
                      "RETURN length(shortestPath((a)-[:K*]->(c)))")
        assert r.rows == [[2]]
