"""Real-weight import path validation (VERDICT r3 task 2).

The importer must round-trip published-architecture checkpoints onto
the flax HFEncoder so that the day real bge-m3-class weights are
reachable it is "drop in weights, done" (reference ships bge-m3 over
llama.cpp, pkg/embed/local_gguf.go:57,100). No network here, so the
proof is numerical: instantiate transformers' torch BERT and XLM-R
(RoBERTa = bge-m3's backbone architecture) with RANDOM weights at a
small shape-real config, export the state dict, import it, and require
the flax forward to match the torch forward to float tolerance.
"""

import json
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from nornicdb_tpu.models.hf_import import (  # noqa: E402
    HFEncoder,
    HFEncoderConfig,
    import_hf_params,
    load_hf_model_dir,
    read_checkpoint_tensors,
)

SMALL = dict(
    vocab_size=512,
    hidden_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    intermediate_size=128,
    max_position_embeddings=96,
    hidden_dropout_prob=0.0,
    attention_probs_dropout_prob=0.0,
)


def _torch_mean_pool(model, ids, mask):
    with torch.no_grad():
        out = model(input_ids=torch.tensor(ids),
                    attention_mask=torch.tensor(mask.astype(np.int64)))
    h = out.last_hidden_state.numpy()
    m = mask[:, :, None].astype(np.float32)
    pooled = (h * m).sum(1) / np.maximum(m.sum(1), 1.0)
    return pooled / np.maximum(
        np.linalg.norm(pooled, axis=1, keepdims=True), 1e-12)


def _flax_forward(cfg, params, ids, mask):
    out = HFEncoder(cfg).apply({"params": params}, ids,
                               mask.astype(bool))
    return np.asarray(out, np.float32)


def _batch(rng, vocab, pad_id, n=3, width=17):
    ids = rng.integers(max(pad_id + 1, 2), vocab, size=(n, width))
    lens = [width, width - 5, width - 11]
    mask = np.zeros((n, width), bool)
    for i, ln in enumerate(lens):
        mask[i, :ln] = True
        ids[i, ln:] = pad_id
    return ids.astype(np.int32), mask


class TestBertImport:
    def test_matches_torch_bert(self):
        hf_cfg = transformers.BertConfig(**SMALL)
        torch.manual_seed(0)
        model = transformers.BertModel(hf_cfg).eval()
        tensors = {k: v.detach().numpy()
                   for k, v in model.state_dict().items()}
        cfg = HFEncoderConfig.from_hf_config(hf_cfg.to_dict())
        params = import_hf_params(tensors, cfg)
        ids, mask = _batch(np.random.default_rng(1), SMALL["vocab_size"],
                           cfg.pad_token_id)
        want = _torch_mean_pool(model, ids, mask)
        got = _flax_forward(cfg, params, ids, mask)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)

    def test_missing_tensor_is_loud(self):
        hf_cfg = transformers.BertConfig(**SMALL)
        model = transformers.BertModel(hf_cfg)
        tensors = {k: v.detach().numpy()
                   for k, v in model.state_dict().items()}
        del tensors["encoder.layer.1.output.dense.weight"]
        cfg = HFEncoderConfig.from_hf_config(hf_cfg.to_dict())
        with pytest.raises(KeyError, match="output.dense"):
            import_hf_params(tensors, cfg)


class TestXlmRobertaImport:
    """XLM-R is bge-m3's backbone (RoBERTa arch: offset position ids,
    single token type)."""

    def test_matches_torch_xlmr(self):
        hf_cfg = transformers.XLMRobertaConfig(
            **SMALL, type_vocab_size=1, pad_token_id=1)
        torch.manual_seed(0)
        model = transformers.XLMRobertaModel(hf_cfg).eval()
        tensors = {k: v.detach().numpy()
                   for k, v in model.state_dict().items()}
        cfg = HFEncoderConfig.from_hf_config(hf_cfg.to_dict())
        assert cfg.arch == "roberta"
        params = import_hf_params(tensors, cfg)
        ids, mask = _batch(np.random.default_rng(2), SMALL["vocab_size"],
                           pad_id=1)
        want = _torch_mean_pool(model, ids, mask)
        got = _flax_forward(cfg, params, ids, mask)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


class TestModelDirLoad:
    def test_load_hf_model_dir_safetensors(self, tmp_path):
        from safetensors.numpy import save_file

        hf_cfg = transformers.BertConfig(**SMALL)
        torch.manual_seed(3)
        model = transformers.BertModel(hf_cfg).eval()
        tensors = {k: v.detach().numpy().copy()
                   for k, v in model.state_dict().items()}
        save_file(tensors, str(tmp_path / "model.safetensors"))
        with open(tmp_path / "config.json", "w") as f:
            json.dump(hf_cfg.to_dict(), f)
        cfg, params = load_hf_model_dir(str(tmp_path))
        ids, mask = _batch(np.random.default_rng(4), SMALL["vocab_size"],
                           cfg.pad_token_id)
        want = _torch_mean_pool(model, ids, mask)
        got = _flax_forward(cfg, params, ids, mask)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)

    def test_read_torch_bin_and_npz(self, tmp_path):
        hf_cfg = transformers.BertConfig(**SMALL)
        model = transformers.BertModel(hf_cfg)
        sd = model.state_dict()
        torch.save(sd, tmp_path / "pytorch_model.bin")
        arrs = {k: v.detach().numpy() for k, v in sd.items()}
        np.savez(tmp_path / "model.npz", **arrs)
        t1 = read_checkpoint_tensors(str(tmp_path / "pytorch_model.bin"))
        t2 = read_checkpoint_tensors(str(tmp_path / "model.npz"))
        assert set(t1) == set(t2) == set(arrs)
        np.testing.assert_array_equal(
            t1["embeddings.word_embeddings.weight"],
            t2["embeddings.word_embeddings.weight"])


class TestDbWiring:
    """NORNICDB_TPU_MODEL_DIR makes the imported model the DB default."""

    def _model_dir(self, tmp_path):
        from safetensors.numpy import save_file

        hf_cfg = transformers.BertConfig(**SMALL)
        torch.manual_seed(9)
        model = transformers.BertModel(hf_cfg).eval()
        save_file({k: v.detach().numpy().copy()
                   for k, v in model.state_dict().items()},
                  str(tmp_path / "model.safetensors"))
        with open(tmp_path / "config.json", "w") as f:
            json.dump(hf_cfg.to_dict(), f)
        # minimal WordPiece vocab so AutoTokenizer resolves locally
        vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
                 "graph", "vector", "search", "node", "edge",
                 "a", "b", "the", "and"]
        with open(tmp_path / "vocab.txt", "w") as f:
            f.write("\n".join(vocab))
        return str(tmp_path)

    def test_embedder_loads_and_embeds(self, tmp_path):
        from nornicdb_tpu.models.hf_import import HFEncoderEmbedder

        d = self._model_dir(tmp_path)
        emb = HFEncoderEmbedder(d)
        vecs = emb.embed_batch(["graph search", "vector node edge"])
        assert len(vecs) == 2 and len(vecs[0]) == SMALL["hidden_size"]
        assert abs(sum(v * v for v in vecs[0]) - 1.0) < 1e-3

    def test_db_default_uses_model_dir(self, tmp_path, monkeypatch):
        import nornicdb_tpu
        from nornicdb_tpu.models.hf_import import HFEncoderEmbedder

        monkeypatch.setenv("NORNICDB_TPU_MODEL_DIR",
                           self._model_dir(tmp_path))
        monkeypatch.delenv("NORNICDB_TPU_EMBEDDER", raising=False)
        db = nornicdb_tpu.open(auto_embed=False)
        try:
            assert isinstance(db._embedder.inner, HFEncoderEmbedder)
            assert db._embedder.dims == SMALL["hidden_size"]
        finally:
            db.close()

    def test_hash_force_beats_model_dir(self, tmp_path, monkeypatch):
        import nornicdb_tpu
        from nornicdb_tpu.embed.embedder import HashEmbedder

        monkeypatch.setenv("NORNICDB_TPU_MODEL_DIR",
                           self._model_dir(tmp_path))
        monkeypatch.setenv("NORNICDB_TPU_EMBEDDER", "hash")
        db = nornicdb_tpu.open(auto_embed=False)
        try:
            assert isinstance(db._embedder.inner, HashEmbedder)
        finally:
            db.close()

    def test_hash_force_beats_recorded_sidecar(self, tmp_path,
                                               monkeypatch):
        """The escape hatch exists for when the jax backend cannot
        initialize — a recorded sidecar must not route around it."""
        import nornicdb_tpu
        from nornicdb_tpu.embed.embedder import HashEmbedder

        data = str(tmp_path / "store")
        monkeypatch.delenv("NORNICDB_TPU_EMBEDDER", raising=False)
        monkeypatch.delenv("NORNICDB_TPU_MODEL_DIR", raising=False)
        db = nornicdb_tpu.open(data_dir=data, auto_embed=False)
        db.close()
        monkeypatch.setenv("NORNICDB_TPU_EMBEDDER", "hash")
        db = nornicdb_tpu.open(data_dir=data, auto_embed=False)
        try:
            assert isinstance(db._embedder.inner, HashEmbedder)
        finally:
            db.close()
