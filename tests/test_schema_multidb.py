"""Schema constraints, receipts, and the multi-database manager.

Reference: pkg/storage constraint_validation.go / receipt.go,
pkg/multidb/manager.go.
"""

import pytest

from nornicdb_tpu.multidb import (
    DatabaseError,
    DatabaseLimitExceeded,
    DatabaseLimits,
    DatabaseManager,
)
from nornicdb_tpu.storage import (
    ConstrainedEngine,
    Constraint,
    ConstraintViolation,
    MemoryEngine,
    ReceiptLedger,
    SchemaManager,
)
from nornicdb_tpu.storage.types import Edge, Node


def mknode(nid, labels=None, **props):
    return Node(id=nid, labels=labels or ["Person"], properties=props)


class TestConstraints:
    def setup_method(self):
        self.sm = SchemaManager()
        self.eng = ConstrainedEngine(MemoryEngine(), self.sm)

    def test_unique(self):
        self.sm.add(Constraint(name="u", kind="unique", label="Person", property="email"))
        self.eng.create_node(mknode("a", email="x@y.z"))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("b", email="x@y.z"))
        self.eng.create_node(mknode("c", email="other@y.z"))
        # updating a node to keep its own value is fine
        n = self.eng.get_node("a")
        n.properties["name"] = "Ada"
        self.eng.update_node(n)
        # updating to collide is not
        n = self.eng.get_node("c")
        n.properties["email"] = "x@y.z"
        with pytest.raises(ConstraintViolation):
            self.eng.update_node(n)

    def test_exists_and_type(self):
        self.sm.add(Constraint(name="e", kind="exists", label="Person", property="name"))
        self.sm.add(Constraint(name="t", kind="type", label="Person",
                               property="age", property_type="int"))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("a"))
        self.eng.create_node(mknode("a", name="Ada", age=36))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("b", name="Bob", age="old"))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("c", name="Eve", age=True))  # bool != int

    def test_rel_endpoints(self):
        self.sm.add(Constraint(name="r", kind="rel_endpoints", rel_type="WORKS_AT",
                               start_label="Person", end_label="Company"))
        self.eng.create_node(mknode("p", labels=["Person"]))
        self.eng.create_node(mknode("c", labels=["Company"]))
        self.eng.create_node(mknode("x", labels=["Robot"]))
        self.eng.create_edge(Edge(id="ok", type="WORKS_AT", start_node="p", end_node="c"))
        with pytest.raises(ConstraintViolation):
            self.eng.create_edge(Edge(id="bad", type="WORKS_AT", start_node="x", end_node="c"))
        # other types unconstrained
        self.eng.create_edge(Edge(id="any", type="KNOWS", start_node="x", end_node="p"))

    def test_temporal_interval(self):
        self.sm.add(Constraint(name="iv", kind="temporal", label="Event",
                               property="start", property2="end"))
        self.eng.create_node(mknode("ok", labels=["Event"], start=1, end=5))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("bad", labels=["Event"], start=9, end=5))

    def test_validate_existing(self):
        self.eng.create_node(mknode("a", email="dup"))
        self.eng.create_node(mknode("b", email="dup"))
        self.sm.add(Constraint(name="u", kind="unique", label="Person", property="email"))
        problems = self.eng.validate_existing()
        assert len(problems) == 2  # each node sees the other as duplicate

    def test_global_constraint_applies_to_labelless_nodes(self):
        self.sm.add(Constraint(name="g", kind="unique", label="", property="email"))
        self.eng.create_node(Node(id="a", labels=[], properties={"email": "x"}))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(Node(id="b", labels=[], properties={"email": "x"}))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("c", email="x"))  # labeled too

    def test_persistence(self, tmp_path):
        path = str(tmp_path / "schema.json")
        sm = SchemaManager(path)
        sm.add(Constraint(name="u", kind="unique", label="L", property="p"))
        sm2 = SchemaManager(path)
        assert [c.name for c in sm2.list()] == ["u"]
        sm2.drop("u")
        assert SchemaManager(path).list() == []


class TestReceipts:
    def test_chain_and_verify(self):
        ledger = ReceiptLedger()
        r1 = ledger.record("create_node", "a")
        r2 = ledger.record("delete_node", "a")
        assert r2.prev_hash == r1.hash
        ok, bad = ledger.verify()
        assert ok and bad == -1

    def test_tamper_detected(self):
        ledger = ReceiptLedger()
        for i in range(5):
            ledger.record("create_node", f"n{i}")
        ledger.all()  # copies — tamper with internals directly
        ledger._receipts[2].entity_id = "evil"
        ok, bad = ledger.verify()
        assert not ok and bad == 2


class TestDatabaseManager:
    def setup_method(self):
        self.mgr = DatabaseManager(MemoryEngine())

    def test_defaults_present(self):
        names = [d.name for d in self.mgr.list_databases()]
        assert "system" in names and "neo4j" in names

    def test_create_drop(self):
        self.mgr.create_database("tenant1")
        eng = self.mgr.get_storage("tenant1")
        eng.create_node(Node(id="x", labels=["T"]))
        assert self.mgr.counts("tenant1") == {"nodes": 1, "edges": 0}
        # isolation from default DB
        assert self.mgr.get_storage("neo4j").count_nodes() == 0
        assert self.mgr.drop_database("tenant1") is True
        with pytest.raises(KeyError):
            self.mgr.get_storage("tenant1")
        # data swept from the shared store
        self.mgr.create_database("tenant1")
        assert self.mgr.get_storage("tenant1").count_nodes() == 0

    def test_invalid_names_and_duplicates(self):
        with pytest.raises(DatabaseError):
            self.mgr.create_database("9starts-with-digit")
        with pytest.raises(DatabaseError):
            self.mgr.create_database("neo4j")
        assert self.mgr.create_database("neo4j", if_not_exists=True).default

    def test_cannot_drop_system_or_default(self):
        with pytest.raises(DatabaseError):
            self.mgr.drop_database("system")
        with pytest.raises(DatabaseError):
            self.mgr.drop_database("neo4j")

    def test_limits_enforced(self):
        self.mgr.create_database("small", limits=DatabaseLimits(max_nodes=2, max_edges=1))
        eng = self.mgr.get_storage("small")
        eng.create_node(Node(id="1"))
        eng.create_node(Node(id="2"))
        with pytest.raises(DatabaseLimitExceeded):
            eng.create_node(Node(id="3"))
        eng.create_edge(Edge(id="e1", type="R", start_node="1", end_node="2"))
        with pytest.raises(DatabaseLimitExceeded):
            eng.create_edge(Edge(id="e2", type="R", start_node="2", end_node="1"))

    def test_offline_status_blocks_routing(self):
        self.mgr.create_database("t")
        self.mgr.set_status("t", "offline")
        with pytest.raises(DatabaseError):
            self.mgr.get_storage("t")
        self.mgr.set_status("t", "online")
        assert self.mgr.get_storage("t") is not None

    def test_adopts_existing_namespaces_on_restart(self):
        base = MemoryEngine()
        mgr = DatabaseManager(base)
        mgr.create_database("t1")
        mgr.get_storage("t1").create_node(Node(id="n"))
        # simulate restart: new manager over same base
        mgr2 = DatabaseManager(base)
        assert mgr2.exists("t1")
        assert mgr2.get_storage("t1").count_nodes() == 1

    def test_failed_sweep_keeps_tombstone(self):
        mgr = DatabaseManager(MemoryEngine())
        mgr.create_database("t")
        mgr.get_storage("t").create_node(Node(id="n"))
        orig = mgr._base.delete_by_prefix
        mgr._base.delete_by_prefix = lambda p: (_ for _ in ()).throw(IOError("disk"))
        with pytest.raises(IOError):
            mgr.drop_database("t")
        mgr._base.delete_by_prefix = orig
        # tombstone blocks recreation until resolved — no data leak
        with pytest.raises(DatabaseError):
            mgr.create_database("t")

    def test_unique_index_tracks_mutations(self):
        from nornicdb_tpu.storage import ConstrainedEngine as CE

        sm = SchemaManager()
        sm.add(Constraint(name="u", kind="unique", label="Person", property="email"))
        eng = CE(MemoryEngine(), sm)
        eng.create_node(mknode("a", email="x@y.z"))
        # freeing the value by updating lets another node take it
        n = eng.get_node("a")
        n.properties["email"] = "new@y.z"
        eng.update_node(n)
        eng.create_node(mknode("b", email="x@y.z"))
        # deleting frees the value too
        eng.delete_node("b")
        eng.create_node(mknode("c", email="x@y.z"))
        with pytest.raises(ConstraintViolation):
            eng.create_node(mknode("d", email="new@y.z"))

    def test_max_databases(self):
        mgr = DatabaseManager(MemoryEngine(), max_databases=2)
        mgr.create_database("a")  # neo4j counts as user db #1
        with pytest.raises(DatabaseLimitExceeded):
            mgr.create_database("b")


class TestQueryAndRateLimits:
    """Per-database query/rate limits (reference: pkg/multidb/limits.go
    QueryLimits + RateLimits, enforcement.go)."""

    def _manager(self):
        from nornicdb_tpu.multidb import DatabaseLimits, DatabaseManager
        from nornicdb_tpu.storage import MemoryEngine

        mgr = DatabaseManager(MemoryEngine())
        mgr.create_database("tenant", limits=DatabaseLimits(
            max_results=3, max_queries_per_second=5,
            max_writes_per_second=2))
        return mgr

    def test_result_truncation(self):
        from nornicdb_tpu.query.executor import CypherExecutor

        mgr = self._manager()
        ex = CypherExecutor(mgr.get_storage("tenant"))
        for i in range(10):
            ex.execute("CREATE (:T {i: $i})", {"i": i})
        r = ex.execute("MATCH (t:T) RETURN t.i")
        mgr.truncate_result("tenant", r)
        assert len(r.rows) == 3

    def test_query_rate_limit(self):
        from nornicdb_tpu.multidb import DatabaseLimitExceeded

        mgr = self._manager()
        for _ in range(5):
            mgr.enforce_query("tenant")
        import pytest as _pytest

        with _pytest.raises(DatabaseLimitExceeded):
            mgr.enforce_query("tenant")

    def test_write_rate_limit_separate(self):
        from nornicdb_tpu.multidb import DatabaseLimitExceeded

        mgr = self._manager()
        mgr.enforce_query("tenant", is_write=True)
        mgr.enforce_query("tenant", is_write=True)
        import pytest as _pytest

        with _pytest.raises(DatabaseLimitExceeded):
            mgr.enforce_query("tenant", is_write=True)

    def test_unlimited_db_unaffected(self):
        mgr = self._manager()
        for _ in range(100):
            mgr.enforce_query("neo4j")


class TestEvidenceAndQC:
    """Inference evidence buffer + Heimdall QC (reference:
    pkg/inference/evidence.go, heimdall_qc.go)."""

    def test_evidence_threshold_crossing(self):
        from nornicdb_tpu.inference import EvidenceBuffer, EvidenceThreshold

        buf = EvidenceBuffer(default=EvidenceThreshold(
            min_count=3, min_score=1.5, min_sessions=1))
        assert buf.add("a", "b", "REL", 0.6, session="s1") is None
        assert buf.add("a", "b", "REL", 0.6, session="s1") is None
        ev = buf.add("a", "b", "REL", 0.6, session="s1")
        assert ev is not None and ev.count == 3
        assert buf.stats()["materialized"] == 1

    def test_evidence_expiry(self):
        from nornicdb_tpu.inference import EvidenceBuffer, EvidenceThreshold

        buf = EvidenceBuffer(default=EvidenceThreshold(
            min_count=2, min_score=0.5, max_age_s=10.0))
        t = 1_000_000.0
        buf.add("a", "b", "REL", 1.0, at=t)
        # second signal arrives after expiry: the stale entry resets
        assert buf.add("a", "b", "REL", 1.0, at=t + 100) is None
        assert buf.stats()["expired"] == 1

    def test_coaccess_routed_through_evidence(self):
        from nornicdb_tpu.inference import (
            EvidenceBuffer, EvidenceThreshold, InferenceEngine,
        )
        from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine
        from nornicdb_tpu.storage.types import Node

        eng = NamespacedEngine(MemoryEngine(), "test")
        for nid in ("x", "y"):
            eng.create_node(Node(id=nid, labels=["M"], properties={}))
        buf = EvidenceBuffer(default=EvidenceThreshold(
            min_count=2, min_score=1.0))
        inf = InferenceEngine(eng, evidence=buf)

        class _Tracker:
            def co_accessed(self, node_id):
                return [("y", 5)]

        assert inf.on_access(_Tracker(), "x") == []  # first signal buffered
        out = inf.on_access(_Tracker(), "x")  # second crosses threshold
        assert len(out) == 1
        assert out[0].rel_type == "CO_ACCESSED_WITH"

    def test_heimdall_qc_filters_batch(self):
        from nornicdb_tpu.inference import HeimdallQC, Suggestion
        from nornicdb_tpu.storage import MemoryEngine

        qc = HeimdallQC(lambda prompt: "Y\nN\nY", min_confidence_to_skip=0.99)
        sugs = [Suggestion("a", "b", "R", 0.6, "t"),
                Suggestion("a", "c", "R", 0.6, "t"),
                Suggestion("a", "d", "R", 0.6, "t")]
        approved = qc.review_batch(MemoryEngine(), sugs)
        assert [s.to_id for s in approved] == ["b", "d"]
        assert qc.suggestions_in == 3 and qc.suggestions_out == 2

    def test_heimdall_qc_fails_open(self):
        from nornicdb_tpu.inference import HeimdallQC, Suggestion
        from nornicdb_tpu.storage import MemoryEngine

        def broken(prompt):
            raise RuntimeError("model down")

        qc = HeimdallQC(broken)
        sugs = [Suggestion("a", "b", "R", 0.5, "t")]
        assert qc.review_batch(MemoryEngine(), sugs) == sugs
        assert qc.errors == 1

    def test_high_confidence_skips_review(self):
        from nornicdb_tpu.inference import HeimdallQC, Suggestion
        from nornicdb_tpu.storage import MemoryEngine

        calls = []
        qc = HeimdallQC(lambda p: calls.append(p) or "N",
                        min_confidence_to_skip=0.9)
        sugs = [Suggestion("a", "b", "R", 0.95, "t")]
        assert qc.review_batch(MemoryEngine(), sugs) == sugs
        assert calls == []
