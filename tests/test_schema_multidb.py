"""Schema constraints, receipts, and the multi-database manager.

Reference: pkg/storage constraint_validation.go / receipt.go,
pkg/multidb/manager.go.
"""

import pytest

from nornicdb_tpu.multidb import (
    DatabaseError,
    DatabaseLimitExceeded,
    DatabaseLimits,
    DatabaseManager,
)
from nornicdb_tpu.storage import (
    ConstrainedEngine,
    Constraint,
    ConstraintViolation,
    MemoryEngine,
    ReceiptLedger,
    SchemaManager,
)
from nornicdb_tpu.storage.types import Edge, Node


def mknode(nid, labels=None, **props):
    return Node(id=nid, labels=labels or ["Person"], properties=props)


class TestConstraints:
    def setup_method(self):
        self.sm = SchemaManager()
        self.eng = ConstrainedEngine(MemoryEngine(), self.sm)

    def test_unique(self):
        self.sm.add(Constraint(name="u", kind="unique", label="Person", property="email"))
        self.eng.create_node(mknode("a", email="x@y.z"))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("b", email="x@y.z"))
        self.eng.create_node(mknode("c", email="other@y.z"))
        # updating a node to keep its own value is fine
        n = self.eng.get_node("a")
        n.properties["name"] = "Ada"
        self.eng.update_node(n)
        # updating to collide is not
        n = self.eng.get_node("c")
        n.properties["email"] = "x@y.z"
        with pytest.raises(ConstraintViolation):
            self.eng.update_node(n)

    def test_exists_and_type(self):
        self.sm.add(Constraint(name="e", kind="exists", label="Person", property="name"))
        self.sm.add(Constraint(name="t", kind="type", label="Person",
                               property="age", property_type="int"))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("a"))
        self.eng.create_node(mknode("a", name="Ada", age=36))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("b", name="Bob", age="old"))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("c", name="Eve", age=True))  # bool != int

    def test_rel_endpoints(self):
        self.sm.add(Constraint(name="r", kind="rel_endpoints", rel_type="WORKS_AT",
                               start_label="Person", end_label="Company"))
        self.eng.create_node(mknode("p", labels=["Person"]))
        self.eng.create_node(mknode("c", labels=["Company"]))
        self.eng.create_node(mknode("x", labels=["Robot"]))
        self.eng.create_edge(Edge(id="ok", type="WORKS_AT", start_node="p", end_node="c"))
        with pytest.raises(ConstraintViolation):
            self.eng.create_edge(Edge(id="bad", type="WORKS_AT", start_node="x", end_node="c"))
        # other types unconstrained
        self.eng.create_edge(Edge(id="any", type="KNOWS", start_node="x", end_node="p"))

    def test_temporal_interval(self):
        self.sm.add(Constraint(name="iv", kind="temporal", label="Event",
                               property="start", property2="end"))
        self.eng.create_node(mknode("ok", labels=["Event"], start=1, end=5))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("bad", labels=["Event"], start=9, end=5))

    def test_validate_existing(self):
        self.eng.create_node(mknode("a", email="dup"))
        self.eng.create_node(mknode("b", email="dup"))
        self.sm.add(Constraint(name="u", kind="unique", label="Person", property="email"))
        problems = self.eng.validate_existing()
        assert len(problems) == 2  # each node sees the other as duplicate

    def test_global_constraint_applies_to_labelless_nodes(self):
        self.sm.add(Constraint(name="g", kind="unique", label="", property="email"))
        self.eng.create_node(Node(id="a", labels=[], properties={"email": "x"}))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(Node(id="b", labels=[], properties={"email": "x"}))
        with pytest.raises(ConstraintViolation):
            self.eng.create_node(mknode("c", email="x"))  # labeled too

    def test_persistence(self, tmp_path):
        path = str(tmp_path / "schema.json")
        sm = SchemaManager(path)
        sm.add(Constraint(name="u", kind="unique", label="L", property="p"))
        sm2 = SchemaManager(path)
        assert [c.name for c in sm2.list()] == ["u"]
        sm2.drop("u")
        assert SchemaManager(path).list() == []


class TestReceipts:
    def test_chain_and_verify(self):
        ledger = ReceiptLedger()
        r1 = ledger.record("create_node", "a")
        r2 = ledger.record("delete_node", "a")
        assert r2.prev_hash == r1.hash
        ok, bad = ledger.verify()
        assert ok and bad == -1

    def test_tamper_detected(self):
        ledger = ReceiptLedger()
        for i in range(5):
            ledger.record("create_node", f"n{i}")
        ledger.all()  # copies — tamper with internals directly
        ledger._receipts[2].entity_id = "evil"
        ok, bad = ledger.verify()
        assert not ok and bad == 2


class TestDatabaseManager:
    def setup_method(self):
        self.mgr = DatabaseManager(MemoryEngine())

    def test_defaults_present(self):
        names = [d.name for d in self.mgr.list_databases()]
        assert "system" in names and "neo4j" in names

    def test_create_drop(self):
        self.mgr.create_database("tenant1")
        eng = self.mgr.get_storage("tenant1")
        eng.create_node(Node(id="x", labels=["T"]))
        assert self.mgr.counts("tenant1") == {"nodes": 1, "edges": 0}
        # isolation from default DB
        assert self.mgr.get_storage("neo4j").count_nodes() == 0
        assert self.mgr.drop_database("tenant1") is True
        with pytest.raises(KeyError):
            self.mgr.get_storage("tenant1")
        # data swept from the shared store
        self.mgr.create_database("tenant1")
        assert self.mgr.get_storage("tenant1").count_nodes() == 0

    def test_invalid_names_and_duplicates(self):
        with pytest.raises(DatabaseError):
            self.mgr.create_database("9starts-with-digit")
        with pytest.raises(DatabaseError):
            self.mgr.create_database("neo4j")
        assert self.mgr.create_database("neo4j", if_not_exists=True).default

    def test_cannot_drop_system_or_default(self):
        with pytest.raises(DatabaseError):
            self.mgr.drop_database("system")
        with pytest.raises(DatabaseError):
            self.mgr.drop_database("neo4j")

    def test_limits_enforced(self):
        self.mgr.create_database("small", limits=DatabaseLimits(max_nodes=2, max_edges=1))
        eng = self.mgr.get_storage("small")
        eng.create_node(Node(id="1"))
        eng.create_node(Node(id="2"))
        with pytest.raises(DatabaseLimitExceeded):
            eng.create_node(Node(id="3"))
        eng.create_edge(Edge(id="e1", type="R", start_node="1", end_node="2"))
        with pytest.raises(DatabaseLimitExceeded):
            eng.create_edge(Edge(id="e2", type="R", start_node="2", end_node="1"))

    def test_offline_status_blocks_routing(self):
        self.mgr.create_database("t")
        self.mgr.set_status("t", "offline")
        with pytest.raises(DatabaseError):
            self.mgr.get_storage("t")
        self.mgr.set_status("t", "online")
        assert self.mgr.get_storage("t") is not None

    def test_adopts_existing_namespaces_on_restart(self):
        base = MemoryEngine()
        mgr = DatabaseManager(base)
        mgr.create_database("t1")
        mgr.get_storage("t1").create_node(Node(id="n"))
        # simulate restart: new manager over same base
        mgr2 = DatabaseManager(base)
        assert mgr2.exists("t1")
        assert mgr2.get_storage("t1").count_nodes() == 1

    def test_failed_sweep_keeps_tombstone(self):
        mgr = DatabaseManager(MemoryEngine())
        mgr.create_database("t")
        mgr.get_storage("t").create_node(Node(id="n"))
        orig = mgr._base.delete_by_prefix
        mgr._base.delete_by_prefix = lambda p: (_ for _ in ()).throw(IOError("disk"))
        with pytest.raises(IOError):
            mgr.drop_database("t")
        mgr._base.delete_by_prefix = orig
        # tombstone blocks recreation until resolved — no data leak
        with pytest.raises(DatabaseError):
            mgr.create_database("t")

    def test_unique_index_tracks_mutations(self):
        from nornicdb_tpu.storage import ConstrainedEngine as CE

        sm = SchemaManager()
        sm.add(Constraint(name="u", kind="unique", label="Person", property="email"))
        eng = CE(MemoryEngine(), sm)
        eng.create_node(mknode("a", email="x@y.z"))
        # freeing the value by updating lets another node take it
        n = eng.get_node("a")
        n.properties["email"] = "new@y.z"
        eng.update_node(n)
        eng.create_node(mknode("b", email="x@y.z"))
        # deleting frees the value too
        eng.delete_node("b")
        eng.create_node(mknode("c", email="x@y.z"))
        with pytest.raises(ConstraintViolation):
            eng.create_node(mknode("d", email="new@y.z"))

    def test_max_databases(self):
        mgr = DatabaseManager(MemoryEngine(), max_databases=2)
        mgr.create_database("a")  # neo4j counts as user db #1
        with pytest.raises(DatabaseLimitExceeded):
            mgr.create_database("b")
