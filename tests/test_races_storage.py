"""Adversarial interleaving tests for the storage plane (VERDICT r4 #7).

The reference memorializes its concurrency bugs as named regression
tests (pkg/storage/async_engine_count_flush_race_test.go,
async_engine_callback_deadlock_test.go, pkg/cypher/concurrent_count_test.go);
these suites are that corpus for this codebase: real threads, real
interleavings, invariants asserted — not restatements of happy paths.

Covered interleaving classes:
- write-behind flush vs delete/recreate of the same key
- per-key read-your-writes visibility THROUGH a racing flush window
- backpressure storms (max_pending) under many writers
- close() racing a write storm (acked-before-close durability)
- kill -9 (byte-copy snapshot) of a WAL under concurrent writers,
  replayed: prefix-consistent, acked-only, torn tail repaired
- TransactionManager sessions committing/rolling back concurrently
"""

import os
import random
import shutil
import threading
import time

import pytest

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.storage import MemoryEngine, WAL, WALEngine
from nornicdb_tpu.storage.async_engine import AsyncEngine
from nornicdb_tpu.storage.txn import TransactionManager
from nornicdb_tpu.storage.types import Edge, Node


def _node(i, **props):
    return Node(id=f"n{i}", labels=["T"], properties=props or {"v": i})


class TestAsyncFlushDeleteRaces:
    def test_delete_recreate_storm_converges(self):
        """Per-key last-op-wins: N keys, each hammered by its own writer
        with create/delete/recreate cycles while a dedicated thread
        flushes in a tight loop. After the storm + final flush, the
        inner engine must hold exactly the keys whose LAST op was a
        create — a flush applying a stale overlay snapshot would
        resurrect deleted keys or drop recreations."""
        inner = MemoryEngine()
        eng = AsyncEngine(inner, flush_interval_s=0)  # manual flush only
        stop = threading.Event()
        flusher_errors = []

        def flush_loop():
            while not stop.is_set():
                try:
                    eng.flush_pending()
                except Exception as exc:  # pragma: no cover
                    flusher_errors.append(exc)

        n_keys, cycles = 24, 30
        final_alive = {}

        def writer(k):
            rng = random.Random(k)
            alive = False
            for c in range(cycles):
                if not alive:
                    eng.create_node(Node(id=f"k{k}", labels=["T"],
                                         properties={"c": c}))
                    alive = True
                elif rng.random() < 0.5:
                    eng.delete_node(f"k{k}")
                    alive = False
                else:
                    eng.update_node(Node(id=f"k{k}", labels=["T"],
                                         properties={"c": c}))
                if rng.random() < 0.2:
                    time.sleep(0)  # encourage interleavings
            final_alive[k] = alive

        flt = threading.Thread(target=flush_loop)
        flt.start()
        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(n_keys)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        flt.join()
        eng.flush_pending()
        assert not flusher_errors
        for k, alive in final_alive.items():
            assert inner.has_node(f"k{k}") == alive, (
                f"key k{k}: expected alive={alive}")
        eng.close()

    def test_read_your_writes_through_flush_window(self):
        """A created-and-acked node must NEVER be invisible, even at the
        instant the flusher moves it from overlay to inner (the window
        where a naive impl clears the overlay before the inner write
        lands)."""
        inner = MemoryEngine()
        eng = AsyncEngine(inner, flush_interval_s=0)
        stop = threading.Event()
        invisible = []

        eng.create_node(_node("stable"))

        def flush_loop():
            while not stop.is_set():
                eng.flush_pending()

        def reader():
            while not stop.is_set():
                if not eng.has_node("nstable"):
                    invisible.append("has_node")
                try:
                    eng.get_node("nstable")
                except NotFoundError:
                    invisible.append("get_node")

        def churn():
            # unrelated writes keep the flusher busy with real batches
            i = 0
            while not stop.is_set():
                eng.create_node(_node(f"churn{i}"))
                if i % 3 == 0:
                    eng.delete_node(f"nchurn{i}")
                i += 1

        threads = [threading.Thread(target=f)
                   for f in (flush_loop, reader, reader, churn)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join()
        assert invisible == []
        eng.close()

    def test_deleted_nodes_leave_no_ghost_edges_under_flush(self):
        """Delete a node while its edges sit unflushed in the overlay:
        after convergence no edge may reference the dead node (the
        reference's cascade guarantee, exercised through the write-behind
        layer's flush interleavings)."""
        inner = MemoryEngine()
        eng = AsyncEngine(inner, flush_interval_s=0)
        stop = threading.Event()

        def flush_loop():
            while not stop.is_set():
                eng.flush_pending()

        for i in range(40):
            eng.create_node(_node(f"a{i}"))
            eng.create_node(_node(f"b{i}"))

        flt = threading.Thread(target=flush_loop)
        flt.start()

        def link_and_kill(i):
            eng.create_edge(Edge(id=f"e{i}", type="R",
                                 start_node=f"na{i}", end_node=f"nb{i}",
                                 properties={}))
            time.sleep(0)
            eng.delete_node(f"nb{i}")

        threads = [threading.Thread(target=link_and_kill, args=(i,))
                   for i in range(40)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        flt.join()
        eng.flush_pending()
        eng.flush_pending()  # second pass: edges deferred behind deletes
        for e in inner.all_edges():
            assert inner.has_node(e.start_node), f"ghost edge {e.id}"
            assert inner.has_node(e.end_node), f"ghost edge {e.id}"
        eng.close()

    def test_backpressure_storm_no_deadlock_no_loss(self):
        """max_pending backpressure with 16 writers: every acked create
        must land; nobody deadlocks against the flush path."""
        inner = MemoryEngine()
        eng = AsyncEngine(inner, flush_interval_s=0.002, max_pending=64)
        n_threads, per = 16, 150

        def writer(t):
            for i in range(per):
                eng.create_node(_node(f"w{t}_{i}"))

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        eng.close()  # final flush
        assert inner.count_nodes() == n_threads * per
        assert eng.last_flush_errors == []

    def test_close_racing_write_storm_keeps_acked_writes(self):
        """Writers race close(); every write acked BEFORE close() was
        called must be durable in the inner engine afterwards."""
        inner = MemoryEngine()
        eng = AsyncEngine(inner, flush_interval_s=0.005)
        acked = set()
        acked_lock = threading.Lock()
        stop = threading.Event()

        def writer(t):
            i = 0
            while not stop.is_set():
                nid = f"s{t}_{i}"
                try:
                    eng.create_node(_node(nid))
                except Exception:
                    return  # engine closed mid-call: not acked
                with acked_lock:
                    acked.add(f"n{nid}")
                i += 1

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        with acked_lock:
            must_survive = set(acked)
        eng.close()
        stop.set()
        for t in threads:
            t.join()
        for nid in must_survive:
            assert inner.has_node(nid), f"acked write {nid} lost by close"


class TestWALKillDuringWrites:
    def _copy_dir(self, src, dst):
        os.makedirs(dst, exist_ok=True)
        for name in os.listdir(src):
            shutil.copyfile(os.path.join(src, name),
                            os.path.join(dst, name))

    def test_byte_copy_snapshot_replays_acked_prefix(self, tmp_path):
        """kill -9 simulation: while 8 threads write through a WALEngine,
        take raw byte-copies of the WAL dir (what a crash leaves on
        disk). Replaying every copy must yield only acked nodes, with
        object-level integrity (properties round-trip), never an error."""
        d = str(tmp_path / "wal")
        wal = WAL(d, max_segment_bytes=4096)
        eng = WALEngine(MemoryEngine(), wal)
        acked = set()
        acked_lock = threading.Lock()
        stop = threading.Event()

        def writer(t):
            i = 0
            # bounded: enough to span several segments, small enough to
            # keep the 4 replays below a second each
            while not stop.is_set() and i < 1200:
                nid = f"w{t}_{i}"
                eng.create_node(Node(id=nid, labels=["K"],
                                     properties={"t": t, "i": i}))
                with acked_lock:
                    acked.add(nid)
                i += 1

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        copies = []
        for c in range(4):
            time.sleep(0.05)
            dst = str(tmp_path / f"copy{c}")
            self._copy_dir(d, dst)
            with acked_lock:
                acked_at_copy = set(acked)
            copies.append((dst, acked_at_copy))
        stop.set()
        for t in threads:
            t.join()
        eng.close()

        with acked_lock:
            all_submitted = set(acked)
        for dst, _acked_at_copy in copies:
            rep_wal = WAL(dst)
            seen = {}
            rep_wal.replay(lambda op, data, s=seen: s.__setitem__(
                data.get("node", data).get("id", "?"), data))
            rep_wal.close()
            # 1) nothing fabricated: every replayed id was submitted
            assert set(seen) <= all_submitted
            # 2) payload integrity survived the mid-write copy
            for nid, data in seen.items():
                node = data.get("node", data)
                props = node.get("properties", {})
                t, i = nid[1:].split("_")
                assert props.get("t") == int(t) and props.get("i") == int(i)

    def test_truncated_tail_after_concurrent_writes_repairs(self, tmp_path):
        """Concurrent writers, then a crash that tears the final record:
        replay repairs the tail and keeps every complete record."""
        d = str(tmp_path / "wal")
        wal = WAL(d)
        eng = WALEngine(MemoryEngine(), wal)

        def writer(t):
            for i in range(50):
                eng.create_node(Node(id=f"t{t}_{i}", labels=[],
                                     properties={}))

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wal.flush()
        # tear the newest segment mid-record (no close: crash semantics)
        segs = sorted(
            os.path.join(d, f) for f in os.listdir(d)
            if f.startswith("wal-") and f.endswith(".log")
        )
        victim = segs[-1]
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.truncate(size - 7)
        rep = WAL(d)
        applied = []
        res = rep.replay(lambda op, data: applied.append(data))
        rep.close()
        assert res.torn_tail_repaired
        # all but at most the torn final record replay
        assert len(applied) >= 4 * 50 - 1


class TestTransactionManagerConcurrency:
    def test_sessions_commit_and_rollback_isolated(self):
        """32 sessions race begin/write/commit-or-rollback on one shared
        engine: committed writes all land, rolled-back writes never leak,
        and no session observes another's uncommitted overlay."""
        store = MemoryEngine()
        mgr = TransactionManager()
        committed, rolled_back = set(), set()
        lock = threading.Lock()
        leaks = []

        def session(s):
            rng = random.Random(s)
            for round_no in range(10):
                sid = f"sess{s}"
                tx = mgr.begin(sid, store)
                ids = [f"tx{s}_{round_no}_{j}" for j in range(5)]
                for nid in ids:
                    tx.create_node(Node(id=nid, labels=["TX"],
                                        properties={"s": s}))
                # uncommitted overlay must be invisible to the shared store
                if store.has_node(ids[0]):
                    leaks.append(ids[0])
                if rng.random() < 0.5:
                    mgr.commit(sid)
                    with lock:
                        committed.update(ids)
                else:
                    mgr.rollback(sid)
                    with lock:
                        rolled_back.update(ids)

        threads = [threading.Thread(target=session, args=(s,))
                   for s in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert leaks == []
        for nid in committed:
            assert store.has_node(nid)
        for nid in rolled_back:
            assert not store.has_node(nid)
        assert store.count_nodes() == len(committed)

    def test_double_begin_same_session_rejected_under_race(self):
        """Two threads racing begin() on one session id: exactly one may
        hold the open transaction."""
        store = MemoryEngine()
        mgr = TransactionManager()
        wins, losses = [], []
        barrier = threading.Barrier(2)

        def contender(i):
            barrier.wait()
            try:
                mgr.begin("shared", store)
                wins.append(i)
            except RuntimeError:
                losses.append(i)

        threads = [threading.Thread(target=contender, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1 and len(losses) == 1
