"""Integration tests proving the cross-cutting components are live on
production code paths (VERDICT r1 weak #3: cache, vectorspace,
encryption, linkpredict must be *used*, not just exist).

Reference behaviors: read-cache probe (pkg/cypher/executor.go:634),
at-rest encryption (pkg/nornicdb/db.go:776-805), vector space registry
(pkg/vectorspace/registry.go), GDS link prediction procedures
(pkg/cypher/linkprediction.go).
"""

import glob
import os

import pytest

import nornicdb_tpu
from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


# -- encryption at rest ---------------------------------------------------


class TestEncryptionAtRest:
    def _roundtrip(self, tmp_path, engine):
        data_dir = str(tmp_path / f"enc-{engine}")
        db = nornicdb_tpu.open(
            data_dir, engine=engine, passphrase="hunter2", auto_embed=False
        )
        db.cypher(
            "CREATE (:Secret {payload: 'TOPSECRET-ZEBRA', id: 1})"
        )
        db.close()
        # ciphertext check: the plaintext must not appear anywhere on disk
        blob = b""
        for path in glob.glob(os.path.join(data_dir, "**", "*"), recursive=True):
            if os.path.isfile(path):
                with open(path, "rb") as f:
                    blob += f.read()
        assert b"TOPSECRET-ZEBRA" not in blob, (
            f"plaintext leaked to disk ({engine})"
        )
        # reopen with the passphrase: data intact
        db2 = nornicdb_tpu.open(
            data_dir, engine=engine, passphrase="hunter2", auto_embed=False
        )
        r = db2.cypher("MATCH (s:Secret) RETURN s.payload")
        assert r.rows == [["TOPSECRET-ZEBRA"]]
        db2.close()
        return data_dir

    def test_python_engine_encrypts(self, tmp_path):
        self._roundtrip(tmp_path, "python")

    def test_native_engine_encrypts(self, tmp_path):
        from nornicdb_tpu.storage.disk import native_available

        if not native_available():
            pytest.skip("native kv unavailable")
        self._roundtrip(tmp_path, "native")

    def test_python_engine_wrong_passphrase_raises(self, tmp_path):
        from nornicdb_tpu.encryption import EncryptionError

        data_dir = self._roundtrip(tmp_path, "python")
        with pytest.raises(EncryptionError):
            db = nornicdb_tpu.open(
                data_dir, engine="python", passphrase="wrong", auto_embed=False
            )
            db.cypher("MATCH (s:Secret) RETURN s.payload")

    def test_python_engine_missing_passphrase_raises(self, tmp_path):
        from nornicdb_tpu.encryption import EncryptionError

        data_dir = self._roundtrip(tmp_path, "python")
        with pytest.raises(EncryptionError):
            nornicdb_tpu.open(data_dir, engine="python", auto_embed=False)

    def test_native_engine_missing_passphrase_raises(self, tmp_path):
        from nornicdb_tpu.storage.disk import native_available

        if not native_available():
            pytest.skip("native kv unavailable")
        from nornicdb_tpu.encryption import EncryptionError

        data_dir = self._roundtrip(tmp_path, "native")
        with pytest.raises(EncryptionError):
            db = nornicdb_tpu.open(data_dir, engine="native", auto_embed=False)
            db.cypher("MATCH (s:Secret) RETURN s.payload")


# -- vectorspace registry on production paths -----------------------------


class TestVectorSpaceWiring:
    def test_search_service_registers_doc_space(self):
        from nornicdb_tpu.search.service import SearchService

        svc = SearchService()
        keys = svc.vector_registry.list()
        assert any(
            k.entity_type == "node" and k.vector_name == "embedding"
            for k in keys
        )
        # the registered space's index IS the live service index
        space = svc.vector_registry.get(keys[0])
        assert space.index is svc.vectors
        svc.vectors.add("a", [1.0, 0.0, 0.0])
        assert len(space.index) == 1

    def test_qdrant_collections_create_and_drop_spaces(self):
        from nornicdb_tpu.api.qdrant import QdrantCompat

        eng = NamespacedEngine(MemoryEngine(), "test")
        q = QdrantCompat(eng)
        q.create_collection("docs", {"size": 4, "distance": "Cosine"})
        keys = q.vector_registry.list(database="qdrant")
        assert [k.entity_type for k in keys] == ["docs"]
        assert q.get_collection("docs")["config"]["params"]["vectors"]["size"] == 4
        q.upsert_points("docs", [
            {"id": 1, "vector": [1, 0, 0, 0], "payload": {"t": "a"}},
        ])
        hits = q.search_points("docs", [1, 0, 0, 0], limit=1)
        assert hits and hits[0]["id"] == 1
        q.delete_collection("docs")
        assert q.vector_registry.list(database="qdrant") == []

    def test_qdrant_lazy_rebuild_after_restart_uses_registry(self):
        from nornicdb_tpu.api.qdrant import QdrantCompat

        eng = NamespacedEngine(MemoryEngine(), "test")
        q = QdrantCompat(eng)
        q.create_collection("docs", {"size": 2, "distance": "Cosine"})
        q.upsert_points("docs", [{"id": 7, "vector": [0.0, 1.0]}])
        # simulate restart: new compat instance over the same storage
        q2 = QdrantCompat(eng)
        hits = q2.search_points("docs", [0.0, 1.0], limit=1)
        assert hits and hits[0]["id"] == 7
        assert q2.vector_registry.list(database="qdrant")


# -- GDS link prediction procedures ---------------------------------------


class TestLinkPredictionProcedures:
    @pytest.fixture()
    def ex(self):
        eng = NamespacedEngine(MemoryEngine(), "test")
        ex = CypherExecutor(eng)
        # triangle-ish graph: a-b, a-c, b-c, b-d, c-d => predict a-d
        for n in "abcd":
            ex.execute(f"CREATE (:P {{name: '{n}'}})")
        for x, y in [("a", "b"), ("a", "c"), ("b", "c"), ("b", "d"), ("c", "d")]:
            ex.execute(
                "MATCH (x:P {name: $x}), (y:P {name: $y}) "
                "CREATE (x)-[:KNOWS]->(y)", {"x": x, "y": y},
            )
        return ex

    def _id_of(self, ex, name):
        return ex.execute(
            "MATCH (n:P {name: $n}) RETURN n", {"n": name}
        ).rows[0][0].id

    @pytest.mark.parametrize("proc", [
        "gds.linkPrediction.adamicAdar.stream",
        "gds.linkPrediction.commonNeighbors.stream",
        "gds.linkPrediction.jaccard.stream",
        "gds.linkPrediction.preferentialAttachment.stream",
        "gds.linkPrediction.resourceAllocation.stream",
    ])
    def test_stream_procedures_yield_scores(self, ex, proc):
        a = self._id_of(ex, "a")
        d = self._id_of(ex, "d")
        r = ex.execute(
            f"CALL {proc}({{sourceNode: $src, topK: 5}}) "
            "YIELD node1, node2, score RETURN node1, node2, score",
            {"src": a},
        )
        assert r.columns == ["node1", "node2", "score"]
        assert r.rows, f"{proc} returned no predictions"
        # 'd' shares two neighbors with 'a' and is not adjacent -> top hit
        assert r.rows[0][1] == d
        assert all(row[2] > 0 for row in r.rows)

    def test_hybrid_predict_stream(self, ex):
        a = self._id_of(ex, "a")
        r = ex.execute(
            "CALL gds.linkPrediction.predict.stream({sourceNode: $src, topK: 3}) "
            "YIELD node1, node2, score, topology_score RETURN *",
            {"src": a},
        )
        assert r.rows
        assert set(r.columns) >= {"node1", "node2", "score", "topology_score"}


# -- query cache liveness (already covered in parity tests; sanity here) --


def test_cache_stats_reachable_via_db(tmp_path):
    db = nornicdb_tpu.open(auto_embed=False)
    db.cypher("CREATE (:T {v: 1})")
    db.cypher("MATCH (t:T) RETURN t.v")
    db.cypher("MATCH (t:T) RETURN t.v")
    stats = db.executor.query_cache.stats()
    assert stats["hits"] >= 1
    db.close()
