"""Storage layer tests: engine contract, decorators, WAL durability.

Mirrors the reference's test strategy (SURVEY.md §4): MemoryEngine wrapped
in NamespacedEngine as the universal fixture, plus WAL
corruption/truncation/replay regressions (reference:
pkg/storage/wal_corruption_test.go, wal_durability_test.go).
"""

import os
import threading

import pytest

from nornicdb_tpu.errors import AlreadyExistsError, NotFoundError
from nornicdb_tpu.storage import (
    WAL,
    AsyncEngine,
    Direction,
    DurableEngine,
    Edge,
    MemoryEngine,
    NamespacedEngine,
    Node,
    WALEngine,
)


def _mk(engine, nid="n1", labels=("Person",), **props):
    node = Node(id=nid, labels=list(labels), properties=dict(props))
    engine.create_node(node)
    return node


class TestMemoryEngine:
    def test_node_crud(self):
        eng = MemoryEngine()
        _mk(eng, "n1", name="alice")
        got = eng.get_node("n1")
        assert got.properties["name"] == "alice"
        assert got.created_at > 0

        got.properties["age"] = 30
        eng.update_node(got)
        assert eng.get_node("n1").properties["age"] == 30

        with pytest.raises(AlreadyExistsError):
            _mk(eng, "n1")
        eng.delete_node("n1")
        with pytest.raises(NotFoundError):
            eng.get_node("n1")

    def test_label_index_follows_updates(self):
        eng = MemoryEngine()
        _mk(eng, "n1", labels=["Person", "Admin"])
        assert {n.id for n in eng.get_nodes_by_label("Admin")} == {"n1"}
        n = eng.get_node("n1")
        n.labels = ["Person"]
        eng.update_node(n)
        assert eng.get_nodes_by_label("Admin") == []
        assert {n.id for n in eng.get_nodes_by_label("Person")} == {"n1"}

    def test_edges_and_degree(self):
        eng = MemoryEngine()
        _mk(eng, "a")
        _mk(eng, "b")
        _mk(eng, "c")
        eng.create_edge(Edge(id="e1", type="KNOWS", start_node="a", end_node="b"))
        eng.create_edge(Edge(id="e2", type="KNOWS", start_node="c", end_node="a"))
        assert eng.degree("a", Direction.OUTGOING) == 1
        assert eng.degree("a", Direction.INCOMING) == 1
        assert eng.degree("a", Direction.BOTH) == 2
        assert sorted(eng.neighbors("a")) == ["b", "c"]
        assert {e.id for e in eng.get_edges_by_type("KNOWS")} == {"e1", "e2"}

    def test_edge_requires_endpoints(self):
        eng = MemoryEngine()
        _mk(eng, "a")
        with pytest.raises(NotFoundError):
            eng.create_edge(Edge(id="e1", type="T", start_node="a", end_node="zzz"))

    def test_delete_node_cascades_edges(self):
        eng = MemoryEngine()
        _mk(eng, "a")
        _mk(eng, "b")
        eng.create_edge(Edge(id="e1", type="T", start_node="a", end_node="b"))
        eng.delete_node("a")
        assert eng.count_edges() == 0
        assert eng.degree("b") == 0

    def test_returned_copies_are_isolated(self):
        eng = MemoryEngine()
        _mk(eng, "n1", name="alice")
        got = eng.get_node("n1")
        got.properties["name"] = "mutated"
        assert eng.get_node("n1").properties["name"] == "alice"

    def test_batch_get(self):
        eng = MemoryEngine()
        _mk(eng, "a")
        _mk(eng, "b")
        got = eng.batch_get_nodes(["a", "missing", "b"])
        assert got[0].id == "a" and got[1] is None and got[2].id == "b"

    def test_concurrent_writes(self):
        eng = MemoryEngine()

        def writer(start):
            for i in range(100):
                _mk(eng, f"n{start + i}")

        threads = [threading.Thread(target=writer, args=(k * 100,)) for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert eng.count_nodes() == 800


class TestNamespacedEngine:
    def test_isolation_between_databases(self):
        base = MemoryEngine()
        db1 = NamespacedEngine(base, "db1")
        db2 = NamespacedEngine(base, "db2")
        _mk(db1, "n1", name="one")
        _mk(db2, "n1", name="two")
        assert db1.get_node("n1").properties["name"] == "one"
        assert db2.get_node("n1").properties["name"] == "two"
        assert db1.count_nodes() == 1
        assert base.count_nodes() == 2
        assert base.list_namespaces() == ["db1", "db2"]

    def test_edges_namespaced(self):
        base = MemoryEngine()
        db1 = NamespacedEngine(base, "db1")
        _mk(db1, "a")
        _mk(db1, "b")
        db1.create_edge(Edge(id="e1", type="T", start_node="a", end_node="b"))
        e = db1.get_edge("e1")
        assert e.start_node == "a" and e.end_node == "b"
        raw = list(base.all_edges())[0]
        assert raw.start_node == "db1:a"

    def test_drop_database(self):
        base = MemoryEngine()
        db1 = NamespacedEngine(base, "db1")
        db2 = NamespacedEngine(base, "db2")
        _mk(db1, "a")
        _mk(db2, "a")
        nodes, _ = db1.drop_database()
        assert nodes == 1
        assert db1.count_nodes() == 0
        assert db2.count_nodes() == 1

    def test_label_scoped(self):
        base = MemoryEngine()
        db1 = NamespacedEngine(base, "db1")
        db2 = NamespacedEngine(base, "db2")
        _mk(db1, "a", labels=["Person"])
        _mk(db2, "b", labels=["Person"])
        assert {n.id for n in db1.get_nodes_by_label("Person")} == {"a"}


class TestWAL:
    def test_append_and_replay(self, tmp_path):
        wal = WAL(str(tmp_path))
        wal.append("create_node", {"id": "a"})
        wal.append("create_node", {"id": "b"})
        wal.close()

        wal2 = WAL(str(tmp_path))
        seen = []
        res = wal2.replay(lambda op, d: seen.append((op, d["id"])))
        assert res.records_applied == 2
        assert seen == [("create_node", "a"), ("create_node", "b")]
        assert wal2.last_seq == 2

    def test_torn_tail_repair(self, tmp_path):
        wal = WAL(str(tmp_path))
        wal.append("create_node", {"id": "a"})
        wal.append("create_node", {"id": "b"})
        wal.close()
        # corrupt the tail: append garbage bytes (reference: wal_corruption_test.go)
        seg = [p for p in os.listdir(tmp_path) if p.startswith("wal-")][0]
        with open(tmp_path / seg, "ab") as f:
            f.write(b"\x07\x00\x00\x00garbage!!")

        wal2 = WAL(str(tmp_path))
        seen = []
        res = wal2.replay(lambda op, d: seen.append(d["id"]))
        assert res.records_applied == 2
        assert res.torn_tail_repaired
        assert not res.degraded
        # after repair, a fresh replay is clean
        res2 = WAL(str(tmp_path)).replay(lambda op, d: None)
        assert not res2.torn_tail_repaired

    def test_snapshot_prunes_and_restores(self, tmp_path):
        wal = WAL(str(tmp_path), retained_segments=0)
        for i in range(10):
            wal.append("create_node", {"id": f"n{i}"})
        wal.write_snapshot({"nodes": [{"id": "snapshot-state"}], "edges": []})
        wal.append("create_node", {"id": "after-snap"})
        wal.close()

        wal2 = WAL(str(tmp_path))
        state, seq = wal2.load_snapshot()
        assert state["nodes"][0]["id"] == "snapshot-state"
        assert seq == 10
        applied = []
        res = wal2.replay(lambda op, d: applied.append(d["id"]), from_seq=seq)
        assert applied == ["after-snap"]
        assert res.last_seq == 11

    def test_segment_rotation(self, tmp_path):
        wal = WAL(str(tmp_path), max_segment_bytes=256)
        for i in range(50):
            wal.append("create_node", {"id": f"node-{i}", "pad": "x" * 50})
        wal.close()
        segs = [p for p in os.listdir(tmp_path) if p.startswith("wal-")]
        assert len(segs) > 1
        res = WAL(str(tmp_path)).replay(lambda op, d: None)
        assert res.records_applied == 50


class TestDurableEngine:
    def test_survives_restart(self, tmp_path):
        eng = DurableEngine(str(tmp_path))
        _mk(eng, "a", name="alice")
        _mk(eng, "b")
        eng.create_edge(Edge(id="e1", type="T", start_node="a", end_node="b"))
        eng.delete_node("b")
        eng.close()  # writes a snapshot

        eng2 = DurableEngine(str(tmp_path))
        assert eng2.get_node("a").properties["name"] == "alice"
        assert eng2.count_nodes() == 1
        assert eng2.count_edges() == 0
        eng2.close()

    def test_crash_without_snapshot(self, tmp_path):
        eng = DurableEngine(str(tmp_path))
        _mk(eng, "a")
        eng.wal.flush()
        # simulate crash: no close/snapshot
        eng2 = DurableEngine(str(tmp_path))
        assert eng2.count_nodes() == 1
        eng2.close()

    def test_replay_idempotent_over_snapshot(self, tmp_path):
        eng = DurableEngine(str(tmp_path))
        _mk(eng, "a")
        eng.snapshot()
        _mk(eng, "b")
        eng.wal.flush()
        eng2 = DurableEngine(str(tmp_path))
        assert eng2.count_nodes() == 2
        eng2.close()

    def test_wal_engine_over_memory(self, tmp_path):
        wal = WAL(str(tmp_path))
        eng = WALEngine(MemoryEngine(), wal)
        _mk(eng, "x")
        eng.close()
        # fresh engine, replay only
        wal2 = WAL(str(tmp_path))
        eng2 = WALEngine(MemoryEngine(), wal2)
        eng2.recover()
        assert eng2.count_nodes() == 1


class TestAsyncEngine:
    def test_read_your_writes_before_flush(self):
        eng = AsyncEngine(MemoryEngine(), flush_interval_s=0)  # manual flush
        _mk(eng, "a", name="alice")
        assert eng.get_node("a").properties["name"] == "alice"
        assert eng.count_nodes() == 1
        eng.flush_pending()
        assert eng.inner.count_nodes() == 1
        assert eng.count_nodes() == 1

    def test_delete_before_flush(self):
        eng = AsyncEngine(MemoryEngine(), flush_interval_s=0)
        _mk(eng, "a")
        eng.delete_node("a")
        with pytest.raises(NotFoundError):
            eng.get_node("a")
        assert eng.count_nodes() == 0
        eng.flush_pending()
        assert eng.inner.count_nodes() == 0

    def test_count_flush_race_regression(self):
        """Counts must stay correct while a flush races concurrent writes
        (reference: async_engine_count_flush_race_test.go)."""
        eng = AsyncEngine(MemoryEngine(), flush_interval_s=0.001)
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                _mk(eng, f"w{i}")
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(50):
                eng.flush_pending()
        finally:
            stop.set()
            t.join()
        eng.flush_pending()
        eng.flush_pending()
        assert eng.count_nodes() == eng.inner.count_nodes()
        eng.close()

    def test_edges_overlay(self):
        eng = AsyncEngine(MemoryEngine(), flush_interval_s=0)
        _mk(eng, "a")
        _mk(eng, "b")
        eng.create_edge(Edge(id="e1", type="T", start_node="a", end_node="b"))
        assert eng.degree("a", Direction.OUTGOING) == 1
        eng.flush_pending()
        assert eng.inner.count_edges() == 1
        eng.delete_node("a")
        assert eng.degree("b") == 0
        eng.flush_pending()
        assert eng.inner.count_edges() == 0


class TestReviewRegressions:
    """Regressions from the round-1 code review findings."""

    def test_duplicate_create_does_not_poison_wal(self, tmp_path):
        eng = DurableEngine(str(tmp_path))
        _mk(eng, "a")
        with pytest.raises(AlreadyExistsError):
            _mk(eng, "a")
        eng.wal.flush()
        # crash-restart must succeed (no poison record in the WAL)
        eng2 = DurableEngine(str(tmp_path))
        assert eng2.count_nodes() == 1
        eng2.close()

    def test_async_create_duplicate_raises(self):
        eng = AsyncEngine(MemoryEngine(), flush_interval_s=0)
        _mk(eng, "x", v=1)
        with pytest.raises(AlreadyExistsError):
            _mk(eng, "x", v=2)
        eng.flush_pending()
        with pytest.raises(AlreadyExistsError):
            _mk(eng, "x", v=3)
        assert eng.get_node("x").properties["v"] == 1

    def test_async_create_edge_validates_endpoints(self):
        eng = AsyncEngine(MemoryEngine(), flush_interval_s=0)
        _mk(eng, "a")
        with pytest.raises(NotFoundError):
            eng.create_edge(Edge(id="e", type="T", start_node="a", end_node="no"))

    def test_namespaced_id_prefix_no_aliasing(self):
        base = MemoryEngine()
        db1 = NamespacedEngine(base, "db1")
        _mk(db1, "x", v=1)
        _mk(db1, "db1:x", v=2)  # must be a distinct node, not an alias
        assert db1.get_node("x").properties["v"] == 1
        assert db1.get_node("db1:x").properties["v"] == 2
        db1.delete_node("db1:x")
        assert db1.get_node("x").properties["v"] == 1

    def test_unreadable_snapshot_refuses_silent_recovery(self, tmp_path):
        from nornicdb_tpu.errors import WALCorruptionError

        eng = DurableEngine(str(tmp_path))
        _mk(eng, "a")
        eng.snapshot()
        eng.close()
        # corrupt the only snapshot
        snaps = [p for p in os.listdir(tmp_path) if p.startswith("snapshot-")]
        with open(tmp_path / snaps[0], "r+b") as f:
            f.seek(0)
            f.write(b"\xff" * 16)
        with pytest.raises(WALCorruptionError):
            DurableEngine(str(tmp_path))


class TestNamespacedOptionalAPIs:
    """Optional bulk APIs used to fall through EngineDecorator.__getattr__
    UNQUALIFIED — count_nodes_by_label saw every database and clear()
    wiped them all (caught by the r5 admin-UI e2e; pinned here at the
    engine layer)."""

    def _two_dbs(self):
        from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine
        from nornicdb_tpu.storage.types import Node

        base = MemoryEngine()
        a = NamespacedEngine(base, "alpha")
        b = NamespacedEngine(base, "beta")
        a.create_node(Node(id="n1", labels=["Person"], properties={}))
        a.create_node(Node(id="n2", labels=["Person"], properties={}))
        b.create_node(Node(id="n1", labels=["Person"], properties={}))
        return base, a, b

    def test_count_nodes_by_label_is_scoped(self):
        _base, a, b = self._two_dbs()
        assert a.count_nodes_by_label("Person") == 2
        assert b.count_nodes_by_label("Person") == 1

    def test_prefix_counts_are_qualified(self):
        _base, a, b = self._two_dbs()
        assert a.count_nodes_with_prefix("n") == 2
        assert b.count_nodes_with_prefix("n") == 1
        assert a.count_nodes_with_prefix("zzz") == 0

    def test_clear_scoped_to_one_database(self):
        _base, a, b = self._two_dbs()
        a.clear()
        assert a.count_nodes() == 0
        assert b.count_nodes() == 1  # beta untouched

    def test_delete_by_prefix_qualified(self):
        _base, a, b = self._two_dbs()
        deleted_nodes, _edges = a.delete_by_prefix("n")
        assert deleted_nodes == 2
        assert b.count_nodes() == 1
