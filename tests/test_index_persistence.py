"""Search-index persistence + boot load + resume-aware build
(VERDICT r1 item 7; reference: search.go:432,496-507,
fulltext_index_v2_persist.go, hnsw_index.go:490,568)."""

import time

import numpy as np
import pytest

import nornicdb_tpu
from nornicdb_tpu.search.service import SearchService
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine
from nornicdb_tpu.storage.types import Node


def _mk_node(i, dim=8):
    rng = np.random.default_rng(i)
    return Node(id=f"n{i}", labels=["Doc"],
                properties={"content": f"document number {i} about topic{i % 5}"},
                embedding=list(rng.standard_normal(dim).astype(float)))


class TestServicePersistence:
    def test_save_and_load_roundtrip(self, tmp_path):
        eng = NamespacedEngine(MemoryEngine(), "test")
        svc = SearchService(eng, persist_dir=str(tmp_path / "idx"))
        for i in range(20):
            n = _mk_node(i)
            eng.create_node(n)
            svc.index_node(eng.get_node(n.id))
        results_before = svc.search("document topic1", limit=5)
        assert svc.save_indexes()

        svc2 = SearchService(eng, persist_dir=str(tmp_path / "idx"))
        indexed = svc2.build_indexes()
        assert indexed == 0, "resume-aware build must skip unchanged nodes"
        results_after = svc2.search("document topic1", limit=5)
        assert [r["id"] for r in results_before] == [
            r["id"] for r in results_after]

    def test_resume_indexes_only_new_and_updated(self, tmp_path):
        eng = NamespacedEngine(MemoryEngine(), "test")
        svc = SearchService(eng, persist_dir=str(tmp_path / "idx"))
        for i in range(10):
            n = _mk_node(i)
            eng.create_node(n)
            svc.index_node(eng.get_node(n.id))
        svc.save_indexes()
        time.sleep(0.01)
        # while "down": one new node, one updated node, one deleted node
        new = _mk_node(100)
        eng.create_node(new)
        upd = eng.get_node("n3")
        upd.properties["content"] = "freshly changed content xyzzy"
        eng.update_node(upd)
        eng.delete_node("n7")

        svc2 = SearchService(eng, persist_dir=str(tmp_path / "idx"))
        indexed = svc2.build_indexes()
        assert indexed == 2  # n100 + n3 only
        hits = svc2.search("xyzzy", limit=3)
        assert hits and hits[0]["id"] == "n3"
        assert "n7" not in svc2.vectors
        assert "n7" not in svc2.bm25

    def test_format_version_mismatch_falls_back(self, tmp_path):
        import json
        import os

        eng = NamespacedEngine(MemoryEngine(), "test")
        svc = SearchService(eng, persist_dir=str(tmp_path / "idx"))
        n = _mk_node(1)
        eng.create_node(n)
        svc.index_node(eng.get_node(n.id))
        svc.save_indexes()
        meta = os.path.join(str(tmp_path / "idx"), "meta.json")
        doc = json.load(open(meta))
        doc["format"] = 999
        json.dump(doc, open(meta, "w"))
        svc2 = SearchService(eng, persist_dir=str(tmp_path / "idx"))
        assert not svc2.load_indexes()
        assert svc2.build_indexes() == 1  # full rebuild

    def test_corrupt_snapshot_falls_back(self, tmp_path):
        import os

        eng = NamespacedEngine(MemoryEngine(), "test")
        svc = SearchService(eng, persist_dir=str(tmp_path / "idx"))
        n = _mk_node(1)
        eng.create_node(n)
        svc.index_node(eng.get_node(n.id))
        svc.save_indexes()
        with open(os.path.join(str(tmp_path / "idx"), "vectors.npz"), "wb") as f:
            f.write(b"garbage")
        svc2 = SearchService(eng, persist_dir=str(tmp_path / "idx"))
        assert not svc2.load_indexes()
        assert svc2.build_indexes() == 1

    def test_hnsw_persisted_and_restored(self, tmp_path):
        eng = NamespacedEngine(MemoryEngine(), "test")
        svc = SearchService(eng, persist_dir=str(tmp_path / "idx"),
                            hnsw_threshold=50)
        for i in range(60):
            n = _mk_node(i)
            eng.create_node(n)
            svc.index_node(eng.get_node(n.id))
        assert svc.hnsw is not None
        svc.save_indexes()
        svc2 = SearchService(eng, persist_dir=str(tmp_path / "idx"),
                             hnsw_threshold=50)
        assert svc2.load_indexes()
        assert svc2.hnsw is not None
        assert svc2.stats.strategy == "hnsw"


class TestDBLevelPersistence:
    def test_restart_skips_reembed_and_rebuild(self, tmp_path):
        data_dir = str(tmp_path / "db")
        db = nornicdb_tpu.open(data_dir)
        for i in range(8):
            db.store(f"note number {i} about tigers", node_id=f"m{i}")
        db.flush()
        before = [h["id"] for h in db.recall("tigers note")]
        assert before
        db.close()

        db2 = nornicdb_tpu.open(data_dir)
        # embedder must not run again: embeddings already stored AND the
        # search service loads its snapshot instead of re-indexing
        calls = {"n": 0}
        real_embed = db2._embedder.embed

        def counting(text):
            calls["n"] += 1
            return real_embed(text)

        db2._embedder.embed = counting
        svc = db2.search  # triggers boot load
        after = [h["id"] for h in db2.recall("tigers note")]
        assert after == before
        assert calls["n"] <= 1  # only the query embedding, never docs
        db2.close()
