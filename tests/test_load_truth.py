"""Load-truth observability (ISSUE 7): queue-delay stage attribution,
per-query device cost accounting, histogram exemplars with OpenMetrics
content negotiation, the open-loop knee estimator, and the
metric-catalog drift lint.

The acceptance contract pinned here: every MicroBatcher/BatchCoalescer
rider records its coalesce-wait/dispatch/merge (or apply) split into
``nornicdb_request_stage_seconds{surface,stage}`` and the derived
queueing fraction answers "queued or compute?"; device dispatches are
priced in FLOPs/bytes per (kind, index) and aggregate per real query;
``/metrics`` serves OpenMetrics exemplars under content negotiation
while the classic exposition stays byte-identical with tagging on or
off; SLO flight-recorder dumps carry the stage summary; the knee
estimator flags queueing collapse a closed-loop bench cannot see; and
an import-time metric family missing from docs/observability.md fails
the catalog lint.
"""

import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

from nornicdb_tpu import obs
from nornicdb_tpu.obs import cost as obs_cost
from nornicdb_tpu.obs import stages as obs_stages
from nornicdb_tpu.obs.metrics import LATENCY_BUCKETS, Registry
from nornicdb_tpu.search.microbatch import BatchCoalescer, MicroBatcher
from nornicdb_tpu.search.vector_index import BruteForceIndex

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
sys.path.insert(0, REPO)


def _stage_child(surface, stage):
    fam = obs.REGISTRY.get("nornicdb_request_stage_seconds")
    assert fam is not None
    return fam.children().get((surface, stage))


def _stage_count(surface, stage):
    child = _stage_child(surface, stage)
    return child.snapshot()["count"] if child is not None else 0


# ---------------------------------------------------------------------------
# stage attribution
# ---------------------------------------------------------------------------


class TestStageAttribution:
    def test_record_stage_clamps_negative_intervals(self):
        before = _stage_count("t-clamp", "coalesce_wait")
        obs.record_stage("t-clamp", "coalesce_wait", -0.5)
        child = _stage_child("t-clamp", "coalesce_wait")
        snap = child.snapshot()
        assert snap["count"] == before + 1
        assert snap["sum"] == 0.0  # clamped, not recorded negative

    def test_stage_summary_math_and_queueing_fraction(self):
        r = Registry()
        h = r.histogram("nornicdb_request_stage_seconds", "t",
                        labels=("surface", "stage"),
                        buckets=LATENCY_BUCKETS)
        # 3 requests: 10ms wait + 30ms dispatch each on one surface
        for _ in range(3):
            h.labels("svc", "coalesce_wait").observe(0.010)
            h.labels("svc", "device_dispatch").observe(0.030)
        h.labels("other", "parse").observe(0.002)
        summary = obs.stage_summary(r)
        svc = summary["svc"]
        assert svc["stages"]["coalesce_wait"]["count"] == 3
        assert svc["stages"]["coalesce_wait"]["total_ms"] == \
            pytest.approx(30.0, abs=0.01)
        assert svc["stages"]["device_dispatch"]["mean_ms"] == \
            pytest.approx(30.0, abs=0.01)
        # queueing fraction: 30ms waited / 120ms attributed = 0.25
        assert svc["queueing_fraction"] == pytest.approx(0.25, abs=0.001)
        # a surface with no queue-delay stage reports 0.0, not None
        assert summary["other"]["queueing_fraction"] == 0.0

    def test_microbatcher_records_stage_split(self):
        idx = BruteForceIndex()
        rng = np.random.default_rng(3)
        vecs = rng.standard_normal((32, 8)).astype(np.float32)
        idx.add_batch([(f"v{i}", vecs[i]) for i in range(32)])
        mb = MicroBatcher(idx.search_batch, surface="t-stage-mb")
        before = {s: _stage_count("t-stage-mb", s)
                  for s in ("coalesce_wait", "device_dispatch", "merge")}
        n = 5
        for i in range(n):
            mb.search(vecs[i], 3)
        for s in ("coalesce_wait", "device_dispatch", "merge"):
            assert _stage_count("t-stage-mb", s) == before[s] + n, s

    def test_convoy_records_wait_and_apply_stages(self):
        applied = []
        co = BatchCoalescer(lambda batch: [applied.append(v) or v
                                           for v in batch],
                            surface="t-stage-convoy")
        before_wait = _stage_count("t-stage-convoy", "coalesce_wait")
        before_apply = _stage_count("t-stage-convoy", "apply")
        n_threads = 6
        barrier = threading.Barrier(n_threads)

        def write(i):
            barrier.wait()
            assert co.submit(i) == i

        threads = [threading.Thread(target=write, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(applied) == list(range(n_threads))
        assert _stage_count("t-stage-convoy", "coalesce_wait") == \
            before_wait + n_threads
        assert _stage_count("t-stage-convoy", "apply") == \
            before_apply + n_threads

    def test_convoy_stage_spans_ride_the_trace(self):
        co = BatchCoalescer(lambda batch: list(batch),
                            surface="t-span-convoy")
        with obs.trace("wire", method="/t/convoy") as root:
            co.submit("x")
        names = root.span_names()
        assert "coalesce.wait" in names and "apply" in names

    def test_convoy_queue_depth_contract_and_gauge(self):
        """Satellite: write convoys expose the same queue_depth contract
        MicroBatchers got in PR 5, and registering one with
        obs/resources surfaces nornicdb_queue_depth{queue=...}."""
        import re

        from nornicdb_tpu.obs import register_resource, resource_snapshot

        release = threading.Event()
        entered = threading.Event()

        def slow_apply(batch):
            entered.set()
            release.wait(timeout=5)
            return list(batch)

        co = BatchCoalescer(slow_apply, surface="t-depth-convoy")
        assert co.queue_depth() == 0
        register_resource("queue", "t-depth-convoy", co)
        leader = threading.Thread(target=co.submit, args=("lead",))
        leader.start()
        assert entered.wait(timeout=5)
        # while the leader holds the apply, new submissions queue
        followers = [threading.Thread(target=co.submit, args=(i,))
                     for i in range(3)]
        for t in followers:
            t.start()
        deadline = 50
        while co.queue_depth() < 3 and deadline:
            deadline -= 1
            import time as _t
            _t.sleep(0.01)
        assert co.queue_depth() == 3
        entries = [e for e in resource_snapshot()
                   if e["family"] == "queue"
                   and e["index"] == "t-depth-convoy"]
        assert entries and entries[0]["queue_depth"] == 3
        text = obs.REGISTRY.render()
        m = re.search(
            r'nornicdb_queue_depth\{queue="t-depth-convoy"\} (\d+)',
            text)
        assert m and int(m.group(1)) == 3
        release.set()
        leader.join()
        for t in followers:
            t.join()
        assert co.queue_depth() == 0

    def test_qdrant_upsert_convoy_registered(self):
        """The qdrant compat layer registers its upsert coalescer so
        write convoys are /readyz- and gauge-visible."""
        import nornicdb_tpu
        from nornicdb_tpu.api.qdrant import QdrantCompat
        from nornicdb_tpu.obs import resource_snapshot

        db = nornicdb_tpu.open(auto_embed=False)
        try:
            compat = QdrantCompat(db)
            # registration name is per-instance (bare for the first
            # compat in the process, ":n"-suffixed after) so concurrent
            # instances never shadow each other's gauge
            name = compat._convoy_resource_name
            assert name.startswith("qdrant:upsert_convoy")
            entries = [e for e in resource_snapshot()
                       if e["family"] == "queue"
                       and e["index"] == name]
            assert entries and "queue_depth" in entries[0]
            assert compat._upsert_coalescer.queue_depth() == 0
        finally:
            db.close()

    def test_stage_summary_served_in_admin_telemetry(self):
        import nornicdb_tpu
        from nornicdb_tpu.api.http_server import HttpServer

        db = nornicdb_tpu.open(auto_embed=False)
        db.store("stage doc", node_id="st-1", embedding=[0.5] * 8)
        http = HttpServer(db, port=0).start()
        try:
            db.search.search("", mode="vector",
                             query_embedding=[0.5] * 8)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}/admin/telemetry",
                    timeout=5) as resp:
                doc = json.loads(resp.read())
            assert "stages" in doc and "cost" in doc
            vec = doc["stages"].get("service:vector")
            assert vec is not None
            assert "coalesce_wait" in vec["stages"]
            assert vec["queueing_fraction"] is not None
        finally:
            http.stop()
            db.close()


# ---------------------------------------------------------------------------
# per-query cost accounting
# ---------------------------------------------------------------------------


class TestQueryCost:
    def test_pricing_functions_scale_with_shape(self):
        f1, b1 = obs_cost.price_brute(1, 1000, 64)
        f8, b8 = obs_cost.price_brute(8, 1000, 64)
        assert f1 == 2.0 * 1000 * 64 and f8 == 8 * f1
        assert b8 > b1 > 0
        fw, bw = obs_cost.price_walk(4, 64, iters=12, width=4,
                                     degree=16, itopk=64)
        assert fw > 0 and bw > 0
        # more iterations = strictly more work
        fw2, _ = obs_cost.price_walk(4, 64, iters=24, width=4,
                                     degree=16, itopk=64)
        assert fw2 > fw
        fb, bb = obs_cost.price_bm25(4, nnz=5000, unique_terms=30,
                                     rows=2000)
        assert fb >= 8.0 * 5000 and bb > 0

    def test_record_and_summary_per_kind_index(self):
        obs_cost.record_query_cost("t_kind", "t_idx", 4, 1000.0, 400.0)
        obs_cost.record_query_cost("t_kind", "t_idx", 4, 1000.0, 400.0)
        rows = [r for r in obs.cost_summary()
                if r["kind"] == "t_kind" and r["index"] == "t_idx"]
        assert len(rows) == 1
        row = rows[0]
        assert row["queries"] == 8
        assert row["flops_total"] == 2000.0
        assert row["flops_per_query"] == 250.0
        assert row["bytes_per_query"] == 100.0

    def test_brute_search_is_priced_under_resource_identity(self):
        from nornicdb_tpu.obs import register_resource

        idx = BruteForceIndex()
        register_resource("brute", "t-cost-brute", idx)
        rng = np.random.default_rng(5)
        vecs = rng.standard_normal((16, 8)).astype(np.float32)
        idx.add_batch([(f"v{i}", vecs[i]) for i in range(16)])
        idx.search_batch([vecs[0], vecs[1]], 3)
        rows = [r for r in obs.cost_summary()
                if r["kind"] == "brute" and r["index"] == "t-cost-brute"]
        assert len(rows) == 1
        row = rows[0]
        assert row["queries"] >= 2
        # priced at the capacity-padded matrix, so per-query flops >=
        # the live-rows price (padding waste is the point)
        assert row["flops_per_query"] >= 2.0 * 16 * 8

    def test_unregistered_structure_prices_as_unregistered(self):
        idx = BruteForceIndex()
        assert obs_cost.cost_name(idx) == "unregistered"

    def test_device_bm25_and_hybrid_dispatches_priced(self):
        """End-to-end: a hybrid search through the service prices its
        device dispatches (kind depends on corpus-size routing, but the
        cost table must gain rows under the service's identity)."""
        import nornicdb_tpu

        db = nornicdb_tpu.open(auto_embed=False)
        try:
            for i in range(8):
                db.store(f"doc about topic{i % 3} number {i}",
                         node_id=f"c{i}", embedding=[float(i % 3)] * 8)
            db.search.search("topic1", mode="text")
            rows = obs.cost_summary()
            assert any(r["index"].startswith("service:") or
                       r["index"] == "unregistered" for r in rows)
        finally:
            db.close()


# ---------------------------------------------------------------------------
# exemplars + OpenMetrics exposition
# ---------------------------------------------------------------------------


class TestExemplars:
    def test_traced_observe_tags_bucket(self):
        r = Registry()
        h = r.histogram("nornicdb_ex_seconds", "t")
        with obs.trace("wire", method="/t/ex") as root:
            h.observe(0.001)
        assert root.trace_id is not None
        # unlabeled histogram family: the default child carries the tag
        exemplars = [e for e in h.labels().exemplars() if e is not None]
        assert len(exemplars) == 1
        tid, value, ts = exemplars[0]
        assert tid == root.trace_id
        assert value == pytest.approx(0.001)
        assert ts > 0

    def test_untraced_observe_stays_untagged(self):
        r = Registry()
        h = r.histogram("nornicdb_ex2_seconds", "t")
        h.labels().observe(0.001)
        assert all(e is None for e in h.labels().exemplars())

    def test_toggle_disables_tagging(self):
        r = Registry()
        h = r.histogram("nornicdb_ex3_seconds", "t")
        obs.set_exemplars_enabled(False)
        try:
            with obs.trace("wire", method="/t/ex3"):
                h.labels().observe(0.001)
            assert all(e is None for e in h.labels().exemplars())
        finally:
            obs.set_exemplars_enabled(True)
        assert obs.exemplars_enabled()

    def test_openmetrics_exposition_carries_exemplar_and_eof(self):
        r = Registry()
        h = r.histogram("nornicdb_ex4_seconds", "t", labels=("m",))
        with obs.trace("wire", method="/t/ex4") as root:
            h.labels("a").observe(0.001)
        om = r.render_openmetrics()
        assert om.endswith("# EOF\n")
        assert f'# {{trace_id="{root.trace_id}"}}' in om
        # spec: counter TYPE line drops _total, sample keeps it
        c = r.counter("nornicdb_ex4_total", "t")
        c.inc()
        om = r.render_openmetrics()
        assert "# TYPE nornicdb_ex4 counter" in om
        assert "nornicdb_ex4_total 1" in om

    def test_classic_exposition_byte_identical_with_tagging(self):
        def build(tag: bool):
            r = Registry()
            h = r.histogram("nornicdb_ex5_seconds", "t", labels=("m",))
            obs.set_exemplars_enabled(tag)
            try:
                with obs.trace("wire", method="/t/ex5"):
                    for v in (0.001, 0.004, 0.2):
                        h.labels("a").observe(v)
            finally:
                obs.set_exemplars_enabled(True)
            return r.render()

        tagged, untagged = build(True), build(False)
        assert tagged == untagged
        assert "trace_id" not in tagged

    def test_metrics_endpoint_content_negotiation(self):
        import nornicdb_tpu
        from nornicdb_tpu.api.http_server import HttpServer
        from nornicdb_tpu.obs.metrics import REGISTRY as GLOBAL_REG

        db = nornicdb_tpu.open(auto_embed=False)
        http = HttpServer(db, port=0).start()
        base = f"http://127.0.0.1:{http.port}/metrics"
        try:
            with urllib.request.urlopen(base, timeout=5) as resp:
                classic_type = resp.headers.get("Content-Type", "")
                classic = resp.read().decode()
            req = urllib.request.Request(base, headers={
                "Accept": "application/openmetrics-text; version=1.0.0"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                om_type = resp.headers.get("Content-Type", "")
                om = resp.read().decode()
            assert "openmetrics" not in classic_type
            assert "# EOF" not in classic
            assert om_type.startswith("application/openmetrics-text")
            assert om.rstrip().endswith("# EOF")
            assert GLOBAL_REG.OPENMETRICS_CONTENT_TYPE.startswith(
                "application/openmetrics-text")
        finally:
            http.stop()
            db.close()

    def test_trace_ids_unique_and_visible_in_traces(self):
        ids = set()
        for _ in range(50):
            with obs.trace("wire", method="/t/uniq") as root:
                pass
            ids.add(root.trace_id)
        assert len(ids) == 50
        doc = root.to_dict()
        assert doc["trace_id"] == root.trace_id


# ---------------------------------------------------------------------------
# SLO flight recorder carries the stage summary
# ---------------------------------------------------------------------------


class TestFlightRecorderStages:
    def test_dump_includes_stage_decomposition(self, tmp_path):
        from nornicdb_tpu.obs.slo import Objective, SloEngine

        r = Registry()
        h = r.histogram("nornicdb_slotest_seconds", "t", labels=("m",))
        # the dump summarizes ITS registry's stage family (in
        # production that is the process-wide one)
        sh = r.histogram("nornicdb_request_stage_seconds", "t",
                         labels=("surface", "stage"),
                         buckets=LATENCY_BUCKETS)
        sh.labels("t-slo-dump", "coalesce_wait").observe(0.005)
        sh.labels("t-slo-dump", "device_dispatch").observe(0.015)
        eng = SloEngine(
            registry=r,
            objectives=[Objective("test", "nornicdb_slotest_seconds",
                                  0.1, 0.99)],
            windows=(10.0, 60.0), min_requests=10,
            dump_dir=str(tmp_path / "flight"),
            dump_interval_s=300.0, sample_min_interval_s=0.0)
        for _ in range(100):
            h.labels("a").observe(0.001)
        eng.tick(now=1000.0)
        for _ in range(50):
            h.labels("a").observe(2.0)
        eng.tick(now=1004.0)
        assert len(eng.dumps) == 1
        lines = [json.loads(ln) for ln in
                 open(eng.dumps[0], encoding="utf-8")]
        stages = [ln for ln in lines if ln["kind"] == "stages"]
        assert len(stages) == 1
        summary = stages[0]["summary"]
        assert "t-slo-dump" in summary
        assert summary["t-slo-dump"]["queueing_fraction"] == \
            pytest.approx(0.25, abs=0.001)


# ---------------------------------------------------------------------------
# open-loop knee estimator
# ---------------------------------------------------------------------------


def _pt(offered_qps, achieved_qps, p99, offered=100, completed=100,
        errors=0, timed_out=0):
    return {"offered_qps": offered_qps, "achieved_qps": achieved_qps,
            "offered": offered, "completed": completed,
            "errors": errors, "timed_out": timed_out, "p99_ms": p99}


class TestKneeEstimator:
    def test_stable_sweep_knee_is_best_achieved(self):
        import bench

        points = [_pt(100, 99, 2.0), _pt(200, 198, 2.5),
                  _pt(400, 390, 4.0)]
        est = bench._estimate_knee(points)
        assert est["knee_qps"] == 390
        assert est["p99_at_load_ms"] == 4.0
        assert est["queue_collapse_detected"] is False
        assert not any(p["collapsed"] for p in points)

    def test_p99_slope_blowup_flags_collapse(self):
        import bench

        points = [_pt(100, 99, 2.0), _pt(200, 198, 2.5),
                  _pt(400, 395, 300.0)]  # 120x the previous p99
        est = bench._estimate_knee(points)
        assert points[-1]["collapsed"] is True
        assert est["queue_collapse_detected"] is True
        assert est["knee_qps"] == 198  # last stable point

    def test_achieved_shortfall_and_timeouts_flag_collapse(self):
        import bench

        points = [_pt(100, 99, 2.0),
                  _pt(400, 300, 5.0, offered=400, completed=300),
                  _pt(800, 500, 6.0, timed_out=10)]
        bench._estimate_knee(points)
        assert points[1]["collapsed"] and points[2]["collapsed"]

    def test_fully_collapsed_sweep_still_emits_gate_metric(self):
        import bench

        points = [_pt(100, 50, 900.0, offered=100, completed=50)]
        est = bench._estimate_knee(points)
        # gate metric exists even when no point was stable
        assert est["knee_qps"] == 50
        assert est["p99_at_load_ms"] == 900.0
        assert est["queue_collapse_detected"] is True


# ---------------------------------------------------------------------------
# metric-catalog drift lint
# ---------------------------------------------------------------------------


class TestMetricsCatalogLint:
    def test_catalog_is_current(self):
        """The repo's own doc covers every import-time family — the
        CI wiring of scripts/check_metrics_catalog.py. Families come
        from a FRESH subprocess (--list), not this test process's
        registry, which earlier tests may have polluted with
        lazily-created families outside the import-time contract."""
        import subprocess

        import check_metrics_catalog as lint

        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "check_metrics_catalog.py"),
             "--list"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        families = json.loads(out.stdout)
        assert "nornicdb_request_stage_seconds" in families
        assert "nornicdb_query_cost_flops_total" in families
        doc_path = os.path.join(REPO, "docs", "observability.md")
        with open(doc_path, encoding="utf-8") as f:
            doc_text = f.read()
        missing = lint.missing_from_catalog(doc_text, families)
        assert missing == [], (
            f"undocumented metric families {missing}: add them to "
            f"docs/observability.md (the catalog lint gates this)")

    def test_lint_catches_removed_family(self):
        import check_metrics_catalog as lint

        families = ["nornicdb_request_stage_seconds",
                    "nornicdb_invented_total"]
        missing = lint.missing_from_catalog(
            "the doc mentions request_stage_seconds only", families)
        assert missing == ["nornicdb_invented_total"]

    def test_lint_rejects_substring_of_documented_name(self):
        """Matching is word-bounded: a new family whose name happens to
        be a substring of a documented one must still be flagged."""
        import check_metrics_catalog as lint

        doc = "catalog: nornicdb_request_stage_seconds"
        missing = lint.missing_from_catalog(
            doc, ["nornicdb_stage_seconds",
                  "nornicdb_request_stage_seconds"])
        assert missing == ["nornicdb_stage_seconds"]

    def test_brace_shorthand_expands(self):
        import check_metrics_catalog as lint

        doc = "wire_cache_{hits,misses,invalidations}_total"
        missing = lint.missing_from_catalog(
            doc, ["nornicdb_wire_cache_hits_total",
                  "nornicdb_wire_cache_misses_total",
                  "nornicdb_wire_cache_invalidations_total"])
        assert missing == []

    def test_cli_exit_codes(self):
        import subprocess

        ok = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "check_metrics_catalog.py")],
            capture_output=True, text=True, cwd=REPO)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        verdict = json.loads(ok.stdout)
        assert verdict["verdict"] == "pass"


# ---------------------------------------------------------------------------
# open-loop harness plumbing (no servers: the async point machinery)
# ---------------------------------------------------------------------------


class TestOpenLoopPoint:
    def test_poisson_point_offered_vs_achieved(self):
        import asyncio

        import bench

        async def run():
            async def send():
                await asyncio.sleep(0.001)

            return await bench._open_loop_point(
                send, rate_qps=200.0, duration_s=0.25, seed=7)

        point = asyncio.run(run())
        assert point["offered"] > 10
        assert point["completed"] == point["offered"]
        assert point["errors"] == 0 and point["timed_out"] == 0
        assert point["p99_ms"] is not None and point["p99_ms"] >= 1.0
        # arrivals are open-loop: offered rate tracks the request, not
        # the 1ms service time (allow generous sleep-resolution slack)
        assert point["offered_qps"] > 100

    def test_errors_counted_not_raised(self):
        import asyncio

        import bench

        async def run():
            async def send():
                raise RuntimeError("down")

            return await bench._open_loop_point(
                send, rate_qps=100.0, duration_s=0.1, seed=7)

        point = asyncio.run(run())
        assert point["errors"] == point["offered"] > 0
        assert point["completed"] == 0
        assert point["p99_ms"] is None
