"""Tests for embed pipeline + AI-native subsystems (decay, temporal,
inference, linkpredict, filters)."""

import time

import numpy as np
import pytest

from nornicdb_tpu.decay import DAY_MS, DecayManager, Tier
from nornicdb_tpu.embed import (
    CachedEmbedder,
    EmbedQueue,
    HashEmbedder,
    HashTokenizer,
    JaxEncoderEmbedder,
    chunk_tokens,
)
from nornicdb_tpu.filters import AdaptiveKalmanFilter, KalmanFilter, VelocityKalmanFilter
from nornicdb_tpu.inference import InferenceEngine
from nornicdb_tpu.linkpredict import (
    AdjacencySnapshot,
    adamic_adar,
    jaccard,
    predict_links,
)
from nornicdb_tpu.search.service import SearchService
from nornicdb_tpu.storage import (
    Edge,
    ListenableEngine,
    MemoryEngine,
    NamespacedEngine,
    Node,
    now_ms,
)
from nornicdb_tpu.temporal import TemporalTracker


class TestTokenizer:
    def test_deterministic(self):
        tok = HashTokenizer()
        assert tok.encode("hello world") == tok.encode("hello world")
        assert tok.encode("hello") != tok.encode("goodbye")

    def test_chunking_512_50(self):
        ids = list(range(1200))
        chunks = chunk_tokens(ids, 512, 50)
        assert chunks[0] == ids[:512]
        assert chunks[1][0] == ids[462]  # 512 - 50 overlap
        assert chunks[-1][-1] == ids[-1]

    def test_short_text_single_chunk(self):
        assert chunk_tokens(list(range(100)), 512, 50) == [list(range(100))]


class TestEmbedders:
    def test_hash_embedder_similarity(self):
        emb = HashEmbedder(dims=128)
        a = np.asarray(emb.embed("the quick brown fox jumps"))
        b = np.asarray(emb.embed("the quick brown fox leaps"))
        c = np.asarray(emb.embed("completely unrelated text about databases"))
        assert a @ b > a @ c

    def test_jax_encoder_embedder(self):
        from nornicdb_tpu.models.encoder import EncoderConfig

        emb = JaxEncoderEmbedder(cfg=EncoderConfig.tiny())
        vecs = emb.embed_batch(["hello world", "another text"])
        assert len(vecs) == 2 and len(vecs[0]) == emb.dims
        np.testing.assert_allclose(np.linalg.norm(vecs[0]), 1.0, atol=1e-3)
        # determinism
        np.testing.assert_allclose(emb.embed("hello world"), vecs[0], atol=1e-5)

    def test_jax_embedder_chunks(self):
        from nornicdb_tpu.models.encoder import EncoderConfig

        emb = JaxEncoderEmbedder(cfg=EncoderConfig.tiny())
        long_text = " ".join(f"word{i}" for i in range(500))
        chunks = emb.embed_chunks(long_text)
        assert len(chunks) >= 2

    def test_cached_embedder(self):
        inner = HashEmbedder(dims=32)
        cached = CachedEmbedder(inner, capacity=2)
        v1 = cached.embed("a")
        v2 = cached.embed("a")
        assert v1 == v2 and cached.hits == 1 and cached.misses == 1
        cached.embed_batch(["b", "c", "a"])  # 'a' may be evicted by cap 2
        assert cached.embed("b") is not None


class TestEmbedQueue:
    def _setup(self):
        eng = ListenableEngine(NamespacedEngine(MemoryEngine(), "test"))
        embedded = []
        q = EmbedQueue(
            eng, HashEmbedder(dims=32), on_embedded=embedded.append,
            rescan_interval_s=0,
        )
        eng.add_listener(q)
        q.start()
        return eng, q, embedded

    def test_embeds_on_upsert(self):
        eng, q, embedded = self._setup()
        try:
            eng.create_node(Node(id="n1", labels=[], properties={"content": "hello"}))
            # listener sees the namespaced node; queue should still resolve it
            q.drain(5)
            node = eng.get_node("n1")
            assert node.embedding is not None
            assert len(embedded) == 1
        finally:
            q.stop()

    def test_long_text_gets_chunks(self):
        eng = ListenableEngine(NamespacedEngine(MemoryEngine(), "test"))
        from nornicdb_tpu.models.encoder import EncoderConfig

        q = EmbedQueue(eng, JaxEncoderEmbedder(cfg=EncoderConfig.tiny()),
                       rescan_interval_s=0)
        eng.add_listener(q)
        q.start()
        try:
            text = " ".join(f"tok{i}" for i in range(3000))
            eng.create_node(Node(id="long", labels=[], properties={"content": text}))
            q.drain(30)
            node = eng.get_node("long")
            assert node.embedding is not None
            assert node.chunk_embeddings and len(node.chunk_embeddings) >= 2
        finally:
            q.stop()

    def test_failed_embedder_fails_open(self):
        eng = ListenableEngine(NamespacedEngine(MemoryEngine(), "test"))

        class Broken:
            dims = 8

            def embed_batch(self, texts):
                raise RuntimeError("boom")

        q = EmbedQueue(eng, Broken(), max_retries=2, rescan_interval_s=0)
        eng.add_listener(q)
        q.start()
        try:
            eng.create_node(Node(id="x", labels=[], properties={"content": "y"}))
            q.drain(5)
            assert q.failed_count == 1
            assert eng.get_node("x").embedding is None
        finally:
            q.stop()


class TestDecay:
    def test_tier_half_lives(self):
        eng = NamespacedEngine(MemoryEngine(), "t")
        mgr = DecayManager(eng, use_kalman=False)
        assert mgr.half_life(Tier.EPISODIC) == 7 * DAY_MS
        assert mgr.half_life(Tier.SEMANTIC) == 69 * DAY_MS
        assert mgr.half_life(Tier.PROCEDURAL) == 693 * DAY_MS

    def test_recency_decays(self):
        eng = NamespacedEngine(MemoryEngine(), "t")
        mgr = DecayManager(eng, use_kalman=False)
        now = now_ms()
        eng.create_node(Node(id="old", labels=[], properties={},
                             created_at=now - 30 * DAY_MS, updated_at=now - 30 * DAY_MS))
        eng.create_node(Node(id="new", labels=[], properties={},
                             created_at=now, updated_at=now))
        s_old = mgr.score(eng.get_node("old"), now)
        s_new = mgr.score(eng.get_node("new"), now)
        assert s_new.score > s_old.score
        assert s_old.recency == pytest.approx(0.5 ** (30 / 7), rel=1e-3)

    def test_promotion(self):
        eng = NamespacedEngine(MemoryEngine(), "t")
        mgr = DecayManager(eng)
        for _ in range(5):
            mgr.record_access("n")
        assert mgr.tier_of("n") == Tier.SEMANTIC
        for _ in range(25):
            mgr.record_access("n")
        assert mgr.tier_of("n") == Tier.PROCEDURAL

    def test_sweep_archives(self):
        eng = NamespacedEngine(MemoryEngine(), "t")
        mgr = DecayManager(eng, use_kalman=False, archive_threshold=0.2)
        now = now_ms()
        eng.create_node(Node(id="stale", labels=[],
                             properties={"importance": 0.0},
                             created_at=now - 300 * DAY_MS,
                             updated_at=now - 300 * DAY_MS))
        scored, archived = mgr.sweep(now)
        assert scored == 1 and archived == 1
        assert eng.get_node("stale").properties["_archived"] is True


class TestTemporal:
    def test_velocity_and_sessions(self):
        tr = TemporalTracker()
        t0 = 1000.0
        for i in range(5):
            tr.record_access("a", t0 + i * 10)
        st = tr.stats("a")
        assert st.count == 5 and st.velocity > 0
        sid1, nodes = tr.session
        assert "a" in nodes
        # a 31-minute gap starts a new session
        tr.record_access("b", t0 + 50 + 1900)
        sid2, nodes2 = tr.session
        assert sid2 == sid1 + 1 and nodes2 == ["b"]

    def test_co_access(self):
        tr = TemporalTracker()
        for i in range(3):
            tr.record_access("x", 100.0 + i)
            tr.record_access("y", 100.5 + i)
        tr.record_access("z", 99999.0)
        co = dict(tr.co_accessed("x"))
        assert co.get("y", 0) >= 3 and "z" not in co


class TestInference:
    def _setup(self):
        eng = NamespacedEngine(MemoryEngine(), "t")
        svc = SearchService(eng)
        inf = InferenceEngine(eng, svc, similarity_threshold=0.8)
        return eng, svc, inf

    def test_similarity_autolink(self):
        eng, svc, inf = self._setup()
        v = [1.0, 0.0, 0.0]
        for nid, vec in [("a", v), ("b", [0.99, 0.1, 0.0]), ("c", [0.0, 1.0, 0.0])]:
            eng.create_node(Node(id=nid, labels=[], properties={}, embedding=vec))
            svc.index_node(eng.get_node(nid))
        node = eng.get_node("a")
        sugs = inf.on_store(node)
        assert any(s.to_id == "b" for s in sugs)
        assert all(s.to_id != "c" for s in sugs)
        edges = eng.get_node_edges("a")
        assert any(e.properties.get("inferred") for e in edges)

    def test_cooldown_blocks_repeat(self):
        eng, svc, inf = self._setup()
        for nid in ("a", "b"):
            eng.create_node(Node(id=nid, labels=[], properties={},
                                 embedding=[1.0, 0.0]))
            svc.index_node(eng.get_node(nid))
        n = eng.get_node("a")
        first = inf.on_store(n)
        # delete the edge; cooldown should still block instant re-creation
        for e in eng.get_node_edges("a"):
            eng.delete_edge(e.id)
        second = inf.on_store(n)
        assert first and not second

    def test_best_of_chunks(self):
        eng, svc, inf = self._setup()
        eng.create_node(Node(id="t", labels=[], properties={}, embedding=[0.0, 1.0]))
        svc.index_node(eng.get_node("t"))
        chunky = Node(id="c", labels=[], properties={},
                      chunk_embeddings=[[1.0, 0.0], [0.05, 1.0]])
        eng.create_node(chunky)
        sugs = inf.on_store(eng.get_node("c"))
        assert any(s.to_id == "t" for s in sugs)  # second chunk matches

    def test_transitive(self):
        eng, svc, inf = self._setup()
        for nid in ("a", "b", "c"):
            eng.create_node(Node(id=nid, labels=[], properties={}))
        eng.create_edge(Edge(id="e1", type="SIMILAR_TO", start_node="a", end_node="b"))
        eng.create_edge(Edge(id="e2", type="SIMILAR_TO", start_node="b", end_node="c"))
        sugs = inf.suggest_transitive("a")
        assert len(sugs) == 1 and sugs[0].to_id == "c"


class TestLinkPredict:
    def _graph(self):
        eng = NamespacedEngine(MemoryEngine(), "t")
        for nid in "abcdz":
            eng.create_node(Node(id=nid, labels=[], properties={}))
        # a-b, a-c, b-d, c-d: a and d share neighbors b, c
        for i, (s, t) in enumerate([("a", "b"), ("a", "c"), ("b", "d"), ("c", "d")]):
            eng.create_edge(Edge(id=f"e{i}", type="T", start_node=s, end_node=t))
        return eng

    def test_scores(self):
        eng = self._graph()
        snap = AdjacencySnapshot(eng)
        assert jaccard(snap, "a", "d") == 1.0  # identical neighbor sets
        assert adamic_adar(snap, "a", "d") > 0

    def test_predict_links_excludes_existing(self):
        eng = self._graph()
        preds = predict_links(eng, "a")
        ids = [p[0] for p in preds]
        assert "d" in ids and "b" not in ids and "z" not in ids


class TestKalman:
    def test_basic_converges(self):
        kf = KalmanFilter(measurement_noise=0.5)
        for _ in range(100):
            est = kf.update(10.0)
        assert est == pytest.approx(10.0, abs=0.1)

    def test_adaptive_tracks_noise(self):
        kf = AdaptiveKalmanFilter()
        rng = np.random.default_rng(0)
        for _ in range(50):
            kf.update(5.0 + rng.standard_normal() * 2)
        assert kf.measurement_noise > 1e-3

    def test_velocity_filter(self):
        kf = VelocityKalmanFilter(measurement_noise=1e-3)
        for i in range(50):
            pos, vel = kf.update(float(i * 2), float(i))
        assert vel == pytest.approx(2.0, abs=0.3)


class TestAiNativeReviewRegressions:
    def test_batch_not_wedged_by_one_failure(self):
        """One node's write failure must not leave siblings stuck in
        _pending (they would never re-embed)."""
        eng = ListenableEngine(NamespacedEngine(MemoryEngine(), "test"))

        class FlakyStorage:
            def __init__(self, inner):
                self.inner = inner
                self.fail_ids = set()

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def update_node(self, node):
                if node.id in self.fail_ids:
                    raise RuntimeError("disk full")
                return self.inner.update_node(node)

        flaky = FlakyStorage(eng)
        q = EmbedQueue(flaky, HashEmbedder(dims=16), rescan_interval_s=0,
                       batch_size=4)
        flaky.fail_ids.add("bad")
        eng.create_node(Node(id="bad", labels=[], properties={"content": "x"}))
        eng.create_node(Node(id="good", labels=[], properties={"content": "y"}))
        q.enqueue("bad")
        q.enqueue("good")
        q.start()
        try:
            q.drain(5)
            assert eng.get_node("good").embedding is not None
            assert q.failed_count == 1
            with q._lock:
                assert not q._pending  # nothing wedged
        finally:
            q.stop()

    def test_velocity_filter_prior_covariance(self):
        kf = VelocityKalmanFilter()
        kf.update(0.0, 10.0)
        kf.update(2.0, 11.0)
        assert abs(kf.p10 - kf.p01) < 1e-9  # covariance stays symmetric

    def test_velocity_filter_t_zero_start(self):
        kf = VelocityKalmanFilter(measurement_noise=1e-3)
        kf.update(5.0, 0.0)
        _, vel = kf.update(7.0, 1.0)
        assert vel > 0.5  # not collapsed by dt=1e-9

    def test_decay_non_numeric_importance(self):
        eng = NamespacedEngine(MemoryEngine(), "t")
        mgr = DecayManager(eng, use_kalman=False)
        eng.create_node(Node(id="n", labels=[], properties={"importance": "high"}))
        s = mgr.score(eng.get_node("n"))
        assert s.importance == 0.5

    def test_cached_embedder_dedupes_batch(self):
        calls = []

        class Counting:
            dims = 8

            def embed(self, t):
                return [1.0] * 8

            def embed_batch(self, texts):
                calls.append(list(texts))
                return [[1.0] * 8 for _ in texts]

        cached = CachedEmbedder(Counting())
        cached.embed_batch(["a", "a", "b", "a"])
        assert calls == [["a", "b"]]

    def test_cached_embedder_exposes_chunks(self):
        from nornicdb_tpu.models.encoder import EncoderConfig

        inner = JaxEncoderEmbedder(cfg=EncoderConfig.tiny())
        cached = CachedEmbedder(inner)
        assert hasattr(cached, "embed_chunks")
        long_text = " ".join(f"w{i}" for i in range(500))
        assert len(cached.embed_chunks(long_text)) >= 2
