"""Real-workload replay parity (reference analog:
pkg/cypher/mimir_queries_test.go — a captured application session
replayed against the engine, failures memorialized as regressions).

One deterministic "knowledge-app" session — bursts of writes, point
reads, traversals, aggregations, updates, deletes, search-adjacent
lookups — replayed statement-by-statement on TWO executors over
independent stores: fast paths + caches ON (production config) vs the
general row interpreter (fastpaths and caches off). Every statement's
rows and stats must agree; state digests are compared at checkpoints.

This is the harness that catches cross-statement interactions the
per-feature parity corpora can't: a materialized view gone stale after
an interleaved delete, a cached plan surviving a schema change, a
point-write fast path leaving different stats than the interpreter.
"""

import random

import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


def _executors():
    fast = CypherExecutor(NamespacedEngine(MemoryEngine(), "wl"))
    slow = CypherExecutor(NamespacedEngine(MemoryEngine(), "wl"))
    slow.enable_fastpaths = False
    slow.enable_query_cache = False
    return fast, slow


def _norm_rows(result):
    out = []
    for row in result.rows:
        norm = []
        for v in row:
            if hasattr(v, "id") and hasattr(v, "labels"):
                norm.append(("node", v.id, tuple(sorted(v.labels)),
                             tuple(sorted(
                                 (k, repr(x))
                                 for k, x in v.properties.items()))))
            elif hasattr(v, "type") and hasattr(v, "start_node"):
                norm.append(("rel", v.type, v.start_node, v.end_node))
            else:
                norm.append(repr(v))
        out.append(tuple(norm))
    return sorted(map(repr, out))


def _stats_tuple(result):
    s = result.stats
    return (s.nodes_created, s.nodes_deleted, s.relationships_created,
            s.relationships_deleted, s.labels_added)


def _digest(ex):
    """Order-independent full-state digest through the query surface."""
    rows = []
    rows += _norm_rows(ex.execute(
        "MATCH (n) RETURN labels(n), n.id, n.name, n.kind, n.score"))
    rows += _norm_rows(ex.execute(
        "MATCH (a)-[r]->(b) RETURN type(r), a.id, b.id"))
    return rows


def _session(seed: int):
    """Deterministic mixed workload as (statement, params) pairs."""
    rng = random.Random(seed)
    stmts = []
    n_users, n_docs = 40, 120
    for i in range(n_users):
        stmts.append((
            "CREATE (:User {id: $i, name: $n, score: $s})",
            {"i": i, "n": f"user{i}", "s": rng.randrange(100)}))
    for d in range(n_docs):
        stmts.append((
            "CREATE (:Doc {id: $i, kind: $k, name: $t})",
            {"i": 1000 + d, "k": ["note", "task", "ref"][d % 3],
             "t": f"doc {d}"}))
    for d in range(n_docs):
        stmts.append((
            "MATCH (u:User {id: $u}), (d:Doc {id: $d}) "
            "CREATE (u)-[:WROTE]->(d)",
            {"u": rng.randrange(n_users), "d": 1000 + d}))
    for _ in range(60):
        stmts.append((
            "MATCH (a:User {id: $a}), (b:User {id: $b}) "
            "CREATE (a)-[:FOLLOWS]->(b)",
            {"a": rng.randrange(n_users), "b": rng.randrange(n_users)}))
    # interleave reads with mutations from here on
    ops = []
    for _ in range(140):
        roll = rng.random()
        if roll < 0.25:
            ops.append((
                "MATCH (u:User {id: $u})-[:WROTE]->(d:Doc) "
                "RETURN d.name ORDER BY d.name LIMIT 5",
                {"u": rng.randrange(n_users)}))
        elif roll < 0.40:
            ops.append((
                "MATCH (u:User)-[:WROTE]->(d:Doc) "
                "RETURN u.name, count(d) AS n ORDER BY n DESC, u.name "
                "LIMIT 10", {}))
        elif roll < 0.50:
            ops.append((
                "MATCH (a:User)-[:FOLLOWS]->(m:User)-[:FOLLOWS]->(b:User) "
                "WHERE a <> b RETURN a.name, b.name, count(m) AS paths",
                {}))
        elif roll < 0.62:
            ops.append((
                "MATCH (d:Doc {id: $d}) SET d.score = $s",
                {"d": 1000 + rng.randrange(n_docs),
                 "s": rng.randrange(10)}))
        elif roll < 0.72:
            ops.append((
                "MATCH (u:User {id: $u}), (d:Doc {id: $d}) "
                "CREATE (u)-[:REVIEWED]->(d)",
                {"u": rng.randrange(n_users),
                 "d": 1000 + rng.randrange(n_docs)}))
        elif roll < 0.80:
            # delete + recreate a doc (exercises view invalidation)
            d = 1000 + rng.randrange(n_docs)
            ops.append((
                "MATCH (d:Doc {id: $d}) DETACH DELETE d", {"d": d}))
            ops.append((
                "CREATE (:Doc {id: $d, kind: 'reborn', name: $t})",
                {"d": d, "t": f"doc-re {d}"}))
        elif roll < 0.90:
            ops.append((
                "MATCH (d:Doc) WHERE d.kind = $k RETURN count(d)",
                {"k": ["note", "task", "ref", "reborn"][rng.randrange(4)]}))
        else:
            ops.append((
                "MATCH (u:User) RETURN u.kind, count(u), avg(u.score)",
                {}))
    # advanced clause families, interleaved at the tail
    for j in range(12):
        ops.append((
            "MERGE (t:Tag {name: $n}) RETURN t.name",
            {"n": f"tag{j % 5}"}))
        ops.append((
            "MATCH (d:Doc {id: $d}), (t:Tag {name: $n}) "
            "MERGE (d)-[:TAGGED]->(t)",
            {"d": 1000 + rng.randrange(n_docs), "n": f"tag{j % 5}"}))
        ops.append((
            "UNWIND $rows AS r CREATE (:Event {id: r.id, kind: r.k})",
            {"rows": [{"id": 5000 + j * 10 + x, "k": "evt"}
                      for x in range(3)]}))
        ops.append((
            "MATCH (u:User {id: $u}) OPTIONAL MATCH (u)-[:REVIEWED]->(d) "
            "RETURN u.name, count(d)",
            {"u": rng.randrange(n_users)}))
        ops.append((
            "MATCH (u:User)-[:WROTE]->(d:Doc) WITH u, count(d) AS nd "
            "WHERE nd > 2 RETURN u.name, nd ORDER BY nd DESC, u.name "
            "LIMIT 5", {}))
    return stmts + ops


class TestWorkloadReplayParity:
    @pytest.mark.parametrize("seed", [7, 23])
    def test_session_replays_identically(self, seed):
        fast, slow = _executors()
        divergences = []
        for idx, (stmt, params) in enumerate(_session(seed)):
            rf = fast.execute(stmt, dict(params))
            rs = slow.execute(stmt, dict(params))
            if _norm_rows(rf) != _norm_rows(rs):
                divergences.append((idx, stmt, _norm_rows(rf)[:3],
                                    _norm_rows(rs)[:3]))
            if _stats_tuple(rf) != _stats_tuple(rs):
                divergences.append((idx, stmt, "stats",
                                    _stats_tuple(rf), _stats_tuple(rs)))
            if divergences:
                break  # first divergence is the actionable one
            if idx % 50 == 49:
                assert _digest(fast) == _digest(slow), (
                    f"state digests diverged by statement {idx}")
        assert not divergences, divergences[0]
        assert _digest(fast) == _digest(slow)

    def test_repeated_reads_stable_under_cache(self):
        """The same read repeated across interleaved writes must track
        state exactly (cache invalidation, not staleness)."""
        fast, slow = _executors()
        for i in range(30):
            for ex in (fast, slow):
                ex.execute("CREATE (:Item {id: $i, bucket: $b})",
                           {"i": i, "b": i % 3})
            q = "MATCH (x:Item) WHERE x.bucket = 1 RETURN count(x)"
            a = fast.execute(q).rows
            b = slow.execute(q).rows
            assert a == b == [[i // 3 + (1 if i % 3 >= 1 else 0)]]
