"""Device-truth calibration plane (ISSUE 20): measured dispatch
timing, cost-model calibration, device-memory reconciliation, and
cost-aware admission.

The acceptance contracts pinned here:

- the EWMA service-time models calibrate from steady-state samples
  only, abstain below the confidence floor, and split compile out of
  first-call wall time (the PR 3 conflation, fixed);
- a compile observed after a kind is warm increments the
  unexpected-recompile counter and lands ONE ``recompile`` journal
  event;
- the memory ledger reconciles shape-derived gauges against the
  backend probe; sustained drift past the bound flips the leak verdict
  (counter + ``/readyz`` reason), transient drift does not;
- the admission cost gate sheds a predicted-over-budget query with
  reason ``admission_cost`` (exactly-once ledger + journal) at posture
  >= degrade, admits under budget, and abstains when the model is
  unconfident or the posture is ``admit`` — the full matrix;
- measured device wall seconds split across batch riders by tenant
  (the ISSUE 18 rider-mix rule, now in time);
- the 2x + 1ms/op overhead guard HOLDS with the timing bracket
  sampling every dispatch.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from nornicdb_tpu import admission as adm
from nornicdb_tpu import obs
from nornicdb_tpu.obs import audit
from nornicdb_tpu.obs import device as dev
from nornicdb_tpu.obs import dispatch as dsp
from nornicdb_tpu.obs import events as obs_events
from nornicdb_tpu.obs import tenant
from nornicdb_tpu.search.microbatch import MicroBatcher
from nornicdb_tpu.search.vector_index import BruteForceIndex


@pytest.fixture(autouse=True)
def _fresh_device_state(monkeypatch):
    # every steady dispatch samples (deterministic math) unless a test
    # overrides; the models/joins start empty and the admission
    # controller's counters reset around each test
    monkeypatch.setenv("NORNICDB_DEVICE_TIMING_SAMPLE", "1")
    dev.reload()
    dev.reset()
    dev.set_backend_probe(None)
    adm.CONTROLLER.reset()
    yield
    dev.set_backend_probe(None)
    dev.reset()
    dev.reload()
    adm.CONTROLLER.reset()


def _force_posture(monkeypatch, posture):
    monkeypatch.setattr(adm.CONTROLLER, "refresh",
                        lambda now=None, force=False: posture)
    monkeypatch.setattr(adm.CONTROLLER, "posture", posture)


def _feed(kind, b, k, first_s, steady_s, n_steady):
    """Drive the observer directly with a fake timer feed: one first
    call, then n steady calls at a flat execute time."""
    dev.observe_dispatch(kind, b, k, first_s, True)
    for _ in range(n_steady):
        dev.observe_dispatch(kind, b, k, steady_s, False)


def _cost_sheds():
    return [r for r in audit.LEDGER.snapshot(limit=500)
            if r.get("reason") == "admission_cost"]


def _cost_shed_events():
    return [r for r in obs_events.event_snapshot(limit=500, kind="shed")
            if r.get("reason") == "admission_cost"]


# ---------------------------------------------------------------------------
# calibration math (fake timer feeds — no device, no clock)
# ---------------------------------------------------------------------------


class TestCalibrationMath:
    def test_predict_abstains_below_min_samples(self):
        min_n = dev.cfg()["min_samples"]
        _feed("fake_kind", 8, 16, 0.100, 0.010, min_n - 1)
        assert dev.predict_ms("fake_kind", 8) is None
        dev.observe_dispatch("fake_kind", 8, 16, 0.010, False)
        assert dev.predict_ms("fake_kind", 8) == pytest.approx(
            10.0, rel=0.01)

    def test_predict_unknown_kind_or_bucket_is_none(self):
        assert dev.predict_ms("never_served", 8) is None
        _feed("fake_kind", 8, 16, 0.1, 0.01, 20)
        assert dev.predict_ms("fake_kind", 64) is None

    def test_ewma_tracks_flat_feed_exactly(self):
        _feed("fake_kind", 8, 16, 0.100, 0.010, 20)
        # a flat feed converges to the flat value whatever alpha is
        assert dev.predict_ms("fake_kind", 8) == pytest.approx(
            10.0, rel=1e-6)

    def test_compile_split_subtracts_steady_estimate(self):
        _feed("fake_kind", 8, 16, 0.120, 0.010, 20)
        doc = dev.calibration_summary()["kinds"]["fake_kind"]
        # first call 120ms, steady 10ms -> compile est 110ms; execute
        # is measured total minus the compile share
        assert doc["compile_s_est"] == pytest.approx(0.110, rel=0.01)
        assert doc["execute_s"] == pytest.approx(
            0.120 + 20 * 0.010 - 0.110, rel=0.01)
        assert doc["compile_shapes_split"] == 1

    def test_first_call_series_keeps_conflated_meaning(self):
        _feed("legacy_kind", 4, 8, 0.2, 0.01, 10)
        # PR 3's series is byte-compatible: the first-call gauge still
        # carries the CONFLATED wall time; the calibrated split lives
        # in its own family
        dsp.record_dispatch("legacy_kind", 4, 8, 0.0)  # ensure family
        fam = obs.REGISTRY.get("nornicdb_device_first_call_seconds")
        assert fam is not None
        assert "conflated" in fam.help or "compile AND execute" \
            in fam.help

    def test_roofline_join_and_padding_efficiency(self):
        _feed("fake_kind", 8, 16, 0.020, 0.010, 20)
        # cost priced pre-padding: 6 real rows per 8-row dispatch
        for _ in range(21):
            dev.note_cost("fake_kind", 6, 1e6, 2e5)
        doc = dev.calibration_summary()["kinds"]["fake_kind"]
        assert doc["padding_efficiency"] == pytest.approx(6 / 8,
                                                          rel=0.01)
        assert doc["eff_flops_per_s"] == pytest.approx(
            21e6 / doc["execute_s"], rel=0.01)
        assert doc["eff_bytes_per_s"] == pytest.approx(
            21 * 2e5 / doc["execute_s"], rel=0.01)

    def test_dispatch_scope_credits_serving_kind(self):
        with dev.dispatch_scope("serving_kind"):
            # the inner plane prices under its own cost kind and
            # records its own nested dispatch
            dev.note_cost("inner_kind", 4, 5e5, 1e5)
            dev.observe_dispatch("inner_kind", 4, 8, 0.001, True)
            dev.observe_dispatch("serving_kind", 8, 16, 0.010, True)
        cal = dev.calibration_summary()
        assert cal["kinds"]["serving_kind"]["flops"] == 5e5
        assert "inner_kind" not in cal["served_kinds"]  # nested only
        assert cal["kinds"]["inner_kind"]["top_dispatches"] == 0

    def test_note_real_rows_overrides_padded_pricing(self):
        # a coalescer pads 3 riders to an 8-row program and the inner
        # plane prices the padded array; the note pins the real count
        with dev.dispatch_scope("serving_kind"):
            dev.note_real_rows(3.0)
            dev.note_cost("inner_kind", 8, 1e6, 1e5)
            dev.observe_dispatch("serving_kind", 8, 16, 0.010, True)
        for _ in range(12):
            with dev.dispatch_scope("serving_kind"):
                dev.note_real_rows(3.0)
                dev.note_cost("inner_kind", 8, 1e6, 1e5)
                dev.observe_dispatch("serving_kind", 8, 16, 0.010,
                                     False)
        doc = dev.calibration_summary()["kinds"]["serving_kind"]
        assert doc["padding_efficiency"] == pytest.approx(3 / 8,
                                                          rel=0.01)

    def test_coverage_counts_top_level_served_kinds_only(self):
        # a fully calibrated kind...
        _feed("covered", 8, 16, 0.02, 0.01, 20)
        for _ in range(21):
            dev.note_cost("covered", 8, 1e6, 1e5)
        cal = dev.calibration_summary()
        assert cal["served_kinds"] == ["covered"]
        assert cal["calibration_coverage"] == 1.0
        # ...then a served kind with no cost join drops coverage
        _feed("uncosted", 4, 8, 0.02, 0.01, 20)
        cal = dev.calibration_summary()
        assert set(cal["served_kinds"]) == {"covered", "uncosted"}
        assert cal["calibration_coverage"] == 0.5
        assert "uncosted" not in cal["calibrated_kinds"]


# ---------------------------------------------------------------------------
# unexpected-recompile detector
# ---------------------------------------------------------------------------


class TestRecompileDetector:
    def test_cold_compiles_are_expected(self):
        before = dev.calibration_summary()["unexpected_recompiles"]
        _feed("cold_kind", 8, 16, 0.1, 0.01, 5)
        assert dev.calibration_summary()["unexpected_recompiles"] \
            == before

    def test_warm_compile_counts_and_journals(self, monkeypatch):
        monkeypatch.setenv("NORNICDB_DEVICE_RECOMPILE_WARMUP", "10")
        dev.reload()
        ev0 = len(obs_events.event_snapshot(limit=500,
                                            kind="recompile"))
        before = dev.calibration_summary()["unexpected_recompiles"]
        _feed("warm_kind", 8, 16, 0.1, 0.01, 12)  # warm: 13 >= 10
        dev.observe_dispatch("warm_kind", 32, 16, 0.250, True)
        assert dev.calibration_summary()["unexpected_recompiles"] \
            == before + 1
        evs = obs_events.event_snapshot(limit=500, kind="recompile")
        assert len(evs) == ev0 + 1
        rec = evs[-1]
        assert rec["surface"] == "warm_kind"
        assert rec["reason"] == "bucket_churn"
        assert rec["detail"]["b"] == 32
        assert rec["detail"]["first_call_ms"] == pytest.approx(250.0)


# ---------------------------------------------------------------------------
# device-memory ledger reconciliation
# ---------------------------------------------------------------------------


class TestMemoryLedger:
    def test_backend_probe_injection(self):
        dev.set_backend_probe(lambda: 12345.0)
        assert dev.backend_bytes() == 12345.0

    def test_transient_drift_is_not_a_leak(self, monkeypatch):
        monkeypatch.setenv("NORNICDB_DEVICE_MEM_DRIFT_BYTES", "1000")
        monkeypatch.setenv("NORNICDB_DEVICE_MEM_DRIFT_S", "60")
        dev.reload()
        ledger = dev.ledger_bytes()
        dev.set_backend_probe(lambda: ledger + 1e9)
        t0 = time.time()
        doc = dev.reconcile(now=t0)
        assert doc["drift_bytes"] == pytest.approx(1e9)
        assert not doc["leak_suspected"]
        # drift clears before the window elapses: episode resets
        dev.set_backend_probe(lambda: ledger)
        doc = dev.reconcile(now=t0 + 30)
        assert not doc["leak_suspected"]
        dev.set_backend_probe(lambda: ledger + 1e9)
        doc = dev.reconcile(now=t0 + 31)
        assert doc["sustained_s"] == 0.0 and not doc["leak_suspected"]

    def test_sustained_drift_flags_leak_once_per_episode(self,
                                                         monkeypatch):
        monkeypatch.setenv("NORNICDB_DEVICE_MEM_DRIFT_BYTES", "1000")
        monkeypatch.setenv("NORNICDB_DEVICE_MEM_DRIFT_S", "60")
        dev.reload()
        ledger = dev.ledger_bytes()
        dev.set_backend_probe(lambda: ledger + 1e9)
        leak = obs.REGISTRY.get("nornicdb_device_mem_leak_total")
        c0 = leak.value
        t0 = time.time()
        assert not dev.reconcile(now=t0)["leak_suspected"]
        doc = dev.reconcile(now=t0 + 61)
        assert doc["leak_suspected"] and doc["sustained_s"] >= 60
        assert leak.value == c0 + 1
        # still drifting: the episode counts ONCE
        doc = dev.reconcile(now=t0 + 120)
        assert doc["leak_suspected"]
        assert leak.value == c0 + 1
        # recovery closes the episode; a fresh one counts again
        dev.set_backend_probe(lambda: ledger)
        assert not dev.reconcile(now=t0 + 121)["leak_suspected"]
        dev.set_backend_probe(lambda: ledger + 1e9)
        dev.reconcile(now=t0 + 122)
        dev.reconcile(now=t0 + 200)
        assert leak.value == c0 + 2

    def test_no_probe_means_abstain_not_zero_drift(self):
        dev.set_backend_probe(lambda: None)
        doc = dev.reconcile()
        assert doc["backend_bytes"] is None
        assert doc["drift_bytes"] is None
        assert not doc["leak_suspected"]


# ---------------------------------------------------------------------------
# cost-aware admission: the gate matrix
# ---------------------------------------------------------------------------


def _confident_model(kind="microbatch", bucket=1, ms=50.0):
    dev.observe_dispatch(kind, bucket, 16, 1.0, True)
    for _ in range(dev.cfg()["min_samples"] + 2):
        dev.observe_dispatch(kind, bucket, 16, ms / 1e3, False)


class TestAdmissionCostGate:
    def test_confident_over_budget_sheds_exactly_once(self,
                                                      monkeypatch):
        _confident_model(ms=50.0)
        _force_posture(monkeypatch, "degrade")
        led0, ev0 = len(_cost_sheds()), len(_cost_shed_events())
        with adm.deadline_scope(time.time() + 0.010):  # 10ms < 50ms
            with pytest.raises(adm.ShedError) as ei:
                adm.CONTROLLER.cost_check("t-cost", "microbatch", 1,
                                          "interactive")
        assert ei.value.reason == "admission_cost"
        assert ei.value.status == 429
        assert len(_cost_sheds()) == led0 + 1
        assert len(_cost_shed_events()) == ev0 + 1

    def test_confident_under_budget_admits_with_prediction(
            self, monkeypatch):
        _confident_model(ms=5.0)
        _force_posture(monkeypatch, "degrade")
        with adm.deadline_scope(time.time() + 1.0):
            pred = adm.CONTROLLER.cost_check("t-cost", "microbatch",
                                             1, "interactive")
        assert pred == pytest.approx(5.0, rel=0.01)

    def test_unconfident_model_abstains_at_degrade(self, monkeypatch):
        # below the sample floor there is NO prediction: the gate
        # does nothing even over budget (queue-wait-only, no guess)
        dev.observe_dispatch("microbatch", 1, 16, 0.050, True)
        dev.observe_dispatch("microbatch", 1, 16, 0.050, False)
        _force_posture(monkeypatch, "degrade")
        led0 = len(_cost_sheds())
        with adm.deadline_scope(time.time() + 0.001):
            assert adm.CONTROLLER.cost_check(
                "t-cost", "microbatch", 1, "interactive") is None
        assert len(_cost_sheds()) == led0

    def test_admit_posture_skips_gate_even_over_budget(self,
                                                       monkeypatch):
        _confident_model(ms=500.0)
        _force_posture(monkeypatch, "admit")
        with adm.deadline_scope(time.time() + 0.001):
            assert adm.CONTROLLER.cost_check(
                "t-cost", "microbatch", 1, "interactive") is None

    def test_shed_posture_gates_too(self, monkeypatch):
        _confident_model(ms=50.0)
        _force_posture(monkeypatch, "shed")
        with adm.deadline_scope(time.time() + 0.010):
            with pytest.raises(adm.ShedError):
                adm.CONTROLLER.cost_check("t-cost", "microbatch", 1,
                                          "interactive")

    def test_no_deadline_means_no_gate(self, monkeypatch):
        _confident_model(ms=500.0)
        _force_posture(monkeypatch, "degrade")
        assert adm.CONTROLLER.cost_check(
            "t-cost", "microbatch", 1, "interactive") is None

    def test_gate_disable_knob(self, monkeypatch):
        _confident_model(ms=500.0)
        _force_posture(monkeypatch, "degrade")
        monkeypatch.setenv("NORNICDB_ADMISSION_COST_GATE", "0")
        adm.reload()
        try:
            with adm.deadline_scope(time.time() + 0.001):
                assert adm.CONTROLLER.cost_check(
                    "t-cost", "microbatch", 1, "interactive") is None
        finally:
            monkeypatch.delenv("NORNICDB_ADMISSION_COST_GATE")
            adm.reload()

    def test_end_to_end_microbatch_ingress_shed(self, monkeypatch):
        # the real seam: a MicroBatcher rider with a confident model,
        # degrade posture and a too-tight budget sheds AT INGRESS —
        # before taking a queue slot — with the exactly-once records
        idx = BruteForceIndex()
        rng = np.random.default_rng(21)
        vecs = rng.standard_normal((64, 16)).astype(np.float32)
        idx.add_batch([(f"c{i}", vecs[i]) for i in range(64)])
        mb = MicroBatcher(idx.search_batch, surface="t-cost-e2e")
        for i in range(dev.cfg()["min_samples"] + 4):
            mb.search(vecs[i % 64], 5)
        pred = dev.predict_ms("microbatch", 1)
        assert pred is not None
        _force_posture(monkeypatch, "degrade")
        led0, ev0 = len(_cost_sheds()), len(_cost_shed_events())
        with adm.deadline_scope(time.time() + pred / 1e3 / 2.0):
            with pytest.raises(adm.ShedError) as ei:
                mb.search(vecs[0], 5)
        assert ei.value.reason == "admission_cost"
        assert len(_cost_sheds()) == led0 + 1
        assert len(_cost_shed_events()) == ev0 + 1


# ---------------------------------------------------------------------------
# per-tenant device seconds (the rider-mix rule, in time)
# ---------------------------------------------------------------------------


class TestTenantDeviceSeconds:
    def test_measured_seconds_split_across_batch_mix(self):
        fam = obs.REGISTRY.get("nornicdb_tenant_device_seconds_total")

        def val(label):
            ch = fam.children().get((label,))
            return ch.value if ch is not None else 0.0

        a0, b0 = val("dt-a"), val("dt-b")
        with tenant.batch_scope(["dt-a", "dt-a", "dt-a", "dt-b"]):
            dev.observe_dispatch("mix_kind", 4, 8, 0.008, True)
        assert val("dt-a") - a0 == pytest.approx(0.006, rel=0.01)
        assert val("dt-b") - b0 == pytest.approx(0.002, rel=0.01)

    def test_device_seconds_ride_tenants_summary(self):
        with tenant.tenant_scope("dt-solo", explicit=True):
            dev.observe_dispatch("mix_kind", 2, 8, 0.004, True)
        doc = tenant.tenants_summary()
        mine = [t for t in doc["tenants"]
                if t["tenant"] == "dt-solo"]
        assert mine and mine[0]["cost"]["device_seconds"] \
            == pytest.approx(0.004, rel=0.01)


# ---------------------------------------------------------------------------
# /readyz + /admin surfaces
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serving():
    import nornicdb_tpu
    from nornicdb_tpu.api.http_server import HttpServer

    db = nornicdb_tpu.open(auto_embed=False)
    db.store("device truth probe", node_id="dt-1",
             embedding=[0.25] * 8)
    db.search.search("probe", mode="text")
    http = HttpServer(db, port=0).start()
    yield {"db": db, "http": http}
    http.stop()
    db.close()


def _http_get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestAdminSurfaces:
    def test_admin_device_serves_calibration_and_memory(self, serving):
        _feed("fake_kind", 8, 16, 0.02, 0.01, 20)
        for _ in range(21):
            dev.note_cost("fake_kind", 8, 1e6, 1e5)
        status, doc = _http_get(serving["http"].port, "/admin/device")
        assert status == 200
        assert "fake_kind" in doc["kinds"]
        assert doc["kinds"]["fake_kind"]["eff_flops_per_s"] > 0
        assert "calibration_coverage" in doc
        assert "memory" in doc and "bound_bytes" in doc["memory"]

    def test_telemetry_carries_device_block(self, serving):
        status, doc = _http_get(serving["http"].port,
                                "/admin/telemetry")
        assert status == 200
        assert "device" in doc
        assert "calibration_coverage" in doc["device"]

    def test_readyz_carries_leak_reason(self, serving, monkeypatch):
        monkeypatch.setenv("NORNICDB_DEVICE_MEM_DRIFT_BYTES", "1000")
        monkeypatch.setenv("NORNICDB_DEVICE_MEM_DRIFT_S", "0")
        dev.reload()
        ledger = dev.ledger_bytes()
        dev.set_backend_probe(lambda: ledger + 1e9)
        try:
            status, doc = _http_get(serving["http"].port, "/readyz")
            assert status == 503
            assert doc["checks"]["device_mem_leak"] == 1
            assert any(r.startswith("device_mem_drift:")
                       for r in doc["reasons"])
            # recovery: drift back to zero (the probe now agrees with
            # the ledger — the REAL backend in a shared test process
            # carries other tests' arrays, so pin the probe instead
            # of dropping it) -> the drift reason clears
            monkeypatch.delenv("NORNICDB_DEVICE_MEM_DRIFT_BYTES")
            monkeypatch.delenv("NORNICDB_DEVICE_MEM_DRIFT_S")
            dev.reload()
            dev.set_backend_probe(lambda: dev.ledger_bytes())
            status, doc = _http_get(serving["http"].port, "/readyz")
            assert not any(r.startswith("device_mem_drift:")
                           for r in doc.get("reasons", []))
        finally:
            dev.set_backend_probe(None)
            dev.reload()


# ---------------------------------------------------------------------------
# overhead guard with the timing bracket ON
# ---------------------------------------------------------------------------


class TestOverheadWithSampling:
    def test_full_sampling_holds_the_overhead_budget(self):
        # the PR 3 guard, re-pinned with the ISSUE 20 bracket sampling
        # EVERY dispatch (worse than the 1/16 default): instrumented
        # stays within 2x + 1ms/op of the telemetry-off path
        assert dev.cfg()["sample_every"] == 1  # fixture pinned
        idx = BruteForceIndex()
        rng = np.random.default_rng(17)
        vecs = rng.standard_normal((512, 32)).astype(np.float32)
        idx.add_batch([(f"o{i}", vecs[i]) for i in range(512)])
        mb = MicroBatcher(idx.search_batch, surface="t-dev-overhead")
        n = 300

        def measure():
            for i in range(30):
                mb.search(vecs[i], 10)
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for i in range(n):
                    with obs.trace("wire", method="/dev-overhead"):
                        mb.search(vecs[i % 512], 10)
                best = min(best, time.perf_counter() - t0)
            return best

        t_on = measure()
        # the bracket really ran: the bucket-1 model is confident
        assert dev.predict_ms("microbatch", 1) is not None
        obs.set_enabled(False)
        try:
            t_off = measure()
        finally:
            obs.set_enabled(True)
        assert t_on <= t_off * 2.0 + n * 1e-3, (
            f"sampled bracket {t_on:.4f}s vs bare {t_off:.4f}s")
