"""Differential Cypher fuzzing: seeded random graphs + random queries
from a weighted grammar, every query executed on the production config
(fast paths + caches) AND the bare row interpreter, results diffed as
multisets. The broad net for fast-path divergences the hand-written
parity corpora don't reach (reference analog: the breadth of
pkg/cypher's generated/regression corpora).

Determinism: everything derives from the seed, so a CI failure replays
exactly with `pytest -k 'seed==N'`.
"""

import random

import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine

LABELS = ["Person", "Doc", "Org"]
REL_TYPES = ["KNOWS", "WROTE", "IN"]
PROPS = {
    "Person": [("age", "int"), ("name", "str"), ("active", "bool")],
    "Doc": [("score", "int"), ("title", "str")],
    "Org": [("size", "int"), ("name", "str")],
}


def _build_graph(rng, ex_list):
    n_nodes = rng.randrange(30, 80)
    nodes = []
    for i in range(n_nodes):
        label = rng.choice(LABELS)
        props = {"id": i}
        for pname, ptype in PROPS[label]:
            if rng.random() < 0.85:  # some nulls
                if ptype == "int":
                    props[pname] = rng.randrange(0, 20)
                elif ptype == "str":
                    props[pname] = f"{pname}{rng.randrange(8)}"
                else:
                    props[pname] = rng.random() < 0.5
        nodes.append((label, props))
        lit = ", ".join(
            f"{k}: {repr(v) if not isinstance(v, bool) else str(v).lower()}"
            for k, v in props.items())
        for ex in ex_list:
            ex.execute(f"CREATE (:{label} {{{lit}}})")
    n_edges = rng.randrange(40, 150)
    for _ in range(n_edges):
        a = rng.randrange(n_nodes)
        b = rng.randrange(n_nodes)
        t = rng.choice(REL_TYPES)
        for ex in ex_list:
            ex.execute(
                "MATCH (x {id: $a}), (y {id: $b}) "
                f"CREATE (x)-[:{t}]->(y)", {"a": a, "b": b})
    return nodes


def _gen_query(rng):
    """One random read query over the schema above."""
    label = rng.choice(LABELS)
    v = "n"
    pattern_kind = rng.random()
    vars_avail = []
    if pattern_kind < 0.45:
        pattern = f"({v}:{label})"
        vars_avail = [(v, label)]
    elif pattern_kind < 0.8:
        l2 = rng.choice(LABELS)
        t = rng.choice(REL_TYPES)
        arrow = rng.choice(["->", "<-"])
        if arrow == "->":
            pattern = f"({v}:{label})-[:{t}]->(m:{l2})"
        else:
            pattern = f"({v}:{label})<-[:{t}]-(m:{l2})"
        vars_avail = [(v, label), ("m", l2)]
    else:
        l2 = rng.choice(LABELS)
        l3 = rng.choice(LABELS)
        t1, t2 = rng.choice(REL_TYPES), rng.choice(REL_TYPES)
        pattern = (f"({v}:{label})-[:{t1}]->(m:{l2})"
                   f"{rng.choice(['-', '<-'])[:1] and ''}"
                   f"<-[:{t2}]-(o:{l3})")
        vars_avail = [(v, label), ("m", l2), ("o", l3)]

    where = ""
    if rng.random() < 0.5:
        wv, wl = rng.choice(vars_avail)
        pname, ptype = rng.choice(PROPS[wl])
        if ptype == "int":
            op = rng.choice(["=", "<>", "<", ">", "<=", ">="])
            where = f" WHERE {wv}.{pname} {op} {rng.randrange(0, 20)}"
        elif ptype == "str":
            op = rng.choice(["=", "<>"])
            where = f" WHERE {wv}.{pname} {op} '{pname}{rng.randrange(8)}'"
        else:
            where = f" WHERE {wv}.{pname} = {rng.choice(['true', 'false'])}"
    if len(vars_avail) >= 2 and rng.random() < 0.2:
        a_, b_ = vars_avail[0][0], vars_avail[1][0]
        clause = f"{a_} <> {b_}"
        where = (where + " AND " + clause) if where else (" WHERE " + clause)

    ret_kind = rng.random()
    order = ""
    if ret_kind < 0.35:
        rv, rl = rng.choice(vars_avail)
        pname, _ = rng.choice(PROPS[rl])
        distinct = "DISTINCT " if rng.random() < 0.3 else ""
        ret = f"RETURN {distinct}{rv}.{pname}"
        with_id = rng.random() < 0.5
        if with_id:
            ret += f", {rv}.id"
        # ORDER BY an unprojected key under DISTINCT has no defined
        # representative-row semantics (Neo4j rejects the shape); only
        # order by projected expressions when DISTINCT is in play
        if distinct and not with_id:
            order = (f" ORDER BY {rv}.{pname}"
                     if rng.random() < 0.4 else "")
        else:
            order = f" ORDER BY {rv}.id" if rng.random() < 0.4 else ""
    elif ret_kind < 0.6:
        ret = "RETURN count(*)"
    elif ret_kind < 0.8:
        rv, rl = rng.choice(vars_avail)
        gv, gl = vars_avail[0]
        pname, _ = rng.choice(PROPS[gl])
        agg = rng.choice([f"count({rv})", f"count(DISTINCT {rv})"])
        ret = f"RETURN {gv}.{pname}, {agg}"
    else:
        rv, rl = rng.choice(vars_avail)
        numeric = [p for p, t in PROPS[rl] if t == "int"]
        pname = numeric[0]
        fn = rng.choice(["sum", "min", "max", "avg", "count"])
        ret = f"RETURN {fn}({rv}.{pname})"

    tail = ""
    if order and rng.random() < 0.5:
        tail = f" SKIP {rng.randrange(3)} LIMIT {rng.randrange(1, 8)}"
    elif order and rng.random() < 0.5:
        tail = f" LIMIT {rng.randrange(1, 10)}"
    return f"MATCH {pattern}{where} {ret}{order}{tail}"


def _gen_advanced(rng):
    """Clause-family shapes beyond the single-MATCH core."""
    label = rng.choice(LABELS)
    l2 = rng.choice(LABELS)
    t = rng.choice(REL_TYPES)
    kind = rng.random()
    if kind < 0.25:
        return (f"MATCH (n:{label}) OPTIONAL MATCH (n)-[:{t}]->(m:{l2}) "
                f"RETURN n.id, count(m) ORDER BY n.id")
    if kind < 0.45:
        lo = 1
        hi = rng.randrange(1, 3)
        return (f"MATCH (n:{label})-[:{t}*{lo}..{max(lo, hi)}]->(m) "
                f"RETURN n.id, count(m)")
    if kind < 0.65:
        p1, _ = rng.choice(PROPS[label])
        p2, _ = rng.choice(PROPS[l2])
        return (f"MATCH (n:{label}) RETURN n.{p1} AS v "
                f"UNION MATCH (m:{l2}) RETURN m.{p2} AS v")
    if kind < 0.85:
        return (f"MATCH (n:{label})-[:{t}]->(m) WITH n, count(m) AS deg "
                f"WHERE deg >= {rng.randrange(1, 3)} "
                f"RETURN n.id, deg ORDER BY deg DESC, n.id "
                f"LIMIT {rng.randrange(1, 10)}")
    return (f"MATCH (n:{label}) WHERE (n)-[:{t}]->() "
            f"RETURN count(n)")


def _canon(result):
    def one(v):
        if isinstance(v, float):
            return round(v, 9)
        return v
    return sorted(repr([one(v) for v in row]) for row in result.rows)


@pytest.mark.parametrize("seed", list(range(16)))
def test_differential_fuzz(seed):
    rng = random.Random(1000 + seed)
    fast = CypherExecutor(NamespacedEngine(MemoryEngine(), "dz"))
    slow = CypherExecutor(NamespacedEngine(MemoryEngine(), "dz"))
    slow.enable_fastpaths = False
    slow.enable_query_cache = False
    _build_graph(rng, [fast, slow])
    for qi in range(52):
        q = _gen_query(rng) if qi % 4 else _gen_advanced(rng)
        rf = fast.execute(q)
        rs = slow.execute(q)
        assert _canon(rf) == _canon(rs), (
            f"seed={seed} query #{qi} diverged:\n  {q}\n"
            f"  fast: {_canon(rf)[:5]}\n  slow: {_canon(rs)[:5]}")


class TestFuzzFoundRegressions:
    """Divergences the differential fuzzer caught, pinned explicitly."""

    def _pair(self):
        fast = CypherExecutor(NamespacedEngine(MemoryEngine(), "fz"))
        slow = CypherExecutor(NamespacedEngine(MemoryEngine(), "fz"))
        slow.enable_fastpaths = False
        slow.enable_query_cache = False
        return fast, slow

    def test_avg_sum_ignore_nulls(self):
        """numpy astype(object->float64) maps None to nan SILENTLY; the
        one-pass _as_float conversion must audit nan slots back into
        the null mask or aggregates sum the nans."""
        fast, slow = self._pair()
        for ex in (fast, slow):
            ex.execute("CREATE (:P {age: 10})")
            ex.execute("CREATE (:P {age: 16})")
            ex.execute("CREATE (:P)")  # age is null
        for q in ("MATCH (n:P) RETURN avg(n.age)",
                  "MATCH (n:P) RETURN sum(n.age)",
                  "MATCH (n:P) RETURN min(n.age), max(n.age), count(n.age)"):
            assert fast.execute(q).rows == slow.execute(q).rows, q
        assert fast.execute("MATCH (n:P) RETURN avg(n.age)").rows == [[13.0]]

    def test_nan_property_values_still_count(self):
        """A genuine float('nan') property is a VALUE, not a null: it
        participates in count() and poisons avg — exactly like the
        interpreter."""
        fast, slow = self._pair()
        for ex in (fast, slow):
            ex.execute("CREATE (:P {age: 1.0})")
            ex.execute("CREATE (:P {age: $nan})", {"nan": float("nan")})
        q = "MATCH (n:P) RETURN count(n.age)"
        assert fast.execute(q).rows == slow.execute(q).rows == [[2]]

    def test_distinct_with_unprojected_order_key_no_crash(self):
        """RETURN DISTINCT x ORDER BY <unprojected> crashed the
        vectorized projection (DISTINCT reduced the columns, the order
        key was built over full bindings). Fast path must defer."""
        fast, _slow = self._pair()
        for i in range(6):
            fast.execute(f"CREATE (:P {{id: {i}, size: {i % 2}}})")
        r = fast.execute(
            "MATCH (n:P) RETURN DISTINCT n.size ORDER BY n.id LIMIT 2")
        assert len(r.rows) == 2

    def test_order_by_nulls_last_with_fast_conversion(self):
        fast, slow = self._pair()
        for ex in (fast, slow):
            ex.execute("CREATE (:P {id: 1, age: 5})")
            ex.execute("CREATE (:P {id: 2})")
            ex.execute("CREATE (:P {id: 3, age: 1})")
        q = "MATCH (n:P) RETURN n.id ORDER BY n.age"
        assert fast.execute(q).rows == slow.execute(q).rows == [[3], [1], [2]]


def _gen_write(rng, next_id):
    """One random write statement; next_id is a mutable counter so
    created ids never collide."""
    kind = rng.random()
    label = rng.choice(LABELS)
    if kind < 0.35:
        i = next_id[0]
        next_id[0] += 1
        pname, ptype = rng.choice(PROPS[label])
        val = (rng.randrange(20) if ptype == "int"
               else f"'{pname}{rng.randrange(8)}'" if ptype == "str"
               else rng.choice(["true", "false"]))
        return f"CREATE (:{label} {{id: {i}, {pname}: {val}}})"
    if kind < 0.55:
        t = rng.choice(REL_TYPES)
        return (f"MATCH (a {{id: {rng.randrange(40)}}}), "
                f"(b {{id: {rng.randrange(40)}}}) "
                f"CREATE (a)-[:{t}]->(b)")
    if kind < 0.75:
        pname, ptype = rng.choice(PROPS[label])
        val = (rng.randrange(20) if ptype == "int"
               else f"'{pname}{rng.randrange(8)}'" if ptype == "str"
               else rng.choice(["true", "false"]))
        return (f"MATCH (n:{label} {{id: {rng.randrange(40)}}}) "
                f"SET n.{pname} = {val}")
    if kind < 0.88:
        return (f"MATCH (n {{id: {rng.randrange(40)}}}) "
                f"DETACH DELETE n")
    t = rng.choice(REL_TYPES)
    return (f"MATCH (a {{id: {rng.randrange(40)}}})-[r:{t}]->() "
            f"DELETE r")


def _state_digest(ex):
    rows = []
    rows += _canon(ex.execute(
        "MATCH (n) RETURN labels(n), n.id, n.age, n.name, n.score, "
        "n.size, n.title, n.active"))
    rows += _canon(ex.execute(
        "MATCH (a)-[r]->(b) RETURN type(r), a.id, b.id"))
    return rows


@pytest.mark.parametrize("seed", list(range(8)))
def test_differential_write_fuzz(seed):
    """Randomized mixed write/read sessions: both engines must agree on
    every statement's rows AND the resulting graph state."""
    rng = random.Random(5000 + seed)
    fast = CypherExecutor(NamespacedEngine(MemoryEngine(), "dw"))
    slow = CypherExecutor(NamespacedEngine(MemoryEngine(), "dw"))
    slow.enable_fastpaths = False
    slow.enable_query_cache = False
    _build_graph(rng, [fast, slow])
    next_id = [10_000]
    for qi in range(120):
        if rng.random() < 0.55:
            q = _gen_write(rng, next_id)
        else:
            q = _gen_query(rng)
        rf = fast.execute(q)
        rs = slow.execute(q)
        assert _canon(rf) == _canon(rs), (
            f"seed={seed} stmt #{qi} rows diverged:\n  {q}")
        sf, ss = rf.stats, rs.stats
        assert (sf.nodes_created, sf.nodes_deleted,
                sf.relationships_created, sf.relationships_deleted,
                sf.properties_set) == \
               (ss.nodes_created, ss.nodes_deleted,
                ss.relationships_created, ss.relationships_deleted,
                ss.properties_set), (
            f"seed={seed} stmt #{qi} stats diverged:\n  {q}")
        if qi % 30 == 29:
            assert _state_digest(fast) == _state_digest(slow), (
                f"seed={seed} state diverged by stmt #{qi} after {q}")
    assert _state_digest(fast) == _state_digest(slow)


@pytest.mark.skipif(
    not __import__("os").environ.get("NORNICDB_FUZZ_EXTENDED"),
    reason="extended sweep: set NORNICDB_FUZZ_EXTENDED=1 (~60s)")
@pytest.mark.parametrize("block", [0, 1, 2, 3])
def test_differential_fuzz_extended(block):
    """Opt-in wide sweep (60+ seeds across blocks) mixing reads, writes
    and advanced clauses — run before releases / after engine changes."""
    for seed in range(200 + block * 15, 215 + block * 15):
        rng = random.Random(seed)
        fast = CypherExecutor(NamespacedEngine(MemoryEngine(), "xx"))
        slow = CypherExecutor(NamespacedEngine(MemoryEngine(), "xx"))
        slow.enable_fastpaths = False
        slow.enable_query_cache = False
        _build_graph(rng, [fast, slow])
        next_id = [10_000]
        for qi in range(60):
            r = rng.random()
            q = (_gen_write(rng, next_id) if r < 0.3
                 else _gen_advanced(rng) if r < 0.5 else _gen_query(rng))
            assert _canon(fast.execute(q)) == _canon(slow.execute(q)), (
                f"seed={seed} #{qi}: {q}")
        assert _state_digest(fast) == _state_digest(slow), f"seed={seed}"
