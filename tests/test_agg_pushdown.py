"""Aggregation-pushdown fast paths: terminal-hop degree folding and
co-occurrence incidence matmul (reference: traversal_fast_agg.go:15,57,
optimized_executors.go:25-282).

Every query here runs with fast paths on and off and must agree exactly
(up to row order). These shapes are the LDBC "avg friends per city" /
"tag co-occurrence" family — the two hardest rows in BASELINE.md.
"""

import random
import uuid

import numpy as np
import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine
from nornicdb_tpu.storage.types import Edge, Node


def _sorted_rows(result):
    return sorted(repr(r) for r in result.rows)


@pytest.fixture(scope="module")
def graph():
    eng = NamespacedEngine(MemoryEngine(), "pushdown")
    rng = random.Random(3)

    def add_node(labels, props):
        n = Node(id=str(uuid.uuid4()), labels=labels, properties=props)
        eng.create_node(n)
        return n.id

    def add_edge(etype, a, b):
        eng.create_edge(
            Edge(id=str(uuid.uuid4()), type=etype, start_node=a,
                 end_node=b, properties={})
        )

    cities = [add_node(["City"], {"name": c})
              for c in ["Oslo", "Bergen", "Pune"]]
    # one city with no residents: must not appear in grouped output
    add_node(["City"], {"name": "Ghost"})
    tags = [add_node(["Tag"], {"name": f"t{i}"}) for i in range(8)]
    # two tags sharing a name: value-grouping must merge them
    tags.append(add_node(["Tag"], {"name": "t0"}))
    # a tag with a null name: null group key
    tags.append(add_node(["Tag"], {}))
    people = [add_node(["Person"], {"id": i, "age": 20 + i})
              for i in range(30)]
    for i, pid in enumerate(people):
        add_edge("LIVES_IN", pid, cities[i % 3])
        for j in rng.sample(range(30), 4):
            if j != i:
                add_edge("KNOWS", pid, people[j])
    # one person with no KNOWS edges at all
    loner = add_node(["Person"], {"id": 99, "age": 77})
    add_edge("LIVES_IN", loner, cities[0])
    msgs = []
    for m in range(60):
        mid = add_node(["Message"], {"id": m})
        msgs.append(mid)
        for t in rng.sample(range(len(tags)), rng.randrange(1, 4)):
            add_edge("TAGGED", mid, tags[t])
    # duplicate edge: same message tagged twice with the same tag
    add_edge("TAGGED", msgs[0], tags[1])
    add_edge("TAGGED", msgs[0], tags[1])
    return eng


def _both(graph, query, params=None):
    fast = CypherExecutor(graph)
    fast.enable_query_cache = False
    slow = CypherExecutor(graph)
    slow.enable_query_cache = False
    slow.enable_fastpaths = False
    rf = fast.execute(query, params or {})
    rs = slow.execute(query, params or {})
    assert rf.columns == rs.columns
    assert _sorted_rows(rf) == _sorted_rows(rs)
    return rf


PUSHDOWN_CORPUS = [
    # terminal-hop count -> degree fold
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f:Person) "
    "RETURN c.name, count(f)",
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f:Person) "
    "RETURN c.name, count(f) / count(DISTINCT p) AS avg",
    # anonymous terminal node
    "MATCH (p:Person)-[:KNOWS]->(:Person) RETURN count(*)",
    # unlabeled terminal node (unfiltered degree)
    "MATCH (p:Person)-[:KNOWS]->(x) RETURN p.id, count(x)",
    # terminal hop inbound
    "MATCH (p:Person)<-[:KNOWS]-(f:Person) RETURN p.id, count(f)",
    # weighted sum/avg over a non-stripped column
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f) "
    "RETURN c.name, sum(p.age), count(f)",
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f) "
    "RETURN c.name, avg(p.age)",
    # min/max are multiplicity-insensitive but ride the weighted path
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f) "
    "RETURN c.name, min(p.age), max(p.age)",
    # global aggregation (no group keys) with stripped tail
    "MATCH (p:Person)-[:KNOWS]->(f:Person) RETURN count(f)",
    # ORDER BY over aggregated output
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f:Person) "
    "RETURN c.name, count(f) AS k ORDER BY k DESC",
    # NOT strippable: terminal var projected -> general/chain path parity
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f:Person) "
    "RETURN c.name, count(f.age)",
    # NOT strippable: count(DISTINCT f)
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f:Person) "
    "RETURN c.name, count(DISTINCT f)",
    # NOT strippable: terminal var in WHERE
    "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f:Person) "
    "WHERE f.age > 30 RETURN c.name, count(f)",
]

COOC_CORPUS = [
    # flagship co-occurrence (duplicate-name tags merge; null-name tag
    # groups; duplicate edges feed the same-edge correction)
    "MATCH (t1:Tag)<-[:TAGGED]-(m:Message)-[:TAGGED]->(t2:Tag) "
    "WHERE t1 <> t2 RETURN t1.name, t2.name, count(m) AS freq",
    # without the inequality: diagonal pairs from duplicate edges remain
    "MATCH (t1:Tag)<-[:TAGGED]-(m)-[:TAGGED]->(t2:Tag) "
    "RETURN t1.name, t2.name, count(m)",
    # unlabeled middle
    "MATCH (t1:Tag)<-[:TAGGED]-(x)-[:TAGGED]->(t2:Tag) "
    "WHERE t1 <> t2 RETURN t1.name, t2.name, count(*)",
    # reversed orientation (ends point at middle)
    "MATCH (m1:Message)-[:TAGGED]->(t:Tag)<-[:TAGGED]-(m2:Message) "
    "WHERE m1 <> m2 RETURN count(*)",
    # grouping by only one endpoint (rows-are-groups must NOT trigger)
    "MATCH (t1:Tag)<-[:TAGGED]-(m)-[:TAGGED]->(t2:Tag) "
    "WHERE t1 <> t2 RETURN t1.name, count(m)",
    # node-identity group keys
    "MATCH (t1:Tag)<-[:TAGGED]-(m)-[:TAGGED]->(t2:Tag) "
    "RETURN t1, t2, count(m)",
    # ORDER BY / LIMIT over pairs (total order so LIMIT is deterministic)
    "MATCH (t1:Tag)<-[:TAGGED]-(m)-[:TAGGED]->(t2:Tag) "
    "WHERE t1 <> t2 AND t1.name IS NOT NULL AND t2.name IS NOT NULL "
    "RETURN t1.name AS a, t2.name AS b, count(m) AS freq "
    "ORDER BY freq DESC, a, b LIMIT 5",
]


@pytest.mark.parametrize("query", PUSHDOWN_CORPUS)
def test_pushdown_parity(graph, query):
    _both(graph, query)


@pytest.mark.parametrize("query", COOC_CORPUS)
def test_cooccurrence_parity(graph, query):
    _both(graph, query)


def test_pushdown_actually_triggers(graph):
    """The two flagship shapes must not silently fall back."""
    from nornicdb_tpu.query import fastpaths
    from nornicdb_tpu.query.parser import parse

    q = parse(
        "MATCH (c:City)<-[:LIVES_IN]-(p:Person)-[:KNOWS]->(f:Person) "
        "RETURN c.name, count(f)"
    ).parts[0]
    plan = fastpaths._analyze_vectorized(q)
    assert plan is not None and plan["strip"] is not None

    q2 = parse(
        "MATCH (t1:Tag)<-[:TAGGED]-(m)-[:TAGGED]->(t2:Tag) "
        "WHERE t1 <> t2 RETURN t1.name, t2.name, count(m)"
    ).parts[0]
    plan2 = fastpaths._analyze_vectorized(q2)
    assert plan2 is not None and plan2["cooc"] is not None


def test_filtered_degree_index(graph):
    from nornicdb_tpu.query.columnar import ColumnarCatalog

    cat = ColumnarCatalog(graph)
    deg = cat.filtered_degree("KNOWS", "out", "Person")
    nodes = cat.nodes()
    for row in range(len(nodes)):
        n = nodes[row]
        if "Person" not in n.labels:
            continue
        expect = sum(
            1 for e in graph.get_node_edges(n.id, direction="out")
            if e.type == "KNOWS" and e.start_node == n.id
        )
        assert deg[row] == expect, n.properties


def test_pushdown_sees_writes(graph_factory=None):
    """Degree/incidence caches must invalidate on mutation."""
    eng = NamespacedEngine(MemoryEngine(), "inv")
    ex = CypherExecutor(eng)
    ex.enable_query_cache = False
    ex.execute("CREATE (:P {id: 1})-[:R]->(:Q)")
    q = "MATCH (p:P)-[:R]->(x:Q) RETURN p.id, count(x)"
    assert ex.execute(q).rows == [[1, 1]]
    ex.execute("MATCH (p:P {id: 1}) CREATE (p)-[:R]->(:Q)")
    assert ex.execute(q).rows == [[1, 2]]
    ex.execute("CREATE (:P {id: 2})-[:R]->(:Q)")
    assert _sorted_rows(ex.execute(q)) == sorted(
        [repr([1, 2]), repr([2, 1])]
    )


def test_lazy_result_contract():
    """Column-major CypherResult: rows materialize lazily and are safe
    to mutate per consumer; cache hits share columns, not rows."""
    from nornicdb_tpu.query.executor import CypherResult

    r = CypherResult(columns=["a", "b"], col_data=[[1, 2], ["x", "y"]])
    assert r.n_rows == 2
    assert r.col_values(1) == ["x", "y"]
    rows = r.rows
    assert rows == [[1, "x"], [2, "y"]]
    rows[0][0] = 99  # mutation sticks to this materialization
    assert r.rows[0][0] == 99

    # cache round-trip: hits see original values, not a prior consumer's
    # mutations
    eng = NamespacedEngine(MemoryEngine(), "lazy")
    ex = CypherExecutor(eng)
    ex.execute("CREATE (:T {v: 1}), (:T {v: 2})")
    q = "MATCH (t:T) RETURN t.v ORDER BY t.v"
    r1 = ex.execute(q)
    assert r1.rows == [[1], [2]]
    r1.rows[0][0] = 42
    r2 = ex.execute(q)  # cache hit
    assert r2.rows == [[1], [2]]


def test_union_all_with_columnar_parts():
    """UNION ALL merges parts by extending rows in place; a column-major
    first part must not replay only its own rows on cache hits
    (regression: stale _col_data shadowing merged rows)."""
    eng = NamespacedEngine(MemoryEngine(), "union")
    ex = CypherExecutor(eng)
    ex.execute("CREATE (:A {v: 1})")
    ex.execute("CREATE (:B {v: 2})")
    q = ("MATCH (a:A) RETURN a.v AS v "
         "UNION ALL MATCH (b:B) RETURN b.v AS v")
    r1 = ex.execute(q)
    assert sorted(r1.rows) == [[1], [2]]
    assert sorted([r1.col_values(0)[i] for i in range(r1.n_rows)]) == [1, 2]
    r2 = ex.execute(q)  # cache hit must carry both parts
    assert sorted(r2.rows) == [[1], [2]]
