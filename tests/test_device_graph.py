"""Device graph plane (ISSUE 9): parity corpus + freshness ladder.

Contract under test: every LDBC fast-path shape served through
query/device_graph.py is ROW-IDENTICAL to the host executor, and every
freshness/degrade rung (mutation mid-batch, catalog invalidation,
env-gate-off, guard trips) lands on the host path — never a wrong
answer. Plus: the device-built strip/gram views are bit-identical to
the host builds, the fused traverse-rank program matches its host
reference, coalesced chain reads share one dispatch, and the shared
PageRank snapshot is bit-identical and actually cached.
"""

import os
import random
import threading

import numpy as np
import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine
from nornicdb_tpu.storage.types import Edge, Node


def _sorted_rows(result):
    return sorted([repr(r) for r in result.rows])


@pytest.fixture()
def mode():
    """Restore the device-gate env after each test."""
    prev = {k: os.environ.get(k) for k in (
        "NORNICDB_GRAPH_DEVICE", "NORNICDB_GRAPH_DEVICE_MIN_N",
        "NORNICDB_GRAPH_DEVICE_MIN_B")}

    def set_mode(value, **extra):
        os.environ["NORNICDB_GRAPH_DEVICE"] = value
        for k, v in extra.items():
            os.environ[f"NORNICDB_GRAPH_DEVICE_{k}"] = str(v)

    yield set_mode
    for k, v in prev.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _build_graph(n_people=50, n_msgs=110, knows=4, seed=7):
    eng = NamespacedEngine(MemoryEngine(), "t")
    rng = random.Random(seed)
    cities = ["Oslo", "Bergen", "Pune", "Kyoto"]
    tags = ["ai", "tpu", "graphs", "jax"]
    for c in cities:
        eng.create_node(Node(id=f"c_{c}", labels=["City"],
                             properties={"name": c}))
    for t in tags:
        eng.create_node(Node(id=f"t_{t}", labels=["Tag"],
                             properties={"name": t}))
    for i in range(n_people):
        eng.create_node(Node(
            id=f"p{i}", labels=["Person"],
            properties={"id": i, "name": f"p{i}", "age": 18 + (i * 7) % 50}))
    eid = iter(range(10 ** 9))
    for i in range(n_people):
        eng.create_edge(Edge(id=f"e{next(eid)}", type="IS_LOCATED_IN",
                             start_node=f"p{i}",
                             end_node=f"c_{cities[i % len(cities)]}",
                             properties={}))
        for j in rng.sample(range(n_people), knows):
            if j != i:
                eng.create_edge(Edge(id=f"e{next(eid)}", type="KNOWS",
                                     start_node=f"p{i}", end_node=f"p{j}",
                                     properties={}))
    for m in range(n_msgs):
        props = {"id": 1000 + m, "content": f"message {m}"}
        if m < n_msgs - 3:  # three undated: null-first DESC order rung
            # deliberate key ties (ts repeats every 10 messages): the
            # device merge must reproduce the host's stable tie order
            props["creationDate"] = 1700000000 + (m % 10) * 37
        eng.create_node(Node(id=f"m{m}", labels=["Message"],
                             properties=props))
        eng.create_edge(Edge(id=f"e{next(eid)}", type="HAS_CREATOR",
                             start_node=f"m{m}",
                             end_node=f"p{rng.randrange(n_people)}",
                             properties={}))
        for t in rng.sample(tags, rng.randrange(1, 3)):
            eng.create_edge(Edge(id=f"e{next(eid)}", type="HAS_TAG",
                                 start_node=f"m{m}", end_node=f"t_{t}",
                                 properties={}))
    return eng


def _ex(eng):
    ex = CypherExecutor(eng)
    ex.enable_query_cache = False
    return ex


Q_CHAIN = ("MATCH (p:Person {id: $pid})-[:KNOWS]->(f:Person)"
           "<-[:HAS_CREATOR]-(m:Message) "
           "RETURN f.name, m.content, m.creationDate "
           "ORDER BY m.creationDate DESC ")
Q_STRIP = ("MATCH (c:City)<-[:IS_LOCATED_IN]-(p:Person)-[:KNOWS]->"
           "(f:Person) RETURN c.name, "
           "count(f) / count(DISTINCT p) AS avgFriends")
Q_COOC = ("MATCH (t1:Tag)<-[:HAS_TAG]-(m:Message)-[:HAS_TAG]->(t2:Tag) "
          "WHERE t1 <> t2 RETURN t1.name, t2.name, count(m) AS freq")


class TestChainTopkParity:
    """Row/rank-identical device vs host across the chain family."""

    def test_param_and_limit_sweep(self, mode):
        eng = _build_graph()
        mode("off")
        ex_h = _ex(eng)
        mode("on")
        ex_d = _ex(eng)
        cases = []
        for pid in (0, 3, 17, 29, 49):
            for tail in ("LIMIT 10", "LIMIT 1", "LIMIT 3",
                         "SKIP 2 LIMIT 5", "LIMIT 1000"):
                cases.append((Q_CHAIN + tail, {"pid": pid}))
        cases.append((Q_CHAIN + "LIMIT 10", {"pid": 10 ** 9}))  # no anchor
        for q, params in cases:
            mode("off")
            want = ex_h.execute(q, params)
            mode("on")
            got = ex_d.execute(q, params)
            assert got.columns == want.columns, (q, params)
            assert got.rows == want.rows, (q, params)
        assert ex_d.device_graph.dispatches > 0  # parity isn't vacuous

    def test_empty_frontier_and_dangling_label(self, mode):
        eng = _build_graph()
        # a person with no KNOWS edges at all
        eng.create_node(Node(id="p_lonely", labels=["Person"],
                             properties={"id": 7777, "name": "lonely"}))
        mode("on")
        ex_d = _ex(eng)
        assert ex_d.execute(Q_CHAIN + "LIMIT 5", {"pid": 7777}).rows == []
        # dangling mid label: no Ghost nodes exist anywhere
        q = ("MATCH (p:Person {id: $pid})-[:KNOWS]->(f:Ghost)"
             "<-[:HAS_CREATOR]-(m:Message) RETURN m.content "
             "ORDER BY m.creationDate DESC LIMIT 5")
        mode("off")
        want = _ex(eng).execute(q, {"pid": 0})
        mode("on")
        assert ex_d.execute(q, {"pid": 0}).rows == want.rows == []

    def test_multi_hit_anchor_stays_host(self, mode):
        eng = _build_graph()
        # duplicate anchor key: two persons share id 0
        eng.create_node(Node(id="p_dup", labels=["Person"],
                             properties={"id": 0, "name": "dup"}))
        mode("off")
        want = _ex(eng).execute(Q_CHAIN + "LIMIT 10", {"pid": 0})
        mode("on")
        ex_d = _ex(eng)
        got = ex_d.execute(Q_CHAIN + "LIMIT 10", {"pid": 0})
        assert got.rows == want.rows
        assert ex_d.device_graph.dispatches == 0  # multi-anchor: host

    def test_coalesced_concurrent_reads_share_dispatches(self, mode):
        eng = _build_graph()
        mode("off")
        ex_h = _ex(eng)
        expected = {pid: ex_h.execute(Q_CHAIN + "LIMIT 10",
                                      {"pid": pid}).rows
                    for pid in range(20)}
        mode("on")
        ex_d = _ex(eng)
        ex_d.execute(Q_CHAIN + "LIMIT 10", {"pid": 0})  # warm snapshot
        results = {}
        errors = []

        def worker(pid):
            try:
                results[pid] = ex_d.execute(Q_CHAIN + "LIMIT 10",
                                            {"pid": pid}).rows
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(pid,))
                   for pid in range(20)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for pid in range(20):
            assert results[pid] == expected[pid], pid
        batcher = next(
            (b for k, b in ex_d.device_graph._batchers.items()
             if k[0] == "chainb"), None)
        assert batcher is not None
        assert batcher.batched_items >= 20


class TestChainFreshnessLadder:
    """Every rung serves correct answers; degrades land on host."""

    def test_write_visible_immediately(self, mode):
        eng = _build_graph()
        mode("on")
        ex_d = _ex(eng)
        before = ex_d.execute(Q_CHAIN + "LIMIT 5", {"pid": 0}).rows
        assert before
        # a brand-new newest message from one of p0's friends
        friend = None
        for row in ex_d.execute(
                "MATCH (p:Person {id: 0})-[:KNOWS]->(f:Person) "
                "RETURN f.name", {}).rows:
            friend = row[0]
            break
        assert friend is not None
        ex_d.execute(
            "MATCH (f:Person {name: $n}) "
            "CREATE (m:Message {id: 999999, content: 'fresh', "
            "creationDate: 1900000000})-[:HAS_CREATOR]->(f)",
            {"n": friend})
        after = ex_d.execute(Q_CHAIN + "LIMIT 5", {"pid": 0}).rows
        # nulls order first under DESC; "fresh" carries the highest
        # real date, so it must appear in the head
        assert any(row[1] == "fresh" for row in after)
        mode("off")
        assert _ex(eng).execute(Q_CHAIN + "LIMIT 5",
                                {"pid": 0}).rows == after

    def test_invalidation_and_delete(self, mode):
        eng = _build_graph()
        mode("on")
        ex_d = _ex(eng)
        ex_d.execute(Q_CHAIN + "LIMIT 10", {"pid": 1})
        # update a message property -> wholesale invalidation
        ex_d.execute("MATCH (m:Message {id: 1000}) "
                     "SET m.creationDate = 1950000000", {})
        got = ex_d.execute(Q_CHAIN + "LIMIT 10", {"pid": 1})
        mode("off")
        want = _ex(eng).execute(Q_CHAIN + "LIMIT 10", {"pid": 1})
        assert got.rows == want.rows
        mode("on")
        ex_d.execute("MATCH (m:Message {id: 1000}) DETACH DELETE m", {})
        got2 = ex_d.execute(Q_CHAIN + "LIMIT 10", {"pid": 1})
        mode("off")
        want2 = _ex(eng).execute(Q_CHAIN + "LIMIT 10", {"pid": 1})
        assert got2.rows == want2.rows

    def test_mutation_mid_batch_degrades_to_host(self, mode, monkeypatch):
        """A write landing INSIDE the dispatch window: the post-dispatch
        version check must throw the device result away and serve host."""
        import nornicdb_tpu.query.device_graph as dg

        eng = _build_graph()
        mode("on")
        ex_d = _ex(eng)
        ex_d.execute(Q_CHAIN + "LIMIT 5", {"pid": 2})  # warm snapshot
        real_fn = dg._chain_topk_fn
        fired = {}

        def racing_fn(f, kp):
            impl = real_fn(f, kp)

            def wrapper(*args):
                if "done" not in fired:
                    fired["done"] = True
                    # the race: a create lands while the program runs
                    eng.create_node(Node(id="race_node",
                                         labels=["Person"],
                                         properties={"id": 55555}))
                    ex_d.columnar.apply_node_created(
                        eng.get_node("race_node"))
                return impl(*args)

            return wrapper

        monkeypatch.setattr(dg, "_chain_topk_fn", racing_fn)
        got = ex_d.execute(Q_CHAIN + "LIMIT 5", {"pid": 2})
        monkeypatch.setattr(dg, "_chain_topk_fn", real_fn)
        mode("off")
        want = _ex(eng).execute(Q_CHAIN + "LIMIT 5", {"pid": 2})
        assert got.rows == want.rows
        assert fired.get("done")

    def test_env_gate_off_never_dispatches(self, mode):
        eng = _build_graph()
        mode("off")
        ex = _ex(eng)
        for pid in range(5):
            ex.execute(Q_CHAIN + "LIMIT 10", {"pid": pid})
        assert ex.device_graph.dispatches == 0

    def test_auto_single_stream_stays_host(self, mode):
        """auto mode: a lone reader never pays a b=1 dispatch, even on
        an eligible catalog (the demand gate)."""
        eng = _build_graph()
        mode("auto", MIN_N="1", MIN_B="2")
        ex = _ex(eng)
        for pid in range(5):
            ex.execute(Q_CHAIN + "LIMIT 10", {"pid": pid})
        assert ex.device_graph.dispatches == 0


class TestStripAndGramBuilds:
    """Device-built views bit-identical to the host builds."""

    def _strip_args(self):
        return ("IS_LOCATED_IN", "dst", "Person", "KNOWS", "out",
                "Person")

    def test_strip_arrays_bit_identical(self, mode):
        eng = _build_graph()
        # parallel edges: duplicate (g, p) membership, the DISTINCT rung
        eng.create_edge(Edge(id="dup1", type="IS_LOCATED_IN",
                             start_node="p0", end_node="c_Oslo",
                             properties={}))
        mode("off")
        ex_h = _ex(eng)
        host_sv = ex_h.columnar.strip_view(*self._strip_args())
        mode("on")
        ex_d = _ex(eng)
        dev_sv = ex_d.device_graph.build_strip_view(*self._strip_args())
        assert dev_sv is not None
        assert np.array_equal(host_sv.deg, dev_sv.deg)
        assert np.array_equal(host_sv.sum_deg, dev_sv.sum_deg)
        assert np.array_equal(host_sv.nnz, dev_sv.nnz)
        assert dev_sv.deg.dtype == host_sv.deg.dtype == np.int64

    def test_strip_label_none_variants(self, mode):
        eng = _build_graph()
        for args in (("IS_LOCATED_IN", "dst", None, "KNOWS", "out", None),
                     ("HAS_CREATOR", "dst", "Person", "IS_LOCATED_IN",
                      "out", "City")):
            mode("off")
            host_sv = _ex(eng).columnar.strip_view(*args)
            mode("on")
            ex_d = _ex(eng)
            dev_sv = ex_d.device_graph.build_strip_view(*args)
            assert dev_sv is not None, args
            assert np.array_equal(host_sv.sum_deg, dev_sv.sum_deg), args
            assert np.array_equal(host_sv.nnz, dev_sv.nnz), args

    def test_strip_query_parity_and_maintenance(self, mode):
        eng = _build_graph()
        mode("off")
        want = _ex(eng).execute(Q_STRIP)
        mode("on")
        ex_d = _ex(eng)
        got = ex_d.execute(Q_STRIP)
        assert _sorted_rows(got) == _sorted_rows(want)
        # the installed view must ride the catalog's incremental
        # maintenance exactly like a host-built one
        ex_d.execute(
            "MATCH (a:Person {id: 0}), (b:Person {id: 49}) "
            "CREATE (a)-[:KNOWS]->(b)", {})
        mode("off")
        want2 = _ex(eng).execute(Q_STRIP)
        mode("on")
        got2 = ex_d.execute(Q_STRIP)
        assert _sorted_rows(got2) == _sorted_rows(want2)

    def test_gram_bit_identical_and_query_parity(self, mode):
        eng = _build_graph()
        key = ("HAS_TAG", "mid_src", "Message", "Tag", "Tag")
        mode("off")
        ex_h = _ex(eng)
        host_gram = ex_h.columnar.cooc_gram(*key)
        mode("on")
        ex_d = _ex(eng)
        dev_gram = ex_d.columnar.cooc_gram(
            *key, device_plane=ex_d.device_graph)
        assert host_gram is not None and dev_gram is not None
        assert np.array_equal(host_gram.C, dev_gram.C)
        got = ex_d.execute(Q_COOC)
        mode("off")
        want = _ex(eng).execute(Q_COOC)
        assert _sorted_rows(got) == _sorted_rows(want)

    def test_exactness_guard_degrades(self, mode):
        """Structures past the f32-exactness bound refuse the device
        build (host serves) instead of risking inexact counts."""
        import nornicdb_tpu.query.device_graph as dg

        eng = _build_graph()
        mode("on")
        ex = _ex(eng)
        plane = ex.device_graph
        orig = dg._EXACT_F32
        try:
            dg._EXACT_F32 = 1.0  # force the guard
            assert plane.build_strip_view(*self._strip_args()) is None
        finally:
            dg._EXACT_F32 = orig
        # query still answers correctly through the host build
        mode("off")
        want = _ex(eng).execute(Q_STRIP)
        mode("on")
        assert _sorted_rows(ex.execute(Q_STRIP)) == _sorted_rows(want)


class TestTraverseRank:
    def _setup(self, mode_fn, with_vectors=True):
        from nornicdb_tpu.search.vector_index import BruteForceIndex

        eng = _build_graph(n_people=30, n_msgs=60)
        mode_fn("on")
        ex = _ex(eng)
        cat = ex.columnar
        rng = np.random.default_rng(5)
        index = BruteForceIndex(use_device=True)
        if with_vectors:
            rows = cat.label_rows("Message")
            nodes = cat.nodes()
            ids = [nodes[int(r)].id for r in rows]
            vecs = rng.normal(size=(len(ids), 24)).astype(np.float32)
            index.add_batch(list(zip(ids, vecs)))
        return eng, ex, index, rng

    def test_device_matches_host(self, mode):
        eng, ex, index, rng = self._setup(mode)
        plane = ex.device_graph
        cat = ex.columnar
        hops = [("KNOWS", "out"), ("HAS_CREATOR", "in")]
        anchors = [int(cat.node_row(f"p{i}")) for i in (0, 3, 9, 21)]
        q = rng.normal(size=(len(anchors), 24)).astype(np.float32)
        dev = plane.traverse_rank(anchors, hops, q, 7, index)
        host = plane.traverse_rank_host(anchors, hops, q, 7, index)
        assert dev is not None
        for d, h in zip(dev, host):
            assert [r for r, _s in d] == [r for r, _s in h]
            assert np.allclose([s for _r, s in d], [s for _r, s in h],
                               atol=1e-5)

    def test_one_hop_and_empty_frontier(self, mode):
        eng, ex, index, rng = self._setup(mode)
        plane = ex.device_graph
        cat = ex.columnar
        q = rng.normal(size=(1, 24)).astype(np.float32)
        # 1-hop from a message to its creator: Person has no vector ->
        # frontier exists but nothing rankable
        m_row = int(cat.node_row("m0"))
        dev = plane.traverse_rank([m_row], [("HAS_CREATOR", "out")], q, 5,
                                  index)
        assert dev is not None and dev[0] == []
        # empty frontier: a node with no outgoing KNOWS
        eng.create_node(Node(id="iso", labels=["Person"],
                             properties={"id": 424242}))
        ex.invalidate_caches()
        iso_row = int(ex.columnar.node_row("iso"))
        dev2 = plane.traverse_rank(
            [iso_row], [("KNOWS", "out"), ("HAS_CREATOR", "in")], q, 5,
            index)
        assert dev2 is not None and dev2[0] == []

    def test_index_mutation_resnapshots(self, mode):
        eng, ex, index, rng = self._setup(mode)
        plane = ex.device_graph
        cat = ex.columnar
        hops = [("KNOWS", "out"), ("HAS_CREATOR", "in")]
        a = [int(cat.node_row("p0"))]
        q = rng.normal(size=(1, 24)).astype(np.float32)
        first = plane.traverse_rank(a, hops, q, 5, index)
        assert first is not None
        # overwrite one frontier vector with the query itself: it must
        # win the rank on the NEXT call (mutation-keyed snapshot)
        target_row = None
        host = plane.traverse_rank_host(a, hops, q, 50, index)
        assert host[0]
        target_row = host[0][-1][0]
        target_id = cat.nodes()[target_row].id
        index.add(target_id, q[0])
        dev = plane.traverse_rank(a, hops, q, 5, index)
        host2 = plane.traverse_rank_host(a, hops, q, 5, index)
        assert dev is not None
        assert [r for r, _s in dev[0]] == [r for r, _s in host2[0]]
        assert dev[0][0][0] == target_row

    def test_gate_off_returns_none(self, mode):
        eng, ex, index, rng = self._setup(mode)
        mode("off")
        q = rng.normal(size=(1, 24)).astype(np.float32)
        a = [int(ex.columnar.node_row("p0"))]
        assert ex.device_graph.traverse_rank(
            a, [("KNOWS", "out")], q, 5, index) is None

    def test_db_service_surface(self, mode):
        from nornicdb_tpu.db import DB

        mode("on")
        db = DB()
        rng = np.random.default_rng(1)
        for i in range(8):
            db.store(f"n{i}", labels=["Person"], properties={"pid": i},
                     node_id=f"p{i}",
                     embedding=rng.normal(size=12).tolist())
        for i in range(8):
            db.link(f"p{i}", f"p{(i + 1) % 8}", "KNOWS")
        q = rng.normal(size=12).tolist()
        hits = db.graph_vector_search("p0", ["KNOWS"], q, k=3)
        assert hits and hits[0][0] == "p1"
        mode("off")
        assert db.graph_vector_search("p0", ["KNOWS"], q, k=3) == hits
        mode("on")
        # explicit-embedding store AFTER the search service exists must
        # still be rankable (the embed queue skips embedded nodes; the
        # store path indexes them directly)
        db.store("late", labels=["Person"], properties={"pid": 99},
                 node_id="p_late", embedding=q)
        db.link("p0", "p_late", "KNOWS")
        hits2 = db.graph_vector_search("p0", ["KNOWS"], q, k=3)
        assert hits2[0][0] == "p_late"
        with pytest.raises(ValueError):
            db.graph_vector_search("p0", [], q)
        assert db.graph_vector_search("missing", ["KNOWS"], q) == []


class TestPageRankSnapshot:
    def test_bit_identical_and_cached(self, mode):
        from nornicdb_tpu.ops.graph import pagerank_engine

        eng = _build_graph(n_people=25, n_msgs=30)
        mode("on")
        ex = _ex(eng)
        plane = ex.device_graph
        base = pagerank_engine(eng)
        via_plane = pagerank_engine(eng, plane=plane)
        assert base == via_plane  # bit-identical, same snapshot recipe
        snap1 = plane.pagerank_snapshot()
        snap2 = plane.pagerank_snapshot()
        assert snap1 is snap2  # cached: no per-call rebuild/re-ship
        # a write moves the catalog version -> fresh snapshot
        ex.execute("CREATE (:Person {id: 909090})")
        snap3 = plane.pagerank_snapshot()
        assert snap3 is not snap1
        assert len(snap3["ids"]) == len(snap1["ids"]) + 1

    def test_degree_counts_matches_ops(self, mode):
        from nornicdb_tpu.ops.graph import degree_counts, graph_snapshot

        eng = _build_graph(n_people=20, n_msgs=20)
        mode("on")
        plane = _ex(eng).device_graph
        out_d, in_d = plane.degree_counts()
        src, dst, ids = graph_snapshot(eng)
        ref_o, ref_i = degree_counts(src, dst, len(ids))
        assert np.array_equal(out_d, np.asarray(ref_o))
        assert np.array_equal(in_d, np.asarray(ref_i))


class TestObsWiring:
    def test_cost_and_dispatch_accounting(self, mode):
        from nornicdb_tpu import obs
        from nornicdb_tpu.obs.cost import cost_summary

        eng = _build_graph()
        mode("on")
        ex = _ex(eng)
        for pid in range(3):
            ex.execute(Q_CHAIN + "LIMIT 10", {"pid": pid})
        ex.execute(Q_STRIP)
        ex.execute(Q_COOC)
        kinds = {e["kind"] for e in obs.compile_universe()}
        assert {"graph_chain_topk", "graph_strip_agg",
                "graph_cooc_gram"} <= kinds
        rows = {(r["kind"], r["index"]): r for r in cost_summary()}
        chain = next((r for (k, _i), r in rows.items()
                      if k == "graph_chain_topk"), None)
        assert chain is not None
        assert chain["queries"] >= 3  # REAL query counts, not batches
        assert chain["flops_per_query"] > 0

    def test_resource_stats_and_gap(self, mode):
        eng = _build_graph()
        mode("on")
        ex = _ex(eng)
        ex.execute(Q_CHAIN + "LIMIT 10", {"pid": 0})
        stats = ex.device_graph.resource_stats()
        assert stats["device_bytes"] > 0
        assert stats["rows"] > 0
        assert stats["mutation_gap"] == 0
        ex.execute("CREATE (:Person {id: 777777})")
        assert ex.device_graph.resource_stats()["mutation_gap"] >= 1

    def test_gauges_exported(self, mode):
        from nornicdb_tpu import obs
        from nornicdb_tpu.obs.metrics import REGISTRY
        from nornicdb_tpu.obs.resources import update_gauges

        eng = _build_graph()
        mode("on")
        ex = _ex(eng)
        ex.execute(Q_CHAIN + "LIMIT 10", {"pid": 0})
        update_gauges()
        fam = REGISTRY.get("nornicdb_index_device_bytes")
        assert fam is not None
        keys = [k for k in fam.children() if k[0] == "device_graph"]
        assert keys, "device_graph family missing from resource gauges"

    def test_declared_kinds_present_before_traffic(self):
        from nornicdb_tpu.obs.dispatch import bucket_counts

        counts = bucket_counts()
        for kind in ("graph_chain_topk", "graph_strip_agg",
                     "graph_cooc_gram", "graph_traverse_rank"):
            assert kind in counts


class TestSentinelGraphGates:
    def test_parity_floor_and_extraction(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_sentinel",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts",
                "bench_sentinel.py"))
        bs = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bs)
        full = {
            "metric": "ldbc_snb_cypher_geomean", "value": 9000.0,
            "cypher": {"device_graph": {
                "parity": 1.0,
                "recent_messages_friends": {
                    "concurrent_device_qps": 3000.0},
                "traverse_rank": {"device_qps_b16": 12000.0},
                "compile_buckets": 7,
            }},
        }
        m = bs.extract_metrics(full)
        assert m["ldbc_device_parity"] == 1.0
        assert m["graph_chain_conc_qps"] == 3000.0
        assert m["graph_traverse_rank_qps"] == 12000.0
        assert m["graph_compile_buckets"] == 7
        # parity gates ABSOLUTELY (no baseline needed); 0.9 must flag
        broken = dict(m, ldbc_device_parity=0.9)
        verdict = bs.compare(broken, {})
        flagged = {f["metric"] for f in verdict["flagged"]}
        assert "ldbc_device_parity" in flagged
        assert bs.compare(m, {})["verdict"] == "pass"
        # compile-bucket growth past baseline + 2 flags
        grown = dict(m, graph_compile_buckets=10)
        verdict2 = bs.compare(grown, {"graph_compile_buckets": 7})
        assert {f["metric"] for f in verdict2["flagged"]} == {
            "graph_compile_buckets"}
