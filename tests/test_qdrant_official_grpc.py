"""E2E over the OFFICIAL qdrant gRPC wire contract (VERDICT r1 item 5;
reference: pkg/qdrantgrpc/COMPAT.md, qdrant_official_e2e_test.go).

The qdrant-client SDK is not installed in this image, so the client side
here is raw grpc + the generated qdrant_pb2 messages — i.e. exactly the
bytes an official SDK emits: `/qdrant.Points/Upsert` etc. with upstream
field numbers.
"""

import grpc
import pytest

import nornicdb_tpu
from nornicdb_tpu.api.grpc_server import GrpcServer
from nornicdb_tpu.api.proto import qdrant_pb2 as q


@pytest.fixture(scope="module")
def server():
    db = nornicdb_tpu.open(auto_embed=False)
    srv = GrpcServer(db, port=0).start()
    yield srv
    srv.stop()
    db.close()


@pytest.fixture(scope="module")
def channel(server):
    ch = grpc.insecure_channel(server.address)
    yield ch
    ch.close()


def _call(channel, method, request, response_cls):
    fn = channel.unary_unary(
        method,
        request_serializer=lambda r: r.SerializeToString(),
        response_deserializer=response_cls.FromString,
    )
    return fn(request)


class TestOfficialContract:
    def test_create_list_get_collection(self, channel):
        req = q.CreateCollection(collection_name="off1")
        req.vectors_config.params.size = 4
        req.vectors_config.params.distance = q.Cosine
        resp = _call(channel, "/qdrant.Collections/Create", req,
                     q.CollectionOperationResponse)
        assert resp.result is True

        resp = _call(channel, "/qdrant.Collections/List",
                     q.ListCollectionsRequest(), q.ListCollectionsResponse)
        assert "off1" in [c.name for c in resp.collections]

        resp = _call(channel, "/qdrant.Collections/Get",
                     q.GetCollectionInfoRequest(collection_name="off1"),
                     q.GetCollectionInfoResponse)
        assert resp.result.status == q.Green
        params = resp.result.config.params.vectors_config.params
        assert params.size == 4
        assert params.distance == q.Cosine

        resp = _call(channel, "/qdrant.Collections/CollectionExists",
                     q.CollectionExistsRequest(collection_name="off1"),
                     q.CollectionExistsResponse)
        assert resp.result.exists is True

    def test_upsert_search_get_roundtrip(self, channel):
        req = q.CreateCollection(collection_name="off2")
        req.vectors_config.params.size = 3
        req.vectors_config.params.distance = q.Cosine
        _call(channel, "/qdrant.Collections/Create", req,
              q.CollectionOperationResponse)

        up = q.UpsertPoints(collection_name="off2")
        for i, vec in enumerate([[1, 0, 0], [0, 1, 0], [0, 0, 1]]):
            p = up.points.add()
            p.id.num = i + 1
            p.vectors.vector.data.extend(vec)
            p.payload["city"].string_value = "oslo" if i == 0 else "bergen"
            p.payload["rank"].integer_value = i
        resp = _call(channel, "/qdrant.Points/Upsert", up,
                     q.PointsOperationResponse)
        assert resp.result.status == q.Completed

        sr = q.SearchPoints(collection_name="off2", vector=[1, 0, 0], limit=2)
        resp = _call(channel, "/qdrant.Points/Search", sr, q.SearchResponse)
        assert len(resp.result) == 2
        top = resp.result[0]
        assert top.id.num == 1
        assert top.payload["city"].string_value == "oslo"
        assert top.score == pytest.approx(1.0, abs=1e-5)

        # with_vectors
        sr = q.SearchPoints(collection_name="off2", vector=[0, 1, 0], limit=1)
        sr.with_vectors.enable = True
        resp = _call(channel, "/qdrant.Points/Search", sr, q.SearchResponse)
        assert list(resp.result[0].vectors.vector.data) == [0.0, 1.0, 0.0]

        # Get by id
        gr = q.GetPoints(collection_name="off2")
        gr.ids.add().num = 2
        resp = _call(channel, "/qdrant.Points/Get", gr, q.GetResponse)
        assert len(resp.result) == 1
        assert resp.result[0].id.num == 2
        assert resp.result[0].payload["rank"].integer_value == 1

    def test_filtered_search_and_count(self, channel):
        req = q.CreateCollection(collection_name="off3")
        req.vectors_config.params.size = 2
        req.vectors_config.params.distance = q.Cosine
        _call(channel, "/qdrant.Collections/Create", req,
              q.CollectionOperationResponse)
        up = q.UpsertPoints(collection_name="off3")
        for i in range(6):
            p = up.points.add()
            p.id.num = i
            p.vectors.vector.data.extend([1.0, float(i) / 10])
            p.payload["parity"].string_value = "even" if i % 2 == 0 else "odd"
            p.payload["rank"].integer_value = i
        _call(channel, "/qdrant.Points/Upsert", up, q.PointsOperationResponse)

        sr = q.SearchPoints(collection_name="off3", vector=[1, 0], limit=10)
        cond = sr.filter.must.add()
        cond.field.key = "parity"
        cond.field.match.keyword = "even"
        resp = _call(channel, "/qdrant.Points/Search", sr, q.SearchResponse)
        assert {r.id.num for r in resp.result} == {0, 2, 4}

        # range filter
        sr = q.SearchPoints(collection_name="off3", vector=[1, 0], limit=10)
        cond = sr.filter.must.add()
        cond.field.key = "rank"
        cond.field.range.gte = 4
        resp = _call(channel, "/qdrant.Points/Search", sr, q.SearchResponse)
        assert {r.id.num for r in resp.result} == {4, 5}

        # count with filter
        cr = q.CountPoints(collection_name="off3")
        cond = cr.filter.must.add()
        cond.field.key = "parity"
        cond.field.match.keyword = "odd"
        resp = _call(channel, "/qdrant.Points/Count", cr, q.CountResponse)
        assert resp.result.count == 3

        # has_id filter
        sr = q.SearchPoints(collection_name="off3", vector=[1, 0], limit=10)
        cond = sr.filter.must.add()
        cond.has_id.has_id.add().num = 3
        resp = _call(channel, "/qdrant.Points/Search", sr, q.SearchResponse)
        assert [r.id.num for r in resp.result] == [3]

    def test_scroll_and_delete(self, channel):
        req = q.CreateCollection(collection_name="off4")
        req.vectors_config.params.size = 2
        req.vectors_config.params.distance = q.Cosine
        _call(channel, "/qdrant.Collections/Create", req,
              q.CollectionOperationResponse)
        up = q.UpsertPoints(collection_name="off4")
        for i in range(5):
            p = up.points.add()
            p.id.num = i
            p.vectors.vector.data.extend([1.0, 0.0])
        _call(channel, "/qdrant.Points/Upsert", up, q.PointsOperationResponse)

        sc = q.ScrollPoints(collection_name="off4", limit=3)
        resp = _call(channel, "/qdrant.Points/Scroll", sc, q.ScrollResponse)
        assert len(resp.result) == 3
        assert resp.HasField("next_page_offset")

        dl = q.DeletePoints(collection_name="off4")
        dl.points.points.ids.add().num = 0
        dl.points.points.ids.add().num = 1
        resp = _call(channel, "/qdrant.Points/Delete", dl,
                     q.PointsOperationResponse)
        assert resp.result.status == q.Completed
        cr = q.CountPoints(collection_name="off4")
        resp = _call(channel, "/qdrant.Points/Count", cr, q.CountResponse)
        assert resp.result.count == 3

    def test_unknown_collection_is_not_found(self, channel):
        with pytest.raises(grpc.RpcError) as err:
            _call(channel, "/qdrant.Collections/Get",
                  q.GetCollectionInfoRequest(collection_name="nope"),
                  q.GetCollectionInfoResponse)
        assert err.value.code() == grpc.StatusCode.NOT_FOUND

    def test_numeric_and_uuid_point_ids(self, channel):
        req = q.CreateCollection(collection_name="off5")
        req.vectors_config.params.size = 2
        req.vectors_config.params.distance = q.Cosine
        _call(channel, "/qdrant.Collections/Create", req,
              q.CollectionOperationResponse)
        up = q.UpsertPoints(collection_name="off5")
        p = up.points.add()
        p.id.uuid = "3fa85f64-5717-4562-b3fc-2c963f66afa6"
        p.vectors.vector.data.extend([0.0, 1.0])
        _call(channel, "/qdrant.Points/Upsert", up, q.PointsOperationResponse)
        sr = q.SearchPoints(collection_name="off5", vector=[0, 1], limit=1)
        resp = _call(channel, "/qdrant.Points/Search", sr, q.SearchResponse)
        assert resp.result[0].id.uuid == "3fa85f64-5717-4562-b3fc-2c963f66afa6"


def test_has_id_through_scroll_count_delete(channel):
    """Review regression: has_id must thread point_id through Scroll,
    Count, and Delete (not just Search)."""
    req = q.CreateCollection(collection_name="off6")
    req.vectors_config.params.size = 2
    req.vectors_config.params.distance = q.Cosine
    _call(channel, "/qdrant.Collections/Create", req,
          q.CollectionOperationResponse)
    up = q.UpsertPoints(collection_name="off6")
    for i in range(4):
        p = up.points.add()
        p.id.num = i
        p.vectors.vector.data.extend([1.0, 0.0])
    _call(channel, "/qdrant.Points/Upsert", up, q.PointsOperationResponse)

    cr = q.CountPoints(collection_name="off6")
    c = cr.filter.must.add()
    c.has_id.has_id.add().num = 1
    c.has_id.has_id.add().num = 2
    resp = _call(channel, "/qdrant.Points/Count", cr, q.CountResponse)
    assert resp.result.count == 2

    sc = q.ScrollPoints(collection_name="off6", limit=10)
    c = sc.filter.must.add()
    c.has_id.has_id.add().num = 3
    resp = _call(channel, "/qdrant.Points/Scroll", sc, q.ScrollResponse)
    assert [r.id.num for r in resp.result] == [3]

    dl = q.DeletePoints(collection_name="off6")
    c = dl.points.filter.must.add()
    c.has_id.has_id.add().num = 0
    _call(channel, "/qdrant.Points/Delete", dl, q.PointsOperationResponse)
    resp = _call(channel, "/qdrant.Points/Count",
                 q.CountPoints(collection_name="off6"), q.CountResponse)
    assert resp.result.count == 3


def test_search_pagination_offset(channel):
    req = q.CreateCollection(collection_name="off7")
    req.vectors_config.params.size = 2
    req.vectors_config.params.distance = q.Cosine
    _call(channel, "/qdrant.Collections/Create", req,
          q.CollectionOperationResponse)
    up = q.UpsertPoints(collection_name="off7")
    for i in range(20):
        p = up.points.add()
        p.id.num = i
        p.vectors.vector.data.extend([1.0, float(i) * 0.01])
    _call(channel, "/qdrant.Points/Upsert", up, q.PointsOperationResponse)
    sr = q.SearchPoints(collection_name="off7", vector=[1, 0], limit=5)
    page1 = _call(channel, "/qdrant.Points/Search", sr, q.SearchResponse)
    sr.offset = 5
    page2 = _call(channel, "/qdrant.Points/Search", sr, q.SearchResponse)
    ids1 = [r.id.num for r in page1.result]
    ids2 = [r.id.num for r in page2.result]
    assert len(ids1) == 5 and len(ids2) == 5
    assert not set(ids1) & set(ids2)


def test_scroll_filter_fills_pages(channel):
    req = q.CreateCollection(collection_name="off8")
    req.vectors_config.params.size = 2
    req.vectors_config.params.distance = q.Cosine
    _call(channel, "/qdrant.Collections/Create", req,
          q.CollectionOperationResponse)
    up = q.UpsertPoints(collection_name="off8")
    for i in range(30):
        p = up.points.add()
        p.id.num = i
        p.vectors.vector.data.extend([1.0, 0.0])
        p.payload["mod"].integer_value = i % 3
    _call(channel, "/qdrant.Points/Upsert", up, q.PointsOperationResponse)
    sc = q.ScrollPoints(collection_name="off8", limit=5)
    c = sc.filter.must.add()
    c.field.key = "mod"
    c.field.match.integer = 0
    got = []
    while True:
        resp = _call(channel, "/qdrant.Points/Scroll", sc, q.ScrollResponse)
        assert len(resp.result) <= 5
        got.extend(r.id.num for r in resp.result)
        if not resp.HasField("next_page_offset"):
            break
        sc.offset.CopyFrom(resp.next_page_offset)
    assert sorted(got) == [i for i in range(30) if i % 3 == 0]
    # first page must be FULL of matches (filter before pagination)
    sc.ClearField("offset")
    resp = _call(channel, "/qdrant.Points/Scroll", sc, q.ScrollResponse)
    assert len(resp.result) == 5


def test_unsupported_filter_rejected_not_match_all(channel):
    req = q.CreateCollection(collection_name="off9")
    req.vectors_config.params.size = 2
    req.vectors_config.params.distance = q.Cosine
    _call(channel, "/qdrant.Collections/Create", req,
          q.CollectionOperationResponse)
    up = q.UpsertPoints(collection_name="off9")
    p = up.points.add()
    p.id.num = 1
    p.vectors.vector.data.extend([1.0, 0.0])
    _call(channel, "/qdrant.Points/Upsert", up, q.PointsOperationResponse)
    # FieldCondition with no match/range clause would otherwise match all
    dl = q.DeletePoints(collection_name="off9")
    c = dl.points.filter.must.add()
    c.field.key = "anything"
    with pytest.raises(grpc.RpcError) as err:
        _call(channel, "/qdrant.Points/Delete", dl, q.PointsOperationResponse)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    resp = _call(channel, "/qdrant.Points/Count",
                 q.CountPoints(collection_name="off9"), q.CountResponse)
    assert resp.result.count == 1  # nothing was wiped


class TestAliases:
    """Collections alias RPCs (reference: server.go:658-665 —
    UpdateAliases / ListCollectionAliases / ListAliases)."""

    def test_alias_lifecycle(self, channel):
        _call(channel, "/qdrant.Collections/Create",
              q.CreateCollection(collection_name="alsrc"),
              q.CollectionOperationResponse)
        ch = q.ChangeAliases()
        op = ch.actions.add()
        op.create_alias.collection_name = "alsrc"
        op.create_alias.alias_name = "al1"
        resp = _call(channel, "/qdrant.Collections/UpdateAliases", ch,
                     q.CollectionOperationResponse)
        assert resp.result is True

        resp = _call(channel, "/qdrant.Collections/ListAliases",
                     q.ListAliasesRequest(), q.ListAliasesResponse)
        pairs = {(a.alias_name, a.collection_name) for a in resp.aliases}
        assert ("al1", "alsrc") in pairs

        resp = _call(channel, "/qdrant.Collections/ListCollectionAliases",
                     q.ListCollectionAliasesRequest(collection_name="alsrc"),
                     q.ListAliasesResponse)
        assert [a.alias_name for a in resp.aliases] == ["al1"]

        # point ops resolve the alias like upstream qdrant
        up = q.UpsertPoints(collection_name="al1")
        p = up.points.add()
        p.id.num = 1
        p.vectors.vector.data.extend([1.0, 0.0])
        _call(channel, "/qdrant.Points/Upsert", up,
              q.PointsOperationResponse)
        cnt = _call(channel, "/qdrant.Points/Count",
                    q.CountPoints(collection_name="alsrc"),
                    q.CountResponse)
        assert cnt.result.count == 1

        ch = q.ChangeAliases()
        op = ch.actions.add()
        op.rename_alias.old_alias_name = "al1"
        op.rename_alias.new_alias_name = "al2"
        _call(channel, "/qdrant.Collections/UpdateAliases", ch,
              q.CollectionOperationResponse)
        ch = q.ChangeAliases()
        op = ch.actions.add()
        op.delete_alias.alias_name = "al2"
        _call(channel, "/qdrant.Collections/UpdateAliases", ch,
              q.CollectionOperationResponse)
        resp = _call(channel, "/qdrant.Collections/ListAliases",
                     q.ListAliasesRequest(), q.ListAliasesResponse)
        assert not [a for a in resp.aliases if a.alias_name == "al2"]

    def test_alias_to_missing_collection_rejected(self, channel):
        ch = q.ChangeAliases()
        op = ch.actions.add()
        op.create_alias.collection_name = "nope-no-such"
        op.create_alias.alias_name = "alx"
        with pytest.raises(grpc.RpcError):
            _call(channel, "/qdrant.Collections/UpdateAliases", ch,
                  q.CollectionOperationResponse)


class TestSnapshots:
    """qdrant.Snapshots service (reference: snapshots_service.go)."""

    def test_collection_snapshot_lifecycle(self, channel, server):
        _call(channel, "/qdrant.Collections/Create",
              q.CreateCollection(collection_name="snapc"),
              q.CollectionOperationResponse)
        up = q.UpsertPoints(collection_name="snapc")
        for i in range(5):
            p = up.points.add()
            p.id.num = i
            p.vectors.vector.data.extend([float(i), 1.0])
            p.payload["tag"].string_value = f"t{i}"
        _call(channel, "/qdrant.Points/Upsert", up,
              q.PointsOperationResponse)

        resp = _call(channel, "/qdrant.Snapshots/Create",
                     q.CreateSnapshotRequest(collection_name="snapc"),
                     q.CreateSnapshotResponse)
        name = resp.snapshot_description.name
        assert name.startswith("snapc-") and name.endswith(".snapshot")
        assert resp.snapshot_description.size > 0

        resp = _call(channel, "/qdrant.Snapshots/List",
                     q.ListSnapshotsRequest(collection_name="snapc"),
                     q.ListSnapshotsResponse)
        assert name in [d.name for d in resp.snapshot_descriptions]

        # recover path (compat layer): drop + restore from the snapshot
        compat = server.db.qdrant_compat
        compat.delete_points("snapc", [0, 1, 2, 3, 4])
        assert compat.count_points("snapc") == 0
        restored = compat.recover_snapshot("snapc", name,
                                           server.snapshot_dir)
        assert restored == 5
        assert compat.count_points("snapc") == 5

        _call(channel, "/qdrant.Snapshots/Delete",
              q.DeleteSnapshotRequest(collection_name="snapc",
                                      snapshot_name=name),
              q.DeleteSnapshotResponse)
        resp = _call(channel, "/qdrant.Snapshots/List",
                     q.ListSnapshotsRequest(collection_name="snapc"),
                     q.ListSnapshotsResponse)
        assert name not in [d.name for d in resp.snapshot_descriptions]

    def test_full_snapshot_lifecycle(self, channel):
        resp = _call(channel, "/qdrant.Snapshots/CreateFull",
                     q.CreateFullSnapshotRequest(),
                     q.CreateSnapshotResponse)
        name = resp.snapshot_description.name
        assert name.startswith("full-")
        resp = _call(channel, "/qdrant.Snapshots/ListFull",
                     q.ListFullSnapshotsRequest(), q.ListSnapshotsResponse)
        assert name in [d.name for d in resp.snapshot_descriptions]
        _call(channel, "/qdrant.Snapshots/DeleteFull",
              q.DeleteFullSnapshotRequest(snapshot_name=name),
              q.DeleteSnapshotResponse)

    def test_missing_snapshot_delete_is_not_found(self, channel):
        with pytest.raises(grpc.RpcError) as ei:
            _call(channel, "/qdrant.Snapshots/Delete",
                  q.DeleteSnapshotRequest(collection_name="snapc",
                                          snapshot_name="ghost.snapshot"),
                  q.DeleteSnapshotResponse)
        assert ei.value.code() in (grpc.StatusCode.NOT_FOUND,
                                   grpc.StatusCode.INVALID_ARGUMENT)
