"""Config layering + feature flags (reference: pkg/config)."""

import os

from nornicdb_tpu.config import (
    Config,
    DBConfigRegistry,
    FeatureFlags,
    load_config,
)


def test_defaults():
    cfg = load_config(env=False)
    assert cfg.server.http_port == 7474
    assert cfg.server.bolt_port == 7687
    assert cfg.database.default_database == "neo4j"
    assert cfg.memory.episodic_half_life_days == 7.0
    assert cfg.memory.semantic_half_life_days == 69.0
    assert cfg.memory.procedural_half_life_days == 693.0


def test_yaml_layer(tmp_path):
    p = tmp_path / "nornicdb.yaml"
    p.write_text("server:\n  http_port: 9999\ndatabase:\n  data_dir: /tmp/x\n")
    cfg = load_config(yaml_path=str(p), env=False)
    assert cfg.server.http_port == 9999
    assert cfg.database.data_dir == "/tmp/x"
    # untouched sections keep defaults
    assert cfg.server.bolt_port == 7687


def test_env_overrides_yaml(tmp_path, monkeypatch):
    p = tmp_path / "nornicdb.yaml"
    p.write_text("server:\n  http_port: 9999\n")
    monkeypatch.setenv("NORNICDB_HTTP_PORT", "8888")
    monkeypatch.setenv("NORNICDB_AUTH_ENABLED", "true")
    monkeypatch.setenv("NORNICDB_AUTO_LINK_THRESHOLD", "0.9")
    cfg = load_config(yaml_path=str(p))
    assert cfg.server.http_port == 8888
    assert cfg.auth.enabled is True
    assert abs(cfg.memory.auto_link_threshold - 0.9) < 1e-9


def test_explicit_overrides_win(monkeypatch):
    monkeypatch.setenv("NORNICDB_HTTP_PORT", "8888")
    cfg = load_config(overrides={"server": {"http_port": 7777}})
    assert cfg.server.http_port == 7777


def test_replication_peers_env(monkeypatch):
    monkeypatch.setenv("NORNICDB_REPLICATION_PEERS", "a:7688, b:7688")
    cfg = load_config()
    assert cfg.replication.peers == ["a:7688", "b:7688"]


def test_feature_flags_env(monkeypatch):
    monkeypatch.setenv("NORNICDB_FLAG_PARSER", "strict")
    monkeypatch.setenv("NORNICDB_FLAG_QUERY_CACHE", "false")
    ff = FeatureFlags()
    assert ff.get("parser") == "strict"
    assert ff.get("query_cache") is False
    ff.set("parser", "nornic")
    assert ff.get("parser") == "nornic"
    assert "fast_paths" in ff.all()


def test_malformed_env_keeps_default(monkeypatch):
    monkeypatch.setenv("NORNICDB_HTTP_PORT", "7474x")
    cfg = load_config()
    assert cfg.server.http_port == 7474


def test_yaml_null_and_mistyped_values(tmp_path):
    p = tmp_path / "nornicdb.yaml"
    p.write_text("server:\n  http_port:\n  bolt_port: '7999'\n")
    cfg = load_config(yaml_path=str(p), env=False)
    assert cfg.server.http_port == 7474  # null keeps default
    assert cfg.server.bolt_port == 7999  # string coerced to int


def test_flags_read_env_live(monkeypatch):
    ff = FeatureFlags()
    assert ff.get("parser") == "nornic"
    monkeypatch.setenv("NORNICDB_FLAG_PARSER", "strict")
    assert ff.get("parser") == "strict"  # env read after construction
    ff.set("parser", "nornic")
    assert ff.get("parser") == "nornic"  # explicit set wins over env
    ff.reset("parser")
    assert ff.get("parser") == "strict"


def test_decay_half_life_wiring():
    from nornicdb_tpu.config import MemoryConfig, decay_half_life_ms
    from nornicdb_tpu.decay import DecayManager, Tier
    from nornicdb_tpu.storage import MemoryEngine

    mem = MemoryConfig(episodic_half_life_days=1.0)
    mgr = DecayManager(MemoryEngine(), half_life_ms=decay_half_life_ms(mem))
    assert mgr.half_life(Tier.EPISODIC) == 86_400_000
    assert mgr.half_life(Tier.SEMANTIC) == 69 * 86_400_000


def test_per_db_overrides():
    reg = DBConfigRegistry(Config())
    reg.set_override("tenant1", {"search": {"ann_quality": "accurate"}})
    assert reg.for_database("tenant1").search.ann_quality == "accurate"
    assert reg.for_database("other").search.ann_quality == "balanced"
    reg.set_override("tenant1", {"search": {"rrf_k": 10}})
    c = reg.for_database("tenant1")
    assert c.search.ann_quality == "accurate" and c.search.rrf_k == 10
    reg.clear_override("tenant1")
    assert reg.for_database("tenant1").search.ann_quality == "balanced"
