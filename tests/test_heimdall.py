"""Heimdall subsystem tests.

Reference: pkg/heimdall — scheduler (Manager load/unload + budget),
Generate/Chat/GenerateWithTools, Bifrost push channel, plugin API.
EchoGenerator is the stub backend (reference tests use stub generators).
"""

import json
import threading

import pytest

import nornicdb_tpu
from nornicdb_tpu.heimdall import (
    Bifrost,
    EchoGenerator,
    Manager,
    ModelSpec,
    ToolLoop,
)


class TestDecoderModel:
    def test_greedy_generation_is_deterministic(self):
        from nornicdb_tpu.heimdall.model import DecoderConfig, DecoderModel

        m = DecoderModel(DecoderConfig.tiny())
        a = m.generate("hi", max_tokens=6)
        b = m.generate("hi", max_tokens=6)
        assert a == b

    def test_generation_respects_max_tokens(self):
        from nornicdb_tpu.heimdall.model import DecoderConfig, DecoderModel

        m = DecoderModel(DecoderConfig.tiny())
        out = m.generate("x", max_tokens=4, temperature=1.0, seed=3)
        assert len(out.encode("utf-8", errors="replace")) <= 16

    def test_param_bytes_positive(self):
        from nornicdb_tpu.heimdall.generators import JAXGenerator
        from nornicdb_tpu.heimdall.model import DecoderConfig

        g = JAXGenerator(cfg=DecoderConfig.tiny())
        assert g.param_bytes() > 0


class TestManager:
    def test_register_load_generate(self):
        mgr = Manager()
        mgr.register(ModelSpec(name="m1", backend="echo",
                               memory_bytes=100))
        r = mgr.generate("hello", model="m1")
        assert r.text.startswith("echo:")
        assert r.model == "m1"
        assert mgr.models()[0].loaded

    def test_memory_budget_evicts(self):
        mgr = Manager(memory_budget_bytes=150)
        mgr.register(ModelSpec(name="a", backend="echo", memory_bytes=100))
        mgr.register(ModelSpec(name="b", backend="echo", memory_bytes=100))
        mgr.load("a")
        mgr.load("b")  # must evict a
        specs = {s.name: s for s in mgr.models()}
        assert specs["b"].loaded and not specs["a"].loaded
        assert mgr.memory_used == 100

    def test_over_budget_model_rejected(self):
        mgr = Manager(memory_budget_bytes=50)
        mgr.register(ModelSpec(name="big", backend="echo",
                               memory_bytes=100))
        with pytest.raises(MemoryError):
            mgr.load("big")

    def test_chat_renders_transcript(self):
        mgr = Manager()
        echo = EchoGenerator()
        mgr.register(ModelSpec(name="e", backend="echo"))
        mgr._loaded["e"] = echo  # inject to inspect calls
        mgr._specs["e"].loaded = True
        mgr.chat([{"role": "system", "content": "be brief"},
                  {"role": "user", "content": "hi"}], model="e")
        assert "system: be brief" in echo.calls[0]
        assert echo.calls[0].rstrip().endswith("assistant:")

    def test_rbac_check_runs(self):
        denied = []

        def rbac(user):
            denied.append(user)
            raise PermissionError("nope")

        mgr = Manager(rbac_check=rbac)
        mgr.register(ModelSpec(name="e", backend="echo"))
        with pytest.raises(PermissionError):
            mgr.generate("x", model="e", user="alice")
        assert denied == ["alice"]

    def test_plugin_transforms_output(self):
        class Upper:
            def on_generate(self, prompt, text):
                return text.upper()

        mgr = Manager()
        mgr.register(ModelSpec(name="e", backend="echo"))
        mgr.register_plugin(Upper())
        r = mgr.generate("hi", model="e")
        assert r.text.startswith("ECHO:")


class TestToolLoop:
    def test_tool_loop_executes_mcp_and_answers(self):
        from nornicdb_tpu.api.mcp import McpServer

        db = nornicdb_tpu.open()
        try:
            mcp = McpServer(db)
            gen = EchoGenerator(replies=[
                'TOOL {"tool": "store", "args": {"content": "note one",'
                ' "node_id": "n1"}}',
                "stored it!",
            ])
            loop = ToolLoop(gen, mcp)
            text, calls = loop.run("please store a note")
            assert text == "stored it!"
            assert len(calls) == 1
            assert calls[0]["tool"] == "store"
            assert db.storage.has_node("n1")
        finally:
            db.close()

    def test_unknown_tool_reported_not_crash(self):
        from nornicdb_tpu.api.mcp import McpServer

        db = nornicdb_tpu.open()
        try:
            mcp = McpServer(db)
            gen = EchoGenerator(replies=[
                'TOOL {"tool": "nope", "args": {}}',
                "done",
            ])
            text, calls = ToolLoop(gen, mcp).run("x")
            assert calls[0]["result"]["error"].startswith("unknown tool")
            assert text == "done"
        finally:
            db.close()

    def test_round_cap(self):
        from nornicdb_tpu.api.mcp import McpServer

        db = nornicdb_tpu.open()
        try:
            mcp = McpServer(db)
            gen = EchoGenerator(replies=[
                'TOOL {"tool": "tasks", "args": {}}'] * 10)
            text, calls = ToolLoop(gen, mcp).run("x", max_rounds=3)
            assert len(calls) == 3
        finally:
            db.close()


class TestBifrost:
    def test_pubsub_fanout(self):
        b = Bifrost()
        s1, s2 = b.subscribe(), b.subscribe()
        assert b.publish("tick", {"n": 1}) == 2
        e1 = list(b.events(s1, timeout=0.1, max_events=1))
        e2 = list(b.events(s2, timeout=0.1, max_events=1))
        assert e1[0]["data"] == {"n": 1}
        assert e2[0]["event"] == "tick"

    def test_slow_subscriber_drops_oldest(self):
        b = Bifrost(max_queue=2)
        s = b.subscribe()
        for i in range(5):
            b.publish("e", {"i": i})
        got = [m["data"]["i"] for m in b.events(s, timeout=0.05)]
        assert got == [3, 4]

    def test_sse_rendering(self):
        b = Bifrost()
        s = b.subscribe()
        b.publish("gen", {"x": "y"})
        msg = next(b.events(s, timeout=0.1))
        sse = Bifrost.sse(msg)
        assert sse.startswith("event: gen\n")
        assert 'data: {"x": "y"}' in sse


class TestHTTPSurface:
    @pytest.fixture()
    def server(self):
        from nornicdb_tpu.api.http_server import HttpServer

        db = nornicdb_tpu.open()
        srv = HttpServer(db, port=0).start()
        # swap the default JAX model for the echo stub: HTTP tests
        # shouldn't pay a jit compile
        from nornicdb_tpu.heimdall import Bifrost as _B, Manager, ModelSpec

        mgr = Manager()
        mgr.register(ModelSpec(name="echo", backend="echo"))
        mgr.bifrost = _B()
        srv._heimdall = mgr
        yield srv
        srv.stop()
        db.close()

    def _post(self, server, path, body):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read())

    def test_openai_compatible_chat(self, server):
        r = self._post(server, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "hello"}]})
        assert r["object"] == "chat.completion"
        assert r["choices"][0]["message"]["role"] == "assistant"
        assert "hello" in r["choices"][0]["message"]["content"]

    def test_heimdall_models_and_generate(self, server):
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/heimdall/models"
        ) as resp:
            models = json.loads(resp.read())["models"]
        assert models[0]["name"] == "echo"
        r = self._post(server, "/heimdall/generate", {"prompt": "yo"})
        assert r["text"].startswith("echo:")

    def test_bifrost_sse_stream(self, server):
        import urllib.request

        events = []

        def reader():
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/bifrost/events"
                "?idle_timeout=1.5")
            with urllib.request.urlopen(req) as resp:
                buf = b""
                while True:
                    chunk = resp.read(1)
                    if not chunk:
                        break
                    buf += chunk
                    if buf.endswith(b"\n\n") and b"event:" in buf:
                        events.append(buf.decode())
                        break

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        import time as _t

        _t.sleep(0.3)  # let the subscriber attach
        self._post(server, "/heimdall/generate", {"prompt": "ping"})
        t.join(timeout=5)
        assert events and "event: generation" in events[0]


class TestHTTPRegressions:
    def test_chat_null_content_and_total_tokens(self, server=None):
        from nornicdb_tpu.api.http_server import HttpServer
        import urllib.request

        db = nornicdb_tpu.open()
        srv = HttpServer(db, port=0).start()
        from nornicdb_tpu.heimdall import Bifrost as _B, Manager, ModelSpec

        mgr = Manager()
        mgr.register(ModelSpec(name="echo", backend="echo"))
        mgr.bifrost = _B()
        srv._heimdall = mgr
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/chat/completions",
                data=json.dumps({"messages": [
                    {"role": "assistant", "content": None},
                    {"role": "user", "content": "hello"},
                ]}).encode(), method="POST")
            with urllib.request.urlopen(req) as resp:
                r = json.loads(resp.read())
            usage = r["usage"]
            assert usage["total_tokens"] == (
                usage["prompt_tokens"] + usage["completion_tokens"])
        finally:
            srv.stop()
            db.close()

    def test_sse_requires_auth_when_enabled(self):
        import urllib.error
        import urllib.request

        from nornicdb_tpu.api.http_server import HttpServer
        from nornicdb_tpu.auth import Authenticator

        db = nornicdb_tpu.open()
        auth = Authenticator()
        auth.create_user("admin", "pw", roles=["admin"])
        srv = HttpServer(db, port=0, authenticator=auth).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/bifrost/events"
                    "?idle_timeout=0.2")
            assert ei.value.code in (401, 403)
        finally:
            srv.stop()
            db.close()

    def test_sse_bad_idle_timeout_is_400(self):
        import urllib.error
        import urllib.request

        from nornicdb_tpu.api.http_server import HttpServer

        db = nornicdb_tpu.open()
        srv = HttpServer(db, port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/bifrost/events"
                    "?idle_timeout=abc")
            assert ei.value.code == 400
        finally:
            srv.stop()
            db.close()

    def test_store_tool_schema_declares_node_id(self):
        from nornicdb_tpu.api.mcp import McpServer

        db = nornicdb_tpu.open()
        try:
            mcp = McpServer(db)
            schema = mcp._tools["store"]["inputSchema"]
            assert "node_id" in schema["properties"]
        finally:
            db.close()
