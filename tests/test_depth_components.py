"""Depth components from VERDICT r1: temporal pattern detector +
relationship evolution (ref pkg/temporal), FastRP + GDS graph catalog
(ref fastrp.go), hybrid cluster routing (ref
hybrid_cluster_routing.go:248), strict parser mode (ref pkg/cypher/antlr
+ parser_comparison_test.go)."""

import numpy as np
import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


# ----------------------------------------------------- pattern detection


class TestPatternDetector:
    def test_daily_pattern(self):
        from nornicdb_tpu.temporal import PatternDetector

        pd = PatternDetector()
        base = 1_700_000_000.0
        base -= base % 86400  # midnight
        # access at ~09:00 every day for a week
        for day in range(7):
            pd.record_access("n", base + day * 86400 + 9 * 3600)
            pd.record_access("n", base + day * 86400 + 9 * 3600 + 600)
        pats = pd.detect_patterns("n", now=base + 7 * 86400)
        types = {p.type for p in pats}
        assert "daily" in types
        hour, _day, conf = pd.peak_access_time("n")
        assert hour == 9
        assert conf > 0.5

    def test_weekly_pattern(self):
        from nornicdb_tpu.temporal import PatternDetector

        pd = PatternDetector()
        base = 1_700_000_000.0
        base -= base % 86400
        # every Monday-ish (same weekday) for 6 weeks, random-ish hours
        for week in range(6):
            for h in (8, 13, 19):
                pd.record_access("w", base + week * 7 * 86400 + h * 3600)
        pats = pd.detect_patterns("w", now=base + 6 * 7 * 86400)
        assert any(p.type == "weekly" for p in pats)

    def test_burst_pattern(self):
        from nornicdb_tpu.temporal import PatternDetector

        pd = PatternDetector()
        now = 1_700_000_000.0
        for i in range(10):
            pd.record_access("b", now - i * 60)  # all in the last 10 min
        pats = pd.detect_patterns("b", now=now)
        assert any(p.type == "burst" for p in pats)

    def test_trend_patterns_from_velocity(self):
        from nornicdb_tpu.temporal import PatternDetector

        pd = PatternDetector()
        assert pd.has_pattern("x", "growing", velocity=0.5)
        assert pd.has_pattern("x", "decaying", velocity=-0.5)
        assert not pd.has_pattern("x", "growing", velocity=0.0)

    def test_no_pattern_on_sparse_history(self):
        from nornicdb_tpu.temporal import PatternDetector

        pd = PatternDetector()
        pd.record_access("s", 1_700_000_000.0)
        assert pd.detect_patterns("s", now=1_700_000_100.0) == []


class TestRelationshipEvolution:
    def test_strengthening_and_prediction(self):
        from nornicdb_tpu.temporal import RelationshipEvolution

        re_ = RelationshipEvolution()
        t = 1_700_000_000.0
        for i in range(10):
            re_.record_co_access("a", "b", weight=1.0, at=t + i * 60)
        tr = re_.get_trend("a", "b")
        assert tr is not None
        assert tr.trend == "strengthening"
        assert tr.velocity > 0
        assert re_.predict_strength("a", "b", steps=5) > tr.current_strength

    def test_weakening_via_decayed_updates(self):
        from nornicdb_tpu.temporal import RelationshipEvolution

        re_ = RelationshipEvolution()
        t = 1_700_000_000.0
        weights = [20.0 - 2.0 * i for i in range(10)]  # steep decline
        for i, w in enumerate(weights):
            re_.update_weight("a", "b", w, at=t + i * 60)
        tr = re_.get_trend("a", "b")
        assert tr.trend == "weakening"
        assert re_.weakening()[0].source_id == "a"

    def test_emerging_and_prune(self):
        from nornicdb_tpu.temporal import RelationshipEvolution

        re_ = RelationshipEvolution()
        t = 1_700_000_000.0
        for i in range(5):
            re_.record_co_access("new1", "new2", at=t + i * 30)
        emerging = re_.emerging(now=t + 200)
        assert [(e.source_id, e.target_id) for e in emerging] == [
            ("new1", "new2")]
        assert re_.should_prune("ghost", "edge")
        assert not re_.should_prune("new1", "new2", threshold=0.1)

    def test_symmetric_keying(self):
        from nornicdb_tpu.temporal import RelationshipEvolution

        re_ = RelationshipEvolution()
        re_.record_co_access("b", "a")
        assert re_.get_trend("a", "b") is not None


# --------------------------------------------------------------- FastRP


class TestFastRP:
    def _community_graph(self):
        """Two dense 10-node communities joined by one bridge edge."""
        import random

        rng = random.Random(3)
        src, dst = [], []
        for base in (0, 10):
            for i in range(10):
                for j in range(i + 1, 10):
                    if rng.random() < 0.7:
                        src.append(base + i)
                        dst.append(base + j)
        src.append(0)
        dst.append(10)
        return np.asarray(src), np.asarray(dst)

    def test_embeddings_cluster_communities(self):
        from nornicdb_tpu.ops.fastrp import fastrp_embeddings

        src, dst = self._community_graph()
        emb = fastrp_embeddings(20, src, dst, dim=32, seed=7)
        assert emb.shape == (20, 32)
        sims = emb @ emb.T
        intra = np.mean([sims[i, j] for i in range(10) for j in range(10)
                         if i != j])
        inter = np.mean([sims[i, j] for i in range(10)
                         for j in range(10, 20)])
        assert intra > inter + 0.2, (intra, inter)

    def test_deterministic_by_seed(self):
        from nornicdb_tpu.ops.fastrp import fastrp_embeddings

        src, dst = self._community_graph()
        a = fastrp_embeddings(20, src, dst, dim=16, seed=1)
        b = fastrp_embeddings(20, src, dst, dim=16, seed=1)
        c = fastrp_embeddings(20, src, dst, dim=16, seed=2)
        np.testing.assert_array_equal(a, b)
        assert not np.allclose(a, c)

    def test_gds_procedures_end_to_end(self):
        ex = CypherExecutor(NamespacedEngine(MemoryEngine(), "test"))
        for i in range(6):
            ex.execute("CREATE (:P {i: $i})", {"i": i})
        for a, b in [(0, 1), (1, 2), (2, 0), (3, 4), (4, 5), (5, 3)]:
            ex.execute("MATCH (x:P {i:$a}), (y:P {i:$b}) "
                       "CREATE (x)-[:KNOWS]->(y)", {"a": a, "b": b})
        r = ex.execute("CALL gds.graph.project('g1', 'P', 'KNOWS') "
                       "YIELD graphName, nodeCount, relationshipCount "
                       "RETURN *")
        rec = r.records()[0]
        assert rec["nodeCount"] == 6 and rec["relationshipCount"] == 6
        r = ex.execute(
            "CALL gds.fastRP.stream('g1', {embeddingDimension: 16}) "
            "YIELD nodeId, embedding RETURN nodeId, size(embedding)")
        assert len(r.rows) == 6
        assert all(row[1] == 16 for row in r.rows)
        assert ex.execute("CALL gds.graph.list() YIELD graphName "
                          "RETURN graphName").rows == [["g1"]]
        ex.execute("CALL gds.graph.drop('g1')")
        assert ex.execute("CALL gds.graph.list() YIELD graphName "
                          "RETURN graphName").rows == []

    def test_fastrp_unknown_graph_errors(self):
        from nornicdb_tpu.errors import CypherRuntimeError

        ex = CypherExecutor(NamespacedEngine(MemoryEngine(), "test"))
        with pytest.raises(CypherRuntimeError):
            ex.execute("CALL gds.fastRP.stream('missing', {})")


# ------------------------------------------------ hybrid cluster routing


class TestHybridClusterRouting:
    def _build_index(self):
        from nornicdb_tpu.search.ivf_hnsw import IVFHNSWIndex

        rng = np.random.default_rng(0)
        # two well-separated clusters in 16d
        a_center = np.zeros(16); a_center[0] = 1.0
        b_center = np.zeros(16); b_center[1] = 1.0
        items = []
        for i in range(40):
            items.append((f"a{i}", a_center + 0.05 * rng.standard_normal(16)))
        for i in range(40):
            items.append((f"b{i}", b_center + 0.05 * rng.standard_normal(16)))
        idx = IVFHNSWIndex(n_clusters=2, nprobe=1)
        idx.build(items)
        return idx

    def test_lexical_hits_redirect_probes(self):
        idx = self._build_index()
        # query semantically in cluster A...
        q = np.zeros(16); q[0] = 1.0
        sem_only = idx.route(q, nprobe=1)
        # ...but every BM25 hit lives in cluster B
        lex_ids = [f"b{i}" for i in range(30)]
        hybrid = idx.route(q, nprobe=1, lexical_doc_ids=lex_ids,
                           lexical_weight=0.8)
        assert sem_only[0] != hybrid[0], "lexical evidence must reroute"

    def test_search_accepts_lexical_ids(self):
        idx = self._build_index()
        q = np.zeros(16); q[0] = 1.0
        hits = idx.search(q, k=3, lexical_doc_ids=[f"a{i}" for i in range(5)])
        assert hits and hits[0][0].startswith("a")

    def test_service_passes_bm25_hits_to_routed_index(self):
        from nornicdb_tpu.search.service import SearchService

        eng = NamespacedEngine(MemoryEngine(), "test")
        svc = SearchService(eng)
        calls = {}

        class _Routed:
            def __len__(self):
                return 1

            def route(self, *a, **k):
                return [0]

            def search(self, q, k, lexical_doc_ids=None):
                calls["lex"] = lexical_doc_ids
                return []

        from nornicdb_tpu.storage.types import Node

        n = Node(id="d1", labels=["Doc"],
                 properties={"content": "tigers roam"}, embedding=[1.0, 0.0])
        eng.create_node(n)
        svc.index_node(eng.get_node("d1"))
        svc.vectors = _Routed()
        svc.search("tigers", query_embedding=[1.0, 0.0])
        assert calls.get("lex") == ["d1"]


# ------------------------------------------------------ strict parser mode


class TestStrictParserMode:
    def test_undefined_variable_rejected(self):
        from nornicdb_tpu.query.strict import validate

        diags = validate("MATCH (n:P) RETURN m")
        assert any(d.severity == "error" and "`m`" in d.message
                   for d in diags)

    def test_aggregate_in_where_rejected(self):
        from nornicdb_tpu.query.strict import validate

        diags = validate("MATCH (n:P) WHERE count(n) > 1 RETURN n")
        assert any("aggregate" in d.message for d in diags)

    def test_unknown_function_warns(self):
        from nornicdb_tpu.query.strict import validate

        diags = validate("RETURN totallyMadeUp(1)")
        assert any(d.severity == "warning" for d in diags)

    def test_syntax_error_has_line_col(self):
        from nornicdb_tpu.query.strict import validate

        diags = validate("MATCH (n:P)\nRETURN n + ")
        assert diags[0].severity == "error"
        assert diags[0].line == 2

    def test_strict_executor_rejects_before_execution(self):
        from nornicdb_tpu.errors import CypherSyntaxError

        eng = NamespacedEngine(MemoryEngine(), "test")
        ex = CypherExecutor(eng, parser_mode="strict")
        with pytest.raises(CypherSyntaxError):
            ex.execute("MATCH (n:P) RETURN nope")
        assert eng.count_nodes() == 0

    # parity corpus: strict mode must accept exactly what the fast path
    # accepts (reference: parser_comparison_test.go)
    ACCEPT = [
        "MATCH (n:Person) RETURN n.name",
        "MATCH (a)-[r:KNOWS]->(b) WHERE a.age > 30 RETURN b, count(r)",
        "CREATE (n:X {v: 1}) RETURN n",
        "MATCH (n) WITH n.age AS age, count(*) AS c RETURN age, c",
        "UNWIND [1,2,3] AS x RETURN x * 2",
        "MATCH (n) WHERE all(l IN labels(n) WHERE l <> 'Banned') RETURN n",
        "RETURN reduce(acc = 0, x IN [1,2] | acc + x)",
        "MATCH (a:P), (b:Q) CREATE (a)-[:REL]->(b)",
        "CALL db.labels() YIELD label RETURN label",
        "MATCH p = (a)-[:K*1..3]->(b) RETURN length(p)",
    ]
    REJECT = [
        "MATCH (n:P RETURN n",
        "MATCH (n) RETURN undefined_var",
        "MATCH (a)-[]->(b) CREATE (a)-[]->(b)",  # typeless CREATE rel
        "RETURN 1 +",
    ]

    @pytest.mark.parametrize("query", ACCEPT)
    def test_parity_accept(self, query):
        from nornicdb_tpu.query.strict import validate

        errors = [d for d in validate(query) if d.severity == "error"]
        assert errors == [], f"strict rejected valid query: {errors}"

    @pytest.mark.parametrize("query", REJECT)
    def test_parity_reject(self, query):
        from nornicdb_tpu.errors import CypherRuntimeError, CypherSyntaxError
        from nornicdb_tpu.query.strict import validate

        strict_errors = [d for d in validate(query) if d.severity == "error"]
        # the fast path must also reject (parse or runtime). The fast
        # path is lazy — errors surface only when rows flow — so seed a
        # node; strict mode's value is catching these BEFORE execution.
        eng = NamespacedEngine(MemoryEngine(), "test")
        ex = CypherExecutor(eng)
        ex.execute("CREATE (:Seed {v: 1})-[:S]->(:Seed {v: 2})")
        fast_rejects = False
        try:
            ex.execute(query)
        except (CypherSyntaxError, CypherRuntimeError):
            fast_rejects = True
        assert strict_errors and fast_rejects, (
            f"parity broken: strict={bool(strict_errors)} "
            f"fast_rejects={fast_rejects}"
        )


def test_strict_yield_star_keeps_columns_usable():
    """Review regression: CALL ... YIELD * must not flag yielded columns
    as undefined."""
    from nornicdb_tpu.query.strict import validate

    errors = [d for d in validate(
        "CALL db.labels() YIELD * RETURN label"
    ) if d.severity == "error"]
    assert errors == []


def test_daily_peak_hour_exact():
    """Review regression: single-hour concentration reports that hour."""
    from nornicdb_tpu.temporal import PatternDetector

    pd = PatternDetector()
    base = 1_700_000_000.0
    base -= base % 86400
    for day in range(7):
        pd.record_access("n", base + day * 86400 + 9 * 3600)
        pd.record_access("n", base + day * 86400 + 9 * 3600 + 60)
    pats = pd.detect_patterns("n", now=base + 7 * 86400)
    daily = [p for p in pats if p.type == "daily"]
    assert daily and daily[0].peak_hour == 9


def test_jax_generator_respects_explicit_cfg():
    """Review regression: a pinned architecture must not be silently
    replaced by the committed default checkpoint."""
    from nornicdb_tpu.heimdall.generators import JAXGenerator
    from nornicdb_tpu.heimdall.model import DecoderConfig

    cfg = DecoderConfig(d_model=32, n_heads=2, n_layers=1, d_ff=64,
                        max_seq=64)
    g = JAXGenerator(cfg=cfg)
    assert g.model.cfg == cfg


def test_tracker_integrates_patterns_and_evolution():
    """PatternDetector/RelationshipEvolution are live INSIDE the tracker
    (not standalone-only): record_access feeds both."""
    from nornicdb_tpu.temporal import TemporalTracker

    tr = TemporalTracker()
    base = 1_700_000_000.0
    base -= base % 86400
    for day in range(4):
        t = base + day * 86400 + 9 * 3600
        tr.record_access("a", t)
        tr.record_access("a", t + 600)  # min_accesses needs >= 6 samples
        tr.record_access("b", t + 30)  # same session: co-access
    pats = tr.detect_patterns("a")
    assert any(p.type == "daily" for p in pats)
    trend = tr.evolution.get_trend("a", "b")
    assert trend is not None and trend.current_strength > 0


def test_db_inference_uses_evidence_buffer():
    """remember() feeds evidence-gated co-access inference end to end:
    enough co-accesses materialize a CO_ACCESSED_WITH edge, fewer don't."""
    import nornicdb_tpu

    db = nornicdb_tpu.open(auto_embed=False)
    assert db.inference.evidence is not None  # wired by default
    db.store("doc a", node_id="a")
    db.store("doc b", node_id="b")
    _ = db.inference  # materialize the engine (store/auto-link path)
    t = 1_700_000_000.0
    for i in range(8):
        db.decay.record_access("a")
        db.temporal.record_access("a", at=t + i * 20)
        db.temporal.record_access("b", at=t + i * 20 + 5)
        db.inference.on_access(db.temporal, "b")
    edges = [e for e in db.storage.all_edges()
             if e.type == "CO_ACCESSED_WITH"]
    assert edges, "co-access evidence never materialized an edge"
    assert edges[0].properties.get("inferred") is True
    db.close()
