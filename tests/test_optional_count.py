"""Vectorized OPTIONAL MATCH count family
(fastpaths._analyze_optional_count): groups with zero matches must
appear (null-extended row semantics), count(x) vs count(*) differ, and
every shape matches the general executor exactly."""

import random
import uuid

import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine
from nornicdb_tpu.storage.types import Edge, Node


@pytest.fixture(scope="module")
def graph():
    eng = NamespacedEngine(MemoryEngine(), "opt")
    rng = random.Random(3)

    def add_node(labels, props):
        n = Node(id=str(uuid.uuid4()), labels=labels, properties=props)
        eng.create_node(n)
        return n.id

    def add_edge(etype, a, b):
        eng.create_edge(Edge(id=str(uuid.uuid4()), type=etype,
                             start_node=a, end_node=b, properties={}))

    people = [add_node(["P"], {"id": i, "name": f"p{i}"})
              for i in range(30)]
    for i, pid in enumerate(people):
        for j in rng.sample(range(30), i % 4):  # several with 0 edges
            if j != i:
                add_edge("KNOWS", pid, people[j])
    return eng


def _pair(graph):
    fast = CypherExecutor(graph)
    fast.enable_query_cache = False
    slow = CypherExecutor(graph)
    slow.enable_query_cache = False
    slow.enable_fastpaths = False
    return fast, slow


QUERIES = [
    "MATCH (p:P) OPTIONAL MATCH (p)-[:KNOWS]->(f:P) "
    "RETURN p.id, count(f) ORDER BY p.id",
    "MATCH (p:P) OPTIONAL MATCH (p)-[:KNOWS]->(f) "
    "RETURN p.name, count(f), count(*) ORDER BY p.name",
    "MATCH (p:P) OPTIONAL MATCH (p)<-[:KNOWS]-(f:P) "
    "RETURN p.id, count(f) ORDER BY p.id",
    "MATCH (p:P {id: 0}) OPTIONAL MATCH (p)-[:KNOWS]->(f:P) "
    "RETURN p.id, count(f)",
    "MATCH (p:P) WHERE p.id < 10 OPTIONAL MATCH (p)-[:KNOWS]->(f:P) "
    "RETURN p.id, count(f) ORDER BY p.id",
]


@pytest.mark.parametrize("query", QUERIES)
def test_parity(graph, query):
    fast, slow = _pair(graph)
    rf, rs = fast.execute(query), slow.execute(query)
    assert rf.columns == rs.columns
    assert [list(r) for r in rf.rows] == [list(r) for r in rs.rows]


def test_zero_count_groups_present(graph):
    fast, _ = _pair(graph)
    rows = fast.execute(QUERIES[0]).rows
    assert len(rows) == 30  # EVERY person has a group
    assert any(r[1] == 0 for r in rows)  # including friendless ones


def test_plan_compiles(graph):
    from nornicdb_tpu.query import fastpaths
    from nornicdb_tpu.query.parser import parse

    plan = fastpaths._analyze_vectorized(parse(QUERIES[0]).parts[0])
    assert plan is not None and plan["optional_count"] is not None


def test_unsupported_optional_shapes_fall_back(graph):
    """Projected optional vars, WHERE on the optional side, and distinct
    counts use the general path — and stay correct."""
    fast, slow = _pair(graph)
    for q in [
        "MATCH (p:P {id: 1}) OPTIONAL MATCH (p)-[:KNOWS]->(f:P) "
        "RETURN p.id, f.id ORDER BY f.id",
        "MATCH (p:P) OPTIONAL MATCH (p)-[:KNOWS]->(f:P) "
        "WHERE f.id > 5 RETURN p.id, count(f) ORDER BY p.id",
        "MATCH (p:P) OPTIONAL MATCH (p)-[:KNOWS]->(f:P) "
        "RETURN p.id, count(DISTINCT f) ORDER BY p.id",
    ]:
        rf, rs = fast.execute(q), slow.execute(q)
        assert [list(r) for r in rf.rows] == [list(r) for r in rs.rows], q


def test_optional_count_sees_writes(graph):
    eng = NamespacedEngine(MemoryEngine(), "optw")
    ex = CypherExecutor(eng)
    ex.enable_query_cache = False
    ex.execute("CREATE (:P {id: 1}), (:P {id: 2})")
    q = ("MATCH (p:P) OPTIONAL MATCH (p)-[:K]->(x) "
         "RETURN p.id, count(x) ORDER BY p.id")
    assert ex.execute(q).rows == [[1, 0], [2, 0]]
    ex.execute("MATCH (a:P {id:1}), (b:P {id:2}) CREATE (a)-[:K]->(b)")
    assert ex.execute(q).rows == [[1, 1], [2, 0]]


def test_non_match_leading_clause_no_crash(graph):
    """Regression: UNWIND/WITH before OPTIONAL MATCH must fall back, not
    crash on the clause-type assumption."""
    fast, slow = _pair(graph)
    for q in [
        "UNWIND [0, 1] AS i OPTIONAL MATCH (p:P {id: i}) "
        "RETURN count(p)",
        "WITH 1 AS z OPTIONAL MATCH (p:P {id: z}) RETURN z, count(p)",
    ]:
        rf, rs = fast.execute(q), slow.execute(q)
        assert [list(r) for r in rf.rows] == [list(r) for r in rs.rows], q
