"""Device-fused hybrid search (ISSUE 4): BM25 CSR scoring on device,
single-program BM25+vector+RRF fusion, shard_map parity, freshness
ladder (alive refresh + delta side-scan + background rebuild), service
wiring through the hybrid MicroBatcher, and the incremental-df /
weighted-RRF satellites.

The acceptance gate is the hybrid parity corpus: the fused device
pipeline must be RANK-IDENTICAL to the host reference
(BM25Index.search_batch -> BruteForceIndex.search_batch -> rrf_fuse)
on a single device and on 2/4-shard CPU meshes, across multi-term
queries, tombstones, empty lexical/vector sides and k > corpus.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from nornicdb_tpu.search.bm25 import BM25Index, tokenize
from nornicdb_tpu.search.device_bm25 import DeviceBM25
from nornicdb_tpu.search.hybrid_fused import FusedHybrid
from nornicdb_tpu.search.microbatch import pow2_bucket
from nornicdb_tpu.search.rrf import rrf_fuse
from nornicdb_tpu.search.vector_index import BruteForceIndex

VOCAB = [f"term{i}" for i in range(64)]
D = 32


def _corpus(n=400, seed=7, text_only=12, vec_only=12):
    rng = np.random.default_rng(seed)
    bm25 = BM25Index()
    brute = BruteForceIndex()
    for i in range(n):
        words = rng.choice(VOCAB, size=int(rng.integers(3, 12)))
        bm25.index(f"d{i}", " ".join(words))
        brute.add(f"d{i}", rng.standard_normal(D).astype(np.float32))
    for i in range(text_only):
        bm25.index(f"t{i}", f"term1 term2 textonly{i % 3}")
    for i in range(vec_only):
        brute.add(f"v{i}", rng.standard_normal(D).astype(np.float32))
    return bm25, brute, rng


# the >= 20-case parity corpus: multi-term, repeated-term, single-term,
# rare/common mixes, no-match (empty lexical) and stopword-only queries
PARITY_QUERIES = [
    "term1 term2 term3",
    "term4 term9 term11 term12",
    "term7 term8",
    "term0 term63",
    "term5 term5 term5 term6",      # repeated terms
    "term13 term14 term15 term16 term17",
    "term20",
    "term21 term22",
    "term23 term24 term25",
    "term30 term31 term32 term33",
    "term40 term41",
    "term42 term43 term44",
    "term50 term51 term52",
    "term60 term61 term62",
    "term2 textonly0",
    "term1 textonly1 term3",
    "zzz qqq nothing",              # empty lexical side
    "the and of is",                # stopword-only -> no tokens
    "term6 missingword",
    "term18 term19 term26 term27 term28 term29",
    "term34 term35",
    "term36 term37 term38 term39",
]


def _host_reference(bm25, brute, queries, embs, overfetch, weights=()):
    lex = bm25.search_batch(queries, overfetch)
    vec = brute.search_batch(embs, overfetch)
    out = []
    for li, vi in zip(lex, vec):
        if li and vi:
            fused = rrf_fuse([li, vi], weights=weights, limit=overfetch)
        elif li:
            fused = li[:overfetch]
        else:
            fused = vi[:overfetch]
        out.append((li, vi, fused))
    return out


def _fused_rows(fh, queries, embs, overfetch, weights=(1.0, 1.0)):
    kq = pow2_bucket(overfetch)
    extras = [{"tokens": tokenize(q), "n_cand": overfetch,
               "w": tuple(weights)} for q in queries]
    return fh.search_batch(np.asarray(embs, np.float32), kq, extras)


def _assert_parity(fh, bm25, brute, queries, embs, overfetch,
                   weights=(1.0, 1.0)):
    rows = _fused_rows(fh, queries, embs, overfetch, weights)
    ref = _host_reference(bm25, brute, queries, embs, overfetch,
                          weights=list(weights))
    for qi, (row, (li, vi, fused)) in enumerate(zip(rows, ref)):
        assert row is not None, f"query {qi} fell back unexpectedly"
        assert [x[0] for x in row["lex"]] == [x[0] for x in li], qi
        assert [x[0] for x in row["vec"]] == [x[0] for x in vi], qi
        if li and vi:
            assert [x[0] for x in row["fused"]] == \
                [x[0] for x in fused], qi
            # fused scores are float32-bitwise identical to host rrf
            assert [x[1] for x in row["fused"]] == \
                [x[1] for x in fused], qi


# ---------------------------------------------------------------------------
# satellite: incremental live df + search_batch on the host index
# ---------------------------------------------------------------------------


class TestBM25Incremental:
    def _df_recount(self, idx, term):
        p = idx._postings.get(term)
        if p is None:
            return 0
        return sum(1 for i in p.doc_ids if idx._alive[i])

    def test_df_tracks_add_remove_update(self):
        idx = BM25Index()
        idx.index("a", "apple banana")
        idx.index("b", "apple cherry")
        assert idx._df["apple"] == 2
        idx.remove("a")
        assert idx._df["apple"] == 1
        assert "banana" not in idx._df
        idx.index("b", "banana only now")  # update drops apple
        assert "apple" not in idx._df
        assert idx._df["banana"] == 1
        for t in ("banana", "only", "now"):
            assert idx._df.get(t, 0) == self._df_recount(idx, t)

    def test_df_survives_compaction(self):
        idx = BM25Index()
        for i in range(1200):
            idx.index(f"d{i}", f"common word{i % 7}")
        for i in range(0, 1200, 2):
            idx.remove(f"d{i}")
        # force the compaction path (hot re-index triggers it)
        idx.index("fresh", "common freshterm")
        for t in list(idx._df):
            assert idx._df[t] == self._df_recount(idx, t), t

    def test_df_rebuilt_from_dict(self):
        idx = BM25Index()
        idx.index("a", "apple banana")
        idx.index("b", "apple")
        idx.remove("a")
        restored = BM25Index.from_dict(idx.to_dict())
        assert restored._df.get("apple", 0) == 1
        assert "banana" not in restored._df
        # tombstone removal still maintains counters post-restore
        restored.remove("b")
        assert "apple" not in restored._df

    def test_search_batch_matches_search(self):
        bm25, _, _ = _corpus(150)
        queries = PARITY_QUERIES[:8]
        batch = bm25.search_batch(queries, 12)
        single = [bm25.search(q, 12) for q in queries]
        assert batch == single

    def test_seed_doc_ids_uses_live_df(self):
        idx = BM25Index()
        for i in range(40):
            idx.index(f"d{i}", f"shared word{i % 5} filler{i}")
        seeds = idx.seed_doc_ids(max_seeds=16)
        assert seeds and all(s in idx for s in seeds)
        # removing every doc holding a term drops it from seed ranking
        for i in range(40):
            idx.remove(f"d{i}")
        assert idx.seed_doc_ids() == []

    def test_changed_since_and_compaction_floor(self):
        idx = BM25Index()
        idx.index("a", "one")
        gen = idx.mut_gen
        idx.index("b", "two")
        idx.index("a", "one updated")
        assert set(idx.changed_since(gen)) == {"a", "b"}
        assert idx.changed_since(idx.mut_gen) == []
        # compaction invalidates every older marker
        for i in range(1200):
            idx.index(f"d{i}", "bulk")
        for i in range(1100):
            idx.remove(f"d{i}")
        idx.index("trigger", "compact me")
        assert idx.changed_since(gen) is None

    def test_score_docs_matches_search_scores(self):
        bm25, _, _ = _corpus(120)
        q = "term1 term2 term3"
        full = dict(bm25.search(q, 120))
        some = list(full)[:10]
        scored = bm25.score_docs(tokenize(q), some)
        for eid in some:
            assert scored[eid] == pytest.approx(full[eid], rel=1e-6)


# ---------------------------------------------------------------------------
# satellite: weighted + deterministic RRF
# ---------------------------------------------------------------------------


class TestRRFDeterminism:
    def test_weights_shift_ranking(self):
        a = [("x", 1.0), ("y", 0.9)]
        b = [("y", 1.0), ("x", 0.9)]
        lex_heavy = rrf_fuse([a, b], weights=[10.0, 1.0], limit=2)
        vec_heavy = rrf_fuse([a, b], weights=[1.0, 10.0], limit=2)
        assert lex_heavy[0][0] == "x"
        assert vec_heavy[0][0] == "y"

    def test_tie_break_source_rank_then_id(self):
        # A only in source 0 at rank 1; B only in source 1 at rank 1:
        # equal fused scores — source order wins
        s0 = [("top0", 1.0), ("A", 0.5)]
        s1 = [("top1", 1.0), ("B", 0.5)]
        fused = rrf_fuse([s0, s1], limit=4)
        names = [x[0] for x in fused]
        assert names.index("A") < names.index("B")
        # equal score, same source impossible; same (source, rank)
        # impossible -> ordering is total and repeatable
        assert fused == rrf_fuse([s0, s1], limit=4)

    def test_absent_entries_contribute_nothing(self):
        fused = rrf_fuse([[("a", 1.0)], []], limit=3)
        assert [x[0] for x in fused] == ["a"]


# ---------------------------------------------------------------------------
# device BM25: host parity + freshness
# ---------------------------------------------------------------------------


class TestDeviceBM25:
    def test_parity_with_host(self):
        bm25, _, _ = _corpus(300)
        dev = DeviceBM25(bm25, min_n=1)
        assert dev.build()
        host = bm25.search_batch(PARITY_QUERIES, 15)
        devr = dev.search_batch(PARITY_QUERIES, 15)
        for h, d in zip(host, devr):
            assert [x[0] for x in h] == [x[0] for x in d]

    def test_tombstones_live_filtered_with_df_corrected(self):
        bm25, _, _ = _corpus(300)
        dev = DeviceBM25(bm25, min_n=1)
        assert dev.build()
        for i in range(0, 120, 2):
            bm25.remove(f"d{i}")
        host = bm25.search_batch(PARITY_QUERIES[:8], 15)
        devr = dev.search_batch(PARITY_QUERIES[:8], 15)
        for h, d in zip(host, devr):
            assert [x[0] for x in h] == [x[0] for x in d]
            # df correction: scores match too (idf from live counters)
            for (he, hs), (de, ds) in zip(h, d):
                assert hs == pytest.approx(ds, rel=1e-5)

    def test_read_your_writes_delta(self):
        bm25, _, _ = _corpus(300)
        dev = DeviceBM25(bm25, min_n=1)
        assert dev.build()
        bm25.index("fresh", "term1 term2 uniquefresh")
        bm25.index("d0", "term1 updated content")  # update = new slot
        host = bm25.search_batch(["term1 uniquefresh", "term1 term2"], 20)
        devr = dev.search_batch(["term1 uniquefresh", "term1 term2"], 20)
        for h, d in zip(host, devr):
            assert [x[0] for x in h] == [x[0] for x in d]
        assert any(e == "fresh" for e, _ in devr[0])

    def test_below_min_n_serves_host(self):
        bm25 = BM25Index()
        for i in range(10):
            bm25.index(f"d{i}", "tiny corpus term1")
        dev = DeviceBM25(bm25, min_n=64)
        assert not dev.build()
        assert dev.search_batch(["term1"], 5) == \
            bm25.search_batch(["term1"], 5)

    def test_k_larger_than_corpus(self):
        bm25, _, _ = _corpus(60, text_only=0, vec_only=0)
        dev = DeviceBM25(bm25, min_n=1)
        assert dev.build()
        host = bm25.search_batch(["term1 term2"], 500)
        devr = dev.search_batch(["term1 term2"], 500)
        assert [x[0] for x in host[0]] == [x[0] for x in devr[0]]


# ---------------------------------------------------------------------------
# the fused pipeline: parity corpus (acceptance)
# ---------------------------------------------------------------------------


class TestHybridParityCorpus:
    def test_single_device_parity(self):
        bm25, brute, rng = _corpus()
        fh = FusedHybrid(bm25, brute, min_n=1)
        assert fh.build()
        embs = rng.standard_normal(
            (len(PARITY_QUERIES), D)).astype(np.float32)
        _assert_parity(fh, bm25, brute, PARITY_QUERIES, embs, 30)

    def test_parity_with_weights(self):
        bm25, brute, rng = _corpus(seed=11)
        fh = FusedHybrid(bm25, brute, min_n=1)
        assert fh.build()
        qs = PARITY_QUERIES[:10]
        embs = rng.standard_normal((len(qs), D)).astype(np.float32)
        _assert_parity(fh, bm25, brute, qs, embs, 30, weights=(2.0, 0.5))
        _assert_parity(fh, bm25, brute, qs, embs, 30, weights=(0.3, 3.0))

    def test_parity_after_tombstones(self):
        bm25, brute, rng = _corpus(seed=13)
        fh = FusedHybrid(bm25, brute, min_n=1)
        assert fh.build()
        for i in range(0, 150, 3):
            bm25.remove(f"d{i}")
            brute.remove(f"d{i}")
        embs = rng.standard_normal(
            (len(PARITY_QUERIES), D)).astype(np.float32)
        _assert_parity(fh, bm25, brute, PARITY_QUERIES, embs, 30)

    def test_parity_k_exceeds_corpus(self):
        bm25, brute, rng = _corpus(80, seed=17, text_only=4, vec_only=4)
        fh = FusedHybrid(bm25, brute, min_n=1)
        assert fh.build()
        qs = PARITY_QUERIES[:6]
        embs = rng.standard_normal((len(qs), D)).astype(np.float32)
        _assert_parity(fh, bm25, brute, qs, embs, 500)

    def test_parity_small_k(self):
        bm25, brute, rng = _corpus(seed=19)
        fh = FusedHybrid(bm25, brute, min_n=1)
        assert fh.build()
        qs = PARITY_QUERIES[:8]
        embs = rng.standard_normal((len(qs), D)).astype(np.float32)
        _assert_parity(fh, bm25, brute, qs, embs, 4)

    def test_empty_vector_index_falls_back(self):
        bm25, _, rng = _corpus(100, text_only=0, vec_only=0)
        empty = BruteForceIndex()
        fh = FusedHybrid(bm25, empty, min_n=1)
        assert fh.build()
        rows = _fused_rows(fh, ["term1 term2"],
                           rng.standard_normal((1, D)), 10)
        assert rows == [None]  # host path must serve


class TestShardedParity:
    """Acceptance: the mesh shard_map pipeline is bit-identical to the
    single-device reference merge and rank-identical to the host
    reference, on the virtual 2/4-shard CPU meshes."""

    def setup_method(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs the virtual multi-device CPU mesh")

    def _run(self, shards):
        bm25, brute, rng = _corpus(600, seed=23)
        fh = FusedHybrid(bm25, brute, n_shards=shards, min_n=1)
        assert fh.build()
        assert "mesh" in fh.lex._snap  # placed on the mesh at build
        qs = PARITY_QUERIES
        embs = rng.standard_normal((len(qs), D)).astype(np.float32)
        _assert_parity(fh, bm25, brute, qs, embs, 30)

    def test_two_shards(self):
        self._run(2)

    def test_four_shards(self):
        self._run(4)

    def test_mesh_bit_identical_to_reference(self):
        import jax.numpy as jnp

        from nornicdb_tpu.ops.similarity import l2_normalize
        from nornicdb_tpu.search.hybrid_fused import (
            _fused_sharded_impl,
            _holder,
        )

        bm25, brute, rng = _corpus(600, seed=29)
        fh = FusedHybrid(bm25, brute, n_shards=2, min_n=1)
        assert fh.build()
        snap = fh.lex._snap
        qs = PARITY_QUERIES[:4]
        embs = rng.standard_normal((len(qs), D)).astype(np.float32)
        view = brute.device_view()
        m, valid = view[0], view[1]
        l2v = fh._ensure_map(snap, view[3])
        fh.lex.refresh_alive(snap)
        toks = [tokenize(q) for q in qs]
        b = len(qs)
        kq = 32
        ptr, urow, sel, avgdl = fh.lex.plan(snap, toks, b)
        args = (jnp.asarray(ptr), jnp.asarray(urow), jnp.asarray(sel),
                snap["post_doc"], snap["post_tf"], snap["doc_len"],
                snap["alive"], l2v, jnp.float32(avgdl),
                l2_normalize(jnp.asarray(embs)))
        tail = (jnp.asarray(np.full(b, 30, np.int32)),
                jnp.asarray(np.ones(b, np.float32)),
                jnp.asarray(np.ones(b, np.float32)))
        mp, vp = fh._vec_arrays(m, valid, snap)
        mesh_out = _fused_sharded_impl(
            *args, mp, vp, *tail, kq=kq, rrf_k=60,
            mesh_holder=_holder(snap["mesh"]))
        loop_out = fh._shard_loop(snap, args, m, valid, tail, kq)
        for a_arr, b_arr in zip(mesh_out, loop_out):
            a_np, b_np = np.asarray(a_arr), np.asarray(b_arr)
            if a_np.dtype.kind == "f":
                np.testing.assert_array_equal(
                    a_np.view(np.int32), b_np.view(np.int32))
            else:
                np.testing.assert_array_equal(a_np, b_np)


# ---------------------------------------------------------------------------
# freshness: read-your-writes + rebuild ladder
# ---------------------------------------------------------------------------


class TestHybridFreshness:
    def test_read_your_writes_upsert_visible(self):
        bm25, brute, rng = _corpus(seed=31)
        fh = FusedHybrid(bm25, brute, min_n=1)
        assert fh.build()
        builds_before = fh.lex.builds
        bm25.index("fresh", "term1 term2 veryfreshterm")
        brute.add("fresh", rng.standard_normal(D).astype(np.float32))
        qs = ["term1 veryfreshterm"]
        embs = rng.standard_normal((1, D)).astype(np.float32)
        _assert_parity(fh, bm25, brute, qs, embs, 20)
        rows = _fused_rows(fh, qs, embs, 20)
        assert any(e == "fresh" for e, _ in rows[0]["lex"])
        assert fh.lex.builds == builds_before  # no rebuild needed

    def test_update_replaces_old_slot(self):
        bm25, brute, rng = _corpus(seed=37)
        fh = FusedHybrid(bm25, brute, min_n=1)
        assert fh.build()
        bm25.index("d1", "term50 term51 replacedcontent")
        qs = ["term50 replacedcontent", "term1 term2 term3"]
        embs = rng.standard_normal((2, D)).astype(np.float32)
        rows = _fused_rows(fh, qs, embs, 25)
        for row in rows:
            ids = [e for e, _ in row["lex"]]
            assert len(ids) == len(set(ids)), "duplicate id served"
        _assert_parity(fh, bm25, brute, qs, embs, 25)

    def test_churn_kicks_background_rebuild(self):
        bm25, brute, rng = _corpus(200, seed=41, text_only=0, vec_only=0)
        fh = FusedHybrid(bm25, brute, min_n=1, rebuild_stale_frac=0.05)
        assert fh.build()
        for i in range(60):
            bm25.index(f"churn{i}", f"term1 churnword{i % 5}")
        embs = rng.standard_normal((1, D)).astype(np.float32)
        _fused_rows(fh, ["term1"], embs, 10)
        # the rebuild runs on a daemon thread; wait for it to land
        import time as _t

        deadline = _t.time() + 10
        while fh.lex.builds < 2 and _t.time() < deadline:
            _t.sleep(0.02)
        assert fh.lex.builds >= 2
        _assert_parity(fh, bm25, brute, ["term1 churnword0"], embs, 10)

    def test_midrequest_bm25_compaction_detected_by_slot_guard(self):
        """A compaction that lands AFTER a request's changelog check
        must not let snapshot-era slot ids read the remapped alive
        array (resurrected tombstones): alive_slots pins the read to
        the snapshot's compaction generation under one lock hold."""
        from nornicdb_tpu.search.device_bm25 import SnapshotStale

        bm25, _, _ = _corpus(200, seed=71, text_only=0, vec_only=0)
        dev = DeviceBM25(bm25, min_n=1)
        assert dev.build()
        snap = dev._snap
        # simulate the mid-request compaction: the snapshot's slot
        # space is stale the instant the counter moves
        bm25.remove("d0")  # force a refresh (gen moved)
        with bm25._lock:
            bm25.compactions += 1
        with pytest.raises(SnapshotStale):
            dev.refresh_alive(snap)
        # the public path degrades to host-exact, never wrong
        host = bm25.search_batch(["term1 term2"], 10)
        assert dev.search_batch(["term1 term2"], 10) == host

    def test_slots_of_pins_brute_generation(self):
        brute = BruteForceIndex()
        brute.add("a", np.ones(4, np.float32))
        gen = brute.mutations
        assert brute.slots_of(["a"], expect_mutations=gen) == [0]
        brute.add("b", np.ones(4, np.float32))
        # stale expectation -> None, the fused path's mis-join guard
        assert brute.slots_of(["a"], expect_mutations=gen) is None

    def test_plan_overflow_falls_back_to_host(self):
        from nornicdb_tpu.search.device_bm25 import PlanOverflow

        bm25, _, _ = _corpus(120, seed=73, text_only=0, vec_only=0)
        dev = DeviceBM25(bm25, min_n=1)
        assert dev.build()
        snap = dev._snap
        orig_c = snap["c_local"]
        # a c_local so large that any planned batch would wrap int32
        snap["c_local"] = 2**31 - 1
        try:
            with pytest.raises(PlanOverflow):
                dev.plan(snap, [("term1",)], 1)
            host = bm25.search_batch(["term1 term2"], 10)
            assert dev.search_batch(["term1 term2"], 10) == host
        finally:
            snap["c_local"] = orig_c

    def test_brute_compaction_never_misjoins(self):
        bm25, brute, rng = _corpus(seed=43, text_only=0, vec_only=0)
        fh = FusedHybrid(bm25, brute, min_n=1)
        assert fh.build()
        # force a brute compaction (slot remap) without touching bm25
        for i in range(150, 400):
            brute.remove(f"d{i}")
        brute.compact()
        qs = PARITY_QUERIES[:6]
        embs = rng.standard_normal((len(qs), D)).astype(np.float32)
        _assert_parity(fh, bm25, brute, qs, embs, 20)


# ---------------------------------------------------------------------------
# service wiring + observability
# ---------------------------------------------------------------------------


def _make_service(store, rng, n=180):
    from nornicdb_tpu.search.service import SearchService
    from nornicdb_tpu.storage.types import Node

    svc = SearchService(storage=store)
    for i in range(n):
        text = " ".join(rng.choice(VOCAB, size=int(rng.integers(3, 10))))
        node = Node(id=f"n{i}", labels=["Doc"],
                    properties={"content": text},
                    embedding=list(
                        rng.standard_normal(D).astype(np.float32)))
        store.create_node(node)
        svc.index_node(node)
    return svc


class TestServiceWiring:
    def test_fused_path_matches_host_path(self, monkeypatch):
        from nornicdb_tpu.storage import MemoryEngine

        monkeypatch.setenv("NORNICDB_HYBRID_MIN_N", "50")
        monkeypatch.setenv("NORNICDB_HYBRID_INLINE_BUILD", "1")
        rng = np.random.default_rng(47)
        store = MemoryEngine()
        svc = _make_service(store, rng)
        qv = rng.standard_normal(D).astype(np.float32)
        fused_res = svc.search("term1 term2 term3", limit=10,
                               query_embedding=qv)
        assert svc._fused is not None and svc._fused.ready
        monkeypatch.setenv("NORNICDB_HYBRID_FUSED", "0")
        svc2 = _make_service(store, np.random.default_rng(47),
                             n=0)
        for node in store.all_nodes():
            svc2.index_node(node)
        host_res = svc2.search("term1 term2 term3", limit=10,
                               query_embedding=qv)
        assert [r["id"] for r in fused_res] == \
            [r["id"] for r in host_res]
        assert [r["score"] for r in fused_res] == \
            [r["score"] for r in host_res]

    def test_weights_parity_and_cache_key(self, monkeypatch):
        from nornicdb_tpu.storage import MemoryEngine

        monkeypatch.setenv("NORNICDB_HYBRID_MIN_N", "50")
        monkeypatch.setenv("NORNICDB_HYBRID_INLINE_BUILD", "1")
        rng = np.random.default_rng(53)
        store = MemoryEngine()
        svc = _make_service(store, rng)
        qv = rng.standard_normal(D).astype(np.float32)
        r1 = svc.search("term1 term2", limit=8, query_embedding=qv,
                        weights=(4.0, 0.25))
        r2 = svc.search("term1 term2", limit=8, query_embedding=qv)
        assert [x["id"] for x in r1] != [x["id"] for x in r2] or \
            [x["score"] for x in r1] != [x["score"] for x in r2]

    def test_strategy_counter_and_small_corpus_stays_host(
            self, monkeypatch):
        from nornicdb_tpu.obs import REGISTRY
        from nornicdb_tpu.storage import MemoryEngine

        monkeypatch.setenv("NORNICDB_HYBRID_MIN_N", "50")
        monkeypatch.setenv("NORNICDB_HYBRID_INLINE_BUILD", "1")
        rng = np.random.default_rng(59)
        store = MemoryEngine()
        svc = _make_service(store, rng, n=20)  # below the floor
        qv = rng.standard_normal(D).astype(np.float32)
        svc.search("term1", limit=5, query_embedding=qv)
        assert svc._fused is None  # corpus too small
        svc2 = _make_service(MemoryEngine(), rng, n=120)
        before = _counter_value(
            REGISTRY, "nornicdb_search_strategy_total",
            {"strategy": "hybrid_fused"})
        svc2.search("term1 term2", limit=5, query_embedding=qv)
        after = _counter_value(
            REGISTRY, "nornicdb_search_strategy_total",
            {"strategy": "hybrid_fused"})
        assert after == before + 1

    def test_sharded_service_parity(self, monkeypatch):
        if len(jax.devices()) < 2:
            pytest.skip("needs the virtual multi-device CPU mesh")
        from nornicdb_tpu.storage import MemoryEngine

        monkeypatch.setenv("NORNICDB_HYBRID_MIN_N", "50")
        monkeypatch.setenv("NORNICDB_HYBRID_INLINE_BUILD", "1")
        monkeypatch.setenv("NORNICDB_HYBRID_SHARDS", "2")
        rng = np.random.default_rng(61)
        store = MemoryEngine()
        svc = _make_service(store, rng, n=300)
        qv = rng.standard_normal(D).astype(np.float32)
        res = svc.search("term1 term2 term3", limit=10,
                         query_embedding=qv)
        assert svc._fused is not None
        assert svc._fused.lex._snap["shards"] == 2
        monkeypatch.setenv("NORNICDB_HYBRID_FUSED", "0")
        svc2 = _make_service(store, rng, n=0)
        for node in store.all_nodes():
            svc2.index_node(node)
        host = svc2.search("term1 term2 term3", limit=10,
                           query_embedding=qv)
        assert [r["id"] for r in res] == [r["id"] for r in host]

    def test_hybrid_spans_recorded(self, monkeypatch):
        from nornicdb_tpu.obs import tracing
        from nornicdb_tpu.storage import MemoryEngine

        monkeypatch.setenv("NORNICDB_HYBRID_MIN_N", "50")
        monkeypatch.setenv("NORNICDB_HYBRID_INLINE_BUILD", "1")
        rng = np.random.default_rng(67)
        svc = _make_service(MemoryEngine(), rng)
        qv = rng.standard_normal(D).astype(np.float32)
        with tracing.trace("hybrid.test") as root:
            svc.search("term1 term2 term3", limit=5,
                       query_embedding=qv)
        names = root.span_names()
        assert "lexical.score" in names
        assert "fuse" in names
        assert "rerank" in names


def _counter_value(registry, name, labels):
    text = registry.render()
    label_str = ",".join(f'{k}="{v}"' for k, v in labels.items())
    needle = f"{name}{{{label_str}}} "
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.split()[-1])
    return 0.0


class TestGrpcHybridObservability:
    """Satellite: one gRPC Hybrid call shows the lexical.score -> fuse
    -> rerank ladder in /admin/traces and bumps the hybrid_fused
    strategy counter in /metrics."""

    def test_grpc_hybrid_trace_and_metrics(self, monkeypatch):
        import json as _json
        import urllib.request

        import grpc

        import nornicdb_tpu
        from nornicdb_tpu.api.grpc_server import GrpcServer
        from nornicdb_tpu.api.http_server import HttpServer
        from nornicdb_tpu.api.proto import nornic_pb2 as pb
        from nornicdb_tpu.storage.types import Node

        monkeypatch.setenv("NORNICDB_HYBRID_MIN_N", "50")
        monkeypatch.setenv("NORNICDB_HYBRID_INLINE_BUILD", "1")
        rng = np.random.default_rng(71)
        db = nornicdb_tpu.open(auto_embed=False)
        try:
            svc = db.search
            for i in range(120):
                text = " ".join(
                    rng.choice(VOCAB, size=int(rng.integers(3, 10))))
                node = Node(id=f"g{i}", labels=["Doc"],
                            properties={"content": text},
                            embedding=list(rng.standard_normal(D)
                                           .astype(np.float32)))
                db.storage.create_node(node)
                svc.index_node(node)
            grpc_srv = GrpcServer(db, port=0).start()
            http = HttpServer(db, port=0).start()
            try:
                ch = grpc.insecure_channel(grpc_srv.address)
                req = pb.HybridRequest(
                    query="term1 term2 term3",
                    vector=[float(x) for x in
                            rng.standard_normal(D)],
                    limit=5)
                resp = ch.unary_unary(
                    "/nornic.v1.SearchService/Hybrid",
                    request_serializer=lambda r: r.SerializeToString(),
                    response_deserializer=pb.SearchResponse.FromString,
                )(req)
                assert len(resp.hits) == 5
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{http.port}/admin/traces",
                        timeout=5) as r:
                    doc = _json.loads(r.read())
                hybrid = [
                    t for t in doc["traces"]
                    if t["attrs"].get("method")
                    == "/nornic.v1.SearchService/Hybrid"]
                assert hybrid, "Hybrid RPC produced no trace"

                def names(t):
                    out = [t["name"]]
                    for c in t["children"]:
                        out.extend(names(c))
                    return out

                flat = names(hybrid[0])
                assert "lexical.score" in flat
                assert "fuse" in flat
                assert "rerank" in flat
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{http.port}/metrics",
                        timeout=5) as r:
                    metrics_text = r.read().decode()
                assert ('nornicdb_search_strategy_total'
                        '{strategy="hybrid_fused"}') in metrics_text
                ch.close()
            finally:
                grpc_srv.stop()
                http.stop()
        finally:
            db.close()
