"""PackStream codec + Bolt server protocol tests.

Reference: pkg/bolt/packstream.go, server.go. Codec checked against
hand-computed byte sequences (not just round-trips) so a self-consistent
but wrong encoding can't pass; server driven by a raw socket client.
"""

import socket
import struct

import pytest

import nornicdb_tpu
from nornicdb_tpu.api.bolt import (
    BOLT_MAGIC,
    MSG_BEGIN,
    MSG_COMMIT,
    MSG_FAILURE,
    MSG_HELLO,
    MSG_PULL,
    MSG_RECORD,
    MSG_RESET,
    MSG_ROLLBACK,
    MSG_RUN,
    MSG_SUCCESS,
    BoltServer,
    read_message,
    write_message,
)
from nornicdb_tpu.api.packstream import (
    Packer,
    Structure,
    node_structure,
    pack,
    unpack,
    unpack_all,
)
from nornicdb_tpu.auth import Authenticator
from nornicdb_tpu.storage import MemoryEngine
from nornicdb_tpu.storage.txn import TransactionManager, TransactionOverlay
from nornicdb_tpu.storage.types import Edge, Node


class TestPackStreamWireFormat:
    """Exact byte layouts from the PackStream spec."""

    def test_null_bool(self):
        assert pack(None) == b"\xc0"
        assert pack(True) == b"\xc3"
        assert pack(False) == b"\xc2"

    def test_integers(self):
        assert pack(1) == b"\x01"
        assert pack(127) == b"\x7f"
        assert pack(-1) == b"\xff"
        assert pack(-16) == b"\xf0"
        assert pack(-17) == b"\xc8\xef"
        assert pack(128) == b"\xc9\x00\x80"
        assert pack(-32769) == b"\xca\xff\xff\x7f\xff"
        assert pack(2**31) == b"\xcb\x00\x00\x00\x00\x80\x00\x00\x00"

    def test_float(self):
        assert pack(1.1) == b"\xc1" + struct.pack(">d", 1.1)

    def test_strings(self):
        assert pack("") == b"\x80"
        assert pack("a") == b"\x81a"
        assert pack("hello") == b"\x85hello"
        s = "x" * 20
        assert pack(s) == b"\xd0\x14" + s.encode()

    def test_list_map(self):
        assert pack([1, 2]) == b"\x92\x01\x02"
        assert pack({"a": 1}) == b"\xa1\x81a\x01"

    def test_struct(self):
        s = Structure(0x4E, [1, ["L"], {}])
        assert pack(s) == b"\xb3\x4e\x01\x91\x81L\xa0"

    def test_roundtrip_nested(self):
        value = {"list": [1, -200, 3.5, "str", None, True],
                 "map": {"k": [{"deep": "v"}]}, "big": 2**40}
        assert unpack(pack(value)) == value

    def test_unpack_all_and_truncation(self):
        data = pack(1) + pack("two")
        assert unpack_all(data) == [1, "two"]
        with pytest.raises(ValueError):
            unpack(b"\xd1\x00")  # truncated string header

    def test_node_structure(self):
        n = Node(id="abc", labels=["Person"], properties={"name": "Ada"})
        s = node_structure(n)
        assert s.tag == 0x4E
        assert isinstance(s.fields[0], int) and s.fields[0] < 2**53
        assert s.fields[1] == ["Person"]
        assert s.fields[2]["name"] == "Ada"
        assert s.fields[2]["_id"] == "abc"  # real string id preserved


class TestTransactionOverlay:
    def test_commit_applies(self):
        base = MemoryEngine()
        tx = TransactionOverlay(base)
        tx.create_node(Node(id="a"))
        tx.create_node(Node(id="b"))
        tx.create_edge(Edge(id="e", type="R", start_node="a", end_node="b"))
        assert base.count_nodes() == 0  # invisible before commit
        assert tx.count_nodes() == 2  # read-your-writes
        tx.commit()
        assert base.count_nodes() == 2 and base.count_edges() == 1

    def test_rollback_discards(self):
        base = MemoryEngine()
        base.create_node(Node(id="keep"))
        tx = TransactionOverlay(base)
        tx.create_node(Node(id="gone"))
        tx.delete_node("keep")
        assert not tx.has_node("keep")
        tx.rollback()
        assert base.has_node("keep") and not base.has_node("gone")
        with pytest.raises(RuntimeError):
            tx.commit()  # already closed

    def test_overlay_sees_inner_and_updates(self):
        base = MemoryEngine()
        base.create_node(Node(id="n", properties={"v": 1}))
        tx = TransactionOverlay(base)
        n = tx.get_node("n")
        n.properties["v"] = 2
        tx.update_node(n)
        assert tx.get_node("n").properties["v"] == 2
        assert base.get_node("n").properties["v"] == 1
        tx.commit()
        assert base.get_node("n").properties["v"] == 2

    def test_manager_reaps(self):
        mgr = TransactionManager(timeout_seconds=0.0)
        tx = mgr.begin("s1", MemoryEngine())
        assert mgr.get("s1") is tx
        assert mgr.reap_expired() == 1
        assert mgr.get("s1") is None


# ---------------------------------------------------------------------------
# Bolt server integration via raw socket client
# ---------------------------------------------------------------------------


class BoltClient:
    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.sendall(struct.pack(">I", BOLT_MAGIC))
        # propose 4.4 then zeros
        self.sock.sendall(struct.pack(">I", (4 << 8) | 4) + b"\x00" * 12)
        chosen = struct.unpack(">I", self.sock.recv(4))[0]
        assert chosen & 0xFF == 4, f"unexpected version {chosen:#x}"

    def send(self, sig, *fields):
        p = Packer()
        p.pack(Structure(sig, list(fields)))
        write_message(self.sock, p.data())

    def recv(self):
        from nornicdb_tpu.api.packstream import Unpacker

        msg = Unpacker(read_message(self.sock)).unpack()
        return msg.tag, msg.fields

    def recv_until_success_or_failure(self):
        records = []
        while True:
            tag, fields = self.recv()
            if tag == MSG_RECORD:
                records.append(fields[0])
            else:
                return tag, fields, records

    def close(self):
        self.sock.close()


@pytest.fixture
def server():
    db = nornicdb_tpu.open()
    srv = BoltServer(db, port=0).start()
    yield srv
    srv.stop()
    db.close()


@pytest.fixture
def client(server):
    c = BoltClient(server.port)
    c.send(MSG_HELLO, {"user_agent": "test/1.0", "scheme": "none"})
    tag, fields = c.recv()
    assert tag == MSG_SUCCESS and "server" in fields[0]
    yield c
    c.close()


class TestBoltServer:
    def test_run_pull_create_and_match(self, client):
        client.send(MSG_RUN, "CREATE (n:Person {name: 'Ada'}) RETURN n", {}, {})
        tag, fields = client.recv()
        assert tag == MSG_SUCCESS and fields[0]["fields"] == ["n"]
        client.send(MSG_PULL, {"n": -1})
        tag, fields, records = client.recv_until_success_or_failure()
        assert tag == MSG_SUCCESS and len(records) == 1
        node = records[0][0]
        assert node.tag == 0x4E and node.fields[1] == ["Person"]
        assert "bookmark" in fields[0]

        client.send(MSG_RUN, "MATCH (n:Person) RETURN n.name AS name", {}, {})
        client.recv()
        client.send(MSG_PULL, {"n": -1})
        tag, fields, records = client.recv_until_success_or_failure()
        assert records == [["Ada"]]

    def test_pull_batching_has_more(self, client):
        client.send(MSG_RUN, "UNWIND range(1, 5) AS x RETURN x", {}, {})
        client.recv()
        client.send(MSG_PULL, {"n": 2})
        tag, fields, records = client.recv_until_success_or_failure()
        assert fields[0].get("has_more") is True and len(records) == 2
        client.send(MSG_PULL, {"n": -1})
        tag, fields, records = client.recv_until_success_or_failure()
        assert len(records) == 3 and "has_more" not in fields[0]

    def test_parameters(self, client):
        client.send(MSG_RUN, "RETURN $x + 1 AS y", {"x": 41}, {})
        client.recv()
        client.send(MSG_PULL, {"n": -1})
        _, _, records = client.recv_until_success_or_failure()
        assert records == [[42]]

    def test_failure_then_ignored_then_reset(self, client):
        client.send(MSG_RUN, "THIS IS NOT CYPHER", {}, {})
        tag, fields = client.recv()
        assert tag == MSG_FAILURE
        assert fields[0]["code"].startswith("Neo.ClientError")
        # messages are IGNORED until RESET
        client.send(MSG_RUN, "RETURN 1", {}, {})
        tag, _ = client.recv()
        assert tag == 0x7E  # IGNORED
        client.send(MSG_RESET)
        tag, _ = client.recv()
        assert tag == MSG_SUCCESS
        client.send(MSG_RUN, "RETURN 1 AS one", {}, {})
        tag, _ = client.recv()
        assert tag == MSG_SUCCESS

    def test_explicit_transaction_commit(self, server, client):
        client.send(MSG_BEGIN, {})
        assert client.recv()[0] == MSG_SUCCESS
        client.send(MSG_RUN, "CREATE (n:Tx {v: 1})", {}, {})
        assert client.recv()[0] == MSG_SUCCESS
        client.send(MSG_PULL, {"n": -1})
        client.recv_until_success_or_failure()
        # not visible outside the tx yet
        assert server.db.cypher("MATCH (n:Tx) RETURN count(n)").value() == 0
        client.send(MSG_COMMIT)
        tag, fields = client.recv()
        assert tag == MSG_SUCCESS and "bookmark" in fields[0]
        assert server.db.cypher("MATCH (n:Tx) RETURN count(n)").value() == 1

    def test_explicit_transaction_rollback(self, server, client):
        client.send(MSG_BEGIN, {})
        client.recv()
        client.send(MSG_RUN, "CREATE (n:Gone)", {}, {})
        client.recv()
        client.send(MSG_PULL, {"n": -1})
        client.recv_until_success_or_failure()
        client.send(MSG_ROLLBACK)
        assert client.recv()[0] == MSG_SUCCESS
        assert server.db.cypher("MATCH (n:Gone) RETURN count(n)").value() == 0

    def test_auth_required(self):
        db = nornicdb_tpu.open()
        auth = Authenticator()
        auth.create_user("ada", "pw", roles=["admin"])
        srv = BoltServer(db, port=0, authenticator=auth).start()
        try:
            c = BoltClient(srv.port)
            c.send(MSG_HELLO, {"scheme": "basic", "principal": "ada",
                               "credentials": "wrong"})
            tag, fields = c.recv()
            assert tag == MSG_FAILURE
            c.close()
            c2 = BoltClient(srv.port)
            c2.send(MSG_HELLO, {"scheme": "basic", "principal": "ada",
                                "credentials": "pw"})
            assert c2.recv()[0] == MSG_SUCCESS
            c2.close()
        finally:
            srv.stop()
            db.close()
