"""Tenant truth (ISSUE 18): end-to-end per-tenant attribution.

- resolution order at the ingress (header > propagated context >
  multidb namespace > default) and the qdrant collection mapping;
- the cardinality-capped label registry (fold past NORNICDB_TENANT_MAX
  into ``__other__``);
- contextvar propagation across the executor hop and the 4-field
  ``X-Nornic-Trace`` wire format (satellite 2 regression pin, via the
  FleetRouter RemoteReplica path);
- the leader->rider batch-mix cost split;
- worker/plane boundary: a 2-worker thread WirePlane serves
  /admin/tenants with per-tenant counters merged exactly once;
- ledger/journal/shed records carry the tenant stamp and the
  noisy-neighbor detector emits its advisory event.
"""

from __future__ import annotations

import contextvars
import json
import threading
import urllib.request

import numpy as np
import pytest

import nornicdb_tpu
from nornicdb_tpu import admission as _adm
from nornicdb_tpu import obs
from nornicdb_tpu.obs import audit, events, tenant, tracing
from nornicdb_tpu.obs.metrics import REGISTRY

D = 16


def _child(name, key):
    fam = REGISTRY.get(name)
    if fam is None:
        return 0.0
    c = fam.children().get(tuple(key))
    return float(c.value) if c is not None else 0.0


def _requests_for(tenant_name):
    """Sum of nornicdb_tenant_requests_total across surfaces."""
    fam = REGISTRY.get("nornicdb_tenant_requests_total")
    if fam is None:
        return 0.0
    return sum(float(c.value) for k, c in fam.children().items()
               if k[0] == tenant_name)


def _mk_db(n=12):
    import os

    os.environ.setdefault("NORNICDB_TPU_EMBEDDER", "hash")
    db = nornicdb_tpu.open(auto_embed=False)
    emb = db._embedder
    for i in range(n):
        db.store(f"person{i} topic{i % 3}", node_id=f"p{i}",
                 labels=["Person"],
                 properties={"name": f"person{i}"},
                 embedding=emb.embed(f"person{i} topic{i % 3}"))
    db.flush()
    return db


# ---------------------------------------------------------------------------
# resolution order
# ---------------------------------------------------------------------------


class TestResolution:
    def test_header_wins_over_namespace(self):
        assert tenant.resolve("acme", None, "movies") == ("acme", True)

    def test_propagated_context_is_explicit(self):
        ctx = {"trace_id": "feedface00000001", "tenant": "acme"}
        assert tenant.resolve(None, ctx, "movies") == ("acme", True)

    def test_namespace_fallback_is_implicit(self):
        assert tenant.resolve(None, None, "movies") == ("movies", False)

    def test_default_when_nothing(self):
        assert tenant.resolve(None, None, None) == \
            (tenant.DEFAULT_TENANT, False)

    def test_malformed_header_falls_through(self):
        # the header is client-reachable: it becomes a metric label
        # and an admin-surface string, so the charset is validated
        assert tenant.resolve("a b", None, "movies") == \
            ("movies", False)
        assert tenant.resolve("x" * 65, None, None) == \
            (tenant.DEFAULT_TENANT, False)

    def test_collection_mapping(self, monkeypatch):
        monkeypatch.setenv("NORNICDB_TENANT_COLLECTIONS",
                           "shared_docs:acme")
        tenant.reload()
        try:
            assert tenant.tenant_for_collection("shared_docs") == "acme"
            assert tenant.tenant_for_collection("beta__docs") == "beta"
            assert tenant.tenant_for_collection("plain") == "plain"
            assert tenant.tenant_for_collection("") is None
        finally:
            monkeypatch.undo()
            tenant.reload()

    def test_explicit_scope_resists_refine(self):
        with tenant.tenant_scope("acme", explicit=True):
            tenant.refine("derived")
            assert tenant.current_tenant() == "acme"
        with tenant.tenant_scope(None):
            tenant.refine("derived")
            assert tenant.current_tenant() == "derived"


# ---------------------------------------------------------------------------
# contextvar propagation + the 4-field wire format (satellite 2)
# ---------------------------------------------------------------------------


class TestPropagation:
    def test_refine_visible_across_executor_hop(self):
        """The cell is ONE shared mutable object: a refine() inside a
        copy_context()-run executor thread (the MicroBatcher hop) is
        visible at the ingress scope."""
        with tenant.tenant_scope(None):
            ctx = contextvars.copy_context()
            ctx.run(tenant.refine, "late-bound")
            assert tenant.current_tenant() == "late-bound"

    def test_pack_context_carries_tenant(self):
        ctx = {"trace_id": "feedface00000001", "surface": "http",
               "span": "wire", "tenant": "acme"}
        packed = tracing.pack_context(ctx)
        assert packed == "feedface00000001|http|wire|acme"
        assert tracing.unpack_context(packed) == ctx

    def test_three_field_header_still_parses(self):
        # pre-ISSUE-18 peers pack 3 fields; the tenant field is only
        # appended when present, so old<->new interop holds both ways
        ctx = tracing.unpack_context("feedface00000001|http|wire")
        assert ctx == {"trace_id": "feedface00000001",
                       "surface": "http", "span": "wire"}
        assert "tenant" not in tracing.pack_context(ctx)

    def test_malformed_tenant_field_dropped(self):
        ctx = tracing.unpack_context("feedface00000001|http|wire|a b")
        assert ctx is not None and "tenant" not in ctx

    def test_trace_context_reads_tenant_provider(self):
        with tenant.tenant_scope("acme", explicit=True), \
                obs.trace("wire", transport="http"):
            assert tracing.trace_context()["tenant"] == "acme"

    def test_fleet_router_hop_propagates_tenant(self):
        """Satellite 2 regression pin: a fleet-routed read reaches the
        remote node's HTTP server with the caller's tenant riding
        X-Nornic-Trace — the remote attributes its serve to the SAME
        tenant, not to its own namespace default."""
        from nornicdb_tpu.api.fleet_router import RemoteReplica
        from nornicdb_tpu.api.http_server import HttpServer

        db = _mk_db()
        srv = HttpServer(db, port=0).start()
        try:
            replica = RemoteReplica(
                "n1", f"http://127.0.0.1:{srv.port}")
            before = _requests_for("hop-tenant")
            with tenant.tenant_scope("hop-tenant", explicit=True), \
                    obs.trace("wire", transport="http"):
                doc = replica.search({"query": "person1 topic1",
                                      "limit": 2})
            assert doc.get("results") is not None
            assert _requests_for("hop-tenant") > before
        finally:
            srv.stop()
            db.close()


# ---------------------------------------------------------------------------
# cardinality cap
# ---------------------------------------------------------------------------


class TestRegistryCap:
    def test_folding_past_cap(self, monkeypatch):
        monkeypatch.setenv("NORNICDB_TENANT_MAX", "2")
        tenant.reload()
        try:
            fold0 = _child("nornicdb_tenant_folded_total", ())
            for i in range(4):
                with tenant.tenant_scope(f"cap-t{i}", explicit=True):
                    tenant.record_served("http", "host")
            known = tenant.known_tenants()
            assert len(known) == 2
            assert known == ["cap-t0", "cap-t1"]
            # the two over-cap tenants folded into __other__
            assert _child("nornicdb_tenant_folded_total",
                          ()) == fold0 + 2
            assert _child("nornicdb_tenant_requests_total",
                          (tenant.OTHER_TENANT, "http")) >= 2
            # known names stay stable: a repeat does NOT fold
            with tenant.tenant_scope("cap-t1", explicit=True):
                tenant.record_served("http", "host")
            assert _child("nornicdb_tenant_folded_total",
                          ()) == fold0 + 2
        finally:
            monkeypatch.undo()
            tenant.reload()


# ---------------------------------------------------------------------------
# leader->rider batch-mix split
# ---------------------------------------------------------------------------


class TestBatchMix:
    def test_cost_splits_across_riders_by_tenant(self):
        fa = _child("nornicdb_tenant_cost_flops_total", ("mix-a",))
        fb = _child("nornicdb_tenant_cost_flops_total", ("mix-b",))
        with tenant.batch_scope(["mix-a", "mix-a", "mix-b"]):
            tenant.record_cost(queries=3, flops=300.0, bytes_=30.0)
        assert _child("nornicdb_tenant_cost_flops_total",
                      ("mix-a",)) == pytest.approx(fa + 200.0)
        assert _child("nornicdb_tenant_cost_flops_total",
                      ("mix-b",)) == pytest.approx(fb + 100.0)

    def test_serves_distribute_and_scope_nests(self):
        ra = _child("nornicdb_tenant_requests_total", ("mix-a", "vector"))
        with tenant.batch_scope(["mix-a", "mix-b"]):
            with tenant.batch_scope(["mix-a"]):
                tenant.record_served("vector", "host", n=1)
            # inner scope restored: the outer mix splits again
            tenant.record_served("vector", "host", n=2)
        assert _child("nornicdb_tenant_requests_total",
                      ("mix-a", "vector")) == pytest.approx(ra + 2.0)

    def test_unattributed_rider_counts_as_unattributed(self):
        u0 = _child("nornicdb_tenant_requests_total",
                    (tenant.UNATTRIBUTED, "vector"))
        with tenant.batch_scope([None, "mix-a"]):
            tenant.record_served("vector", "host", n=2)
        assert _child("nornicdb_tenant_requests_total",
                      (tenant.UNATTRIBUTED, "vector")) == \
            pytest.approx(u0 + 1.0)


# ---------------------------------------------------------------------------
# multidb namespace -> tenant at the HTTP ingress (satellite 3)
# ---------------------------------------------------------------------------


class TestHttpIngress:
    @pytest.fixture()
    def server(self):
        from nornicdb_tpu.api.http_server import HttpServer
        from nornicdb_tpu.multidb import DatabaseManager
        from nornicdb_tpu.storage import MemoryEngine

        db = _mk_db()
        mgr = DatabaseManager(MemoryEngine())
        mgr.create_database("movies", if_not_exists=True)
        srv = HttpServer(db, port=0, database_manager=mgr).start()
        yield db, srv
        srv.stop()
        db.close()

    def _post(self, port, path, doc, headers=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json",
                     **(headers or {})})
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read())

    def test_default_database_is_the_fallback_tenant(self, server):
        db, srv = server
        # no header, non-multidb path: the server's default database
        # namespace is the implicit tenant
        before = _requests_for(srv.default_database)
        status, doc = self._post(srv.port, "/nornicdb/search",
                                 {"query": "person1", "limit": 2})
        assert status == 200
        assert _requests_for(srv.default_database) > before

    def test_namespace_route_names_the_tenant(self, server):
        db, srv = server
        before = _requests_for("movies")
        status, doc = self._post(
            srv.port, "/db/movies/tx/commit",
            {"statements": [{"statement": "RETURN 1"}]})
        assert status == 200
        assert _requests_for("movies") > before

    def test_header_overrides_namespace(self, server):
        db, srv = server
        before_h = _requests_for("hdr-tenant")
        before_ns = _requests_for("movies")
        status, doc = self._post(
            srv.port, "/db/movies/tx/commit",
            {"statements": [{"statement": "RETURN 1"}]},
            headers={tenant.TENANT_HEADER: "hdr-tenant"})
        assert status == 200
        assert _requests_for("hdr-tenant") > before_h
        assert _requests_for("movies") == before_ns

    def test_admin_tenants_rollup(self, server):
        db, srv = server
        self._post(srv.port, "/nornicdb/search",
                   {"query": "person2", "limit": 2},
                   headers={tenant.TENANT_HEADER: "rollup-t"})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/admin/tenants",
                timeout=15) as r:
            doc = json.loads(r.read())
        assert doc["cap"] >= 1 and doc["total"] >= 1
        names = [t["tenant"] for t in doc["tenants"]]
        assert "rollup-t" in names
        row = doc["tenants"][names.index("rollup-t")]
        assert row["requests"] > 0 and "cost" in row
        assert "noisy_neighbor" in doc
        # the same block rides /admin/telemetry
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/admin/telemetry",
                timeout=15) as r:
            tdoc = json.loads(r.read())
        assert "tenants" in tdoc


# ---------------------------------------------------------------------------
# worker/plane boundary: merged exactly once (satellite 3)
# ---------------------------------------------------------------------------


class TestWirePlaneMerge:
    def test_two_worker_scrape_merges_tenant_counters_once(self):
        from nornicdb_tpu.api.wire_plane import WirePlane

        db = _mk_db()
        plane = WirePlane(db, workers=2, mode="thread").start()
        try:
            body = json.dumps({"query": "person1 topic1",
                               "limit": 2}).encode()
            sent = 3
            for _ in range(sent):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{plane.http_port}"
                    "/nornicdb/search", data=body,
                    headers={"Content-Type": "application/json",
                             tenant.TENANT_HEADER: "plane-t"})
                with urllib.request.urlopen(req, timeout=15) as r:
                    assert r.status == 200
            # worker-served /admin/tenants over the merged view: the
            # tenant appears exactly once with the exact request count
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{plane.http_port}"
                    "/admin/tenants", timeout=15) as r:
                doc = json.loads(r.read())
            rows = [t for t in doc["tenants"]
                    if t["tenant"] == "plane-t"]
            assert len(rows) == 1
            # merged exactly once: the rollup equals the registry's
            # own ground truth (a double merge would double it), and
            # every one of the posted requests was attributed
            assert rows[0]["requests"] == \
                pytest.approx(_requests_for("plane-t"))
            assert rows[0]["requests"] >= sent
            # the scrape shows the family exactly once too
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{plane.http_port}/metrics",
                    timeout=15) as r:
                text = r.read().decode()
            assert text.count(
                "# TYPE nornicdb_tenant_requests_total") == 1
        finally:
            plane.stop()
            db.close()


# ---------------------------------------------------------------------------
# tenant stamps on ledger / journal / shed + the detector
# ---------------------------------------------------------------------------


class TestStampsAndDetector:
    def test_degrade_record_carries_tenant(self):
        with tenant.tenant_scope("stamp-t", explicit=True):
            audit.record_degrade("vector", "device_ann",
                                 "vector_brute_f32", "fallback")
        recs = [r for r in audit.LEDGER.snapshot(limit=10)
                if r.get("tenant") == "stamp-t"]
        assert recs and recs[-1]["surface"] == "vector"

    def test_shed_counts_per_tenant_and_stamps(self):
        before = _child("nornicdb_tenant_shed_total",
                        ("shed-t", "http", "shed"))
        with tenant.tenant_scope("shed-t", explicit=True):
            _adm.record_shed("http", "interactive", "shed",
                             retry_after_s=0.5)
        assert _child("nornicdb_tenant_shed_total",
                      ("shed-t", "http", "shed")) == before + 1
        recs = [r for r in audit.LEDGER.snapshot(limit=10)
                if r.get("tenant") == "shed-t"]
        assert recs and recs[-1]["to_tier"] == audit.TIER_SHED

    def test_journal_events_stamp_tenant(self):
        with tenant.tenant_scope("ev-t", explicit=True):
            events.record_event("degrade", surface="vector",
                                reason="fallback")
        evs = [e for e in events.event_snapshot(limit=20)
               if e.get("tenant") == "ev-t"]
        assert evs and evs[-1]["kind"] == "degrade"

    def test_noisy_neighbor_advisory_event(self):
        tenant.DETECTOR.reset()
        saved = tenant._posture_provider
        tenant.set_posture_provider(lambda: 1)  # degrade posture
        try:
            flops = tenant.cfg()["noisy_min_flops"] * 2
            with tenant.tenant_scope("flood-t", explicit=True):
                tenant.record_cost(queries=1, flops=flops, bytes_=0.0)
            evs = [e for e in events.event_snapshot(limit=20)
                   if e["kind"] == "noisy_neighbor"]
            assert evs, "no advisory event emitted"
            ev = evs[-1]
            assert ev["detail"]["tenant"] == "flood-t"
            assert ev["detail"]["cost_share"] >= \
                tenant.cfg()["noisy_share"]
            emitted = tenant.DETECTOR.emitted
            # cooldown: an immediate repeat does not double-journal
            with tenant.tenant_scope("flood-t", explicit=True):
                tenant.record_cost(queries=1, flops=flops, bytes_=0.0)
            assert tenant.DETECTOR.emitted == emitted
        finally:
            tenant.set_posture_provider(saved)
            tenant.DETECTOR.reset()

    def test_admit_posture_never_accuses(self):
        tenant.DETECTOR.reset()
        saved = tenant._posture_provider
        tenant.set_posture_provider(lambda: 0)  # healthy
        before = tenant.DETECTOR.emitted
        try:
            flops = tenant.cfg()["noisy_min_flops"] * 2
            with tenant.tenant_scope("quiet-t", explicit=True):
                tenant.record_cost(queries=1, flops=flops, bytes_=0.0)
            assert tenant.DETECTOR.emitted == before
        finally:
            tenant.set_posture_provider(saved)
            tenant.DETECTOR.reset()


# ---------------------------------------------------------------------------
# summary math
# ---------------------------------------------------------------------------


class TestSummary:
    def test_attribution_completeness_math(self):
        state = {"nornicdb_tenant_requests_total": {
            "name": "nornicdb_tenant_requests_total",
            "kind": "counter", "help": "", "labels": ("tenant",
                                                      "surface"),
            "children": {("acme", "http"): 3.0,
                         (tenant.UNATTRIBUTED, "http"): 1.0}}}
        assert tenant.attribution_completeness(state) == \
            pytest.approx(0.75)
        assert tenant.attribution_completeness({}) is None

    def test_summary_top_k_orders_by_cost(self):
        state = {
            "nornicdb_tenant_requests_total": {
                "name": "nornicdb_tenant_requests_total",
                "kind": "counter", "help": "",
                "labels": ("tenant", "surface"),
                "children": {("a", "http"): 5.0, ("b", "http"): 1.0}},
            "nornicdb_tenant_cost_flops_total": {
                "name": "nornicdb_tenant_cost_flops_total",
                "kind": "counter", "help": "", "labels": ("tenant",),
                "children": {("a",): 10.0, ("b",): 90.0}},
        }
        doc = tenant.tenants_summary(state=state, top=1)
        assert [t["tenant"] for t in doc["tenants"]] == ["b"]
        assert doc["tenants"][0]["cost_share"] == pytest.approx(0.9)
        assert doc["total"] == 2  # both tenants known, one shown
        assert doc["merged"] is True  # state passed -> flagged merged


# ---------------------------------------------------------------------------
# satellite 1: the TENANT_FAMILIES lint rule (nornic-lint
# metrics-catalog pass)
# ---------------------------------------------------------------------------


class TestTenantFamilyLintRule:
    def test_live_registry_has_no_drift(self):
        """Every registered tenant-labeled family is declared and
        every declaration is live — the committed-tree contract."""
        from nornicdb_tpu.lint.metrics_catalog import tenant_family_drift

        undeclared, stale = tenant_family_drift()
        assert undeclared == []
        assert stale == []

    def test_undeclared_tenant_family_is_flagged(self):
        """A new family that sneaks a ``tenant`` label past the
        declaration registry is the exact hazard the rule exists
        for — pin that it drifts."""
        from nornicdb_tpu.lint.metrics_catalog import tenant_family_drift

        REGISTRY.counter(
            "nornicdb_tenant_lintfixture_total",
            "fixture", labels=("tenant",))
        try:
            undeclared, _ = tenant_family_drift()
            assert "nornicdb_tenant_lintfixture_total" in undeclared
        finally:
            REGISTRY._families.pop(
                "nornicdb_tenant_lintfixture_total", None)

    def test_stale_declaration_is_flagged(self, monkeypatch):
        from nornicdb_tpu.lint import config as lint_config
        from nornicdb_tpu.lint.metrics_catalog import tenant_family_drift

        monkeypatch.setattr(
            lint_config, "TENANT_FAMILIES",
            lint_config.TENANT_FAMILIES + ("nornicdb_tenant_gone_total",))
        _, stale = tenant_family_drift()
        assert stale == ["nornicdb_tenant_gone_total"]

    def test_pass_emits_findings_anchored_to_config(self, monkeypatch):
        """The framework pass turns drift into findings the CLI
        surfaces, anchored at lint/config.py (the file to edit)."""
        from nornicdb_tpu.lint import config as lint_config
        from nornicdb_tpu.lint import metrics_catalog as mc
        from nornicdb_tpu.lint.astutil import PackageTree

        declared = lint_config.TENANT_FAMILIES
        assert declared, "registry must not be empty"
        monkeypatch.setattr(
            lint_config, "TENANT_FAMILIES", declared[1:])
        import os
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(nornicdb_tpu.__file__)))
        tree = PackageTree(root=repo, modules={})
        findings = mc.run(tree)
        rules = {(f.rule, f.detail) for f in findings}
        assert ("undeclared-tenant-family", declared[0]) in rules
        anchored = [f for f in findings
                    if f.rule == "undeclared-tenant-family"]
        assert all(f.path == "nornicdb_tpu/lint/config.py"
                   for f in anchored)
