"""APOC graph/algorithm long tail (apoc_graph.py + apoc_algo.py).

Graph fixture: two directed triangles 0->1->2->0 and 3->4->5->3 joined
by a one-way bridge 2->3, plus an isolated node 6.
"""

import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


@pytest.fixture()
def ex():
    ex = CypherExecutor(NamespacedEngine(MemoryEngine(), "algo"))
    for i in range(7):
        ex.execute("CREATE (:N {id: $i, name: $n})",
                   {"i": i, "n": f"node{i}"})
    for a, b in [(0, 1), (1, 2), (2, 0), (3, 4), (4, 5), (5, 3), (2, 3)]:
        ex.execute(
            "MATCH (x:N {id:$a}), (y:N {id:$b}) "
            "CREATE (x)-[:R {weight: 1}]->(y)", {"a": a, "b": b})
    return ex


def q1(ex, s, p=None):
    return ex.execute(s, p or {}).rows[0][0]


def _by_id(results, value_key):
    return {d["node"].properties["id"]: d[value_key] for d in results}


class TestCommunity:
    def test_components(self, ex):
        assert q1(ex, "RETURN apoc.community.numComponents()") == 2
        cc = _by_id(q1(ex, "RETURN apoc.community.connectedComponents()"),
                    "communityId")
        assert cc[0] == cc[5] and cc[6] != cc[0]
        wcc = _by_id(
            q1(ex, "RETURN apoc.community.weaklyConnectedComponents()"),
            "communityId")
        assert wcc == cc

    def test_scc_respects_direction(self, ex):
        scc = _by_id(
            q1(ex, "RETURN apoc.community.stronglyConnectedComponents()"),
            "communityId")
        assert scc[0] == scc[1] == scc[2]
        assert scc[3] == scc[4] == scc[5]
        assert scc[0] != scc[3]  # the 2->3 bridge is one-way

    def test_triangles_and_clustering(self, ex):
        assert q1(ex, "RETURN apoc.community.totalTriangles()") == 2
        tri = _by_id(q1(ex, "RETURN apoc.community.triangleCount()"),
                     "triangles")
        assert tri[0] == 1 and tri[6] == 0
        cl = _by_id(
            q1(ex, "RETURN apoc.community.clusteringCoefficient()"),
            "coefficient")
        assert cl[0] == 1.0 and cl[6] == 0.0
        assert 0 < q1(
            ex, "RETURN apoc.community.averageClusteringCoefficient()") < 1

    def test_louvain_and_labelprop(self, ex):
        comm = _by_id(q1(ex, "RETURN apoc.community.louvain()"),
                      "communityId")
        assert comm[0] == comm[1] == comm[2]
        assert comm[3] == comm[4] == comm[5]
        assert len(q1(ex, "RETURN apoc.community.labelPropagation()")) == 7
        # reference aliases (community.go:803,1056)
        assert len(q1(ex, "RETURN apoc.community.infomap()")) == 7
        assert len(q1(ex, "RETURN apoc.community.walktrap()")) == 7

    def test_density_kcore_conductance(self, ex):
        assert q1(ex, "RETURN apoc.community.density()") == \
            pytest.approx(2 * 7 / (7 * 6))
        core = _by_id(q1(ex, "RETURN apoc.community.coreNumber()"),
                      "coreNumber")
        assert core[0] == 2 and core[6] == 0
        assert len(q1(ex, "RETURN apoc.community.kcore(2)")) == 6
        cond = q1(ex, "MATCH (n:N) WHERE n.id < 3 WITH collect(n) AS c "
                      "RETURN apoc.community.conductance(c)")
        assert 0 < cond < 1

    def test_modularity(self, ex):
        assert q1(ex, "RETURN apoc.community.modularity()") > 0


class TestPaths:
    def test_distance_and_exists(self, ex):
        # directed: 0 -> 1 -> 2 -> 3 -> 4 -> 5
        assert q1(ex, "MATCH (a:N {id:0}), (b:N {id:5}) "
                      "RETURN apoc.paths.distance(a, b)") == 5
        assert q1(ex, "MATCH (a:N {id:0}), (b:N {id:6}) "
                      "RETURN apoc.paths.distance(a, b)") is None
        assert q1(ex, "MATCH (a:N {id:0}), (b:N {id:6}) "
                      "RETURN apoc.paths.exists(a, b)") is False
        assert q1(ex, "MATCH (a:N {id:3}), (b:N {id:0}) "
                      "RETURN apoc.paths.exists(a, b)") is False  # one-way

    def test_shortest_and_k(self, ex):
        sp = q1(ex, "MATCH (a:N {id:0}), (b:N {id:3}) "
                    "RETURN apoc.paths.shortest(a, b)")
        assert len(sp) == 4  # 0,1,2,3
        ks = q1(ex, "MATCH (a:N {id:0}), (b:N {id:3}) "
                    "RETURN apoc.paths.kShortest(a, b, 2)")
        assert len(ks) >= 1 and len(ks[0]) == 4

    def test_cycles_and_eulerian(self, ex):
        cy = q1(ex, "MATCH (a:N {id:0}) RETURN apoc.paths.cycles(a)")
        assert any(len(c) == 4 for c in cy)  # the triangle
        assert q1(ex, "RETURN apoc.paths.eulerian()") is False

    def test_common_neighbors(self, ex):
        common = q1(ex, "MATCH (a:N {id:0}), (b:N {id:1}) "
                        "RETURN apoc.paths.common(a, b)")
        assert len(common) == 1  # node 2 neighbors both


class TestAlgo:
    def test_dijkstra(self, ex):
        dj = q1(ex, "MATCH (a:N {id:0}), (b:N {id:5}) "
                    "RETURN apoc.algo.dijkstra(a, b)")
        assert dj["weight"] == 5.0 and len(dj["path"]) == 6
        assert q1(ex, "MATCH (a:N {id:3}), (b:N {id:0}) "
                      "RETURN apoc.algo.dijkstra(a, b)") is None

    def test_astar_falls_back_without_coords(self, ex):
        res = q1(ex, "MATCH (a:N {id:0}), (b:N {id:3}) "
                     "RETURN apoc.algo.astar(a, b)")
        assert res["weight"] == 3.0

    def test_centralities(self, ex):
        bw = _by_id(q1(ex, "RETURN apoc.algo.betweennessCentrality()"),
                    "centrality")
        assert bw[2] > bw[0]  # bridge endpoint is most central
        assert bw[6] == 0.0
        dc = _by_id(q1(ex, "RETURN apoc.algo.degreeCentrality()"),
                    "centrality")
        assert dc[2] > dc[6] == 0.0
        cl = _by_id(q1(ex, "RETURN apoc.algo.closenessCentrality()"),
                    "centrality")
        assert cl[0] > 0.0 and cl[6] == 0.0

    def test_pagerank_sums_to_one(self, ex):
        pr = q1(ex, "RETURN apoc.algo.pagerank()")
        assert sum(d["score"] for d in pr) == pytest.approx(1.0, abs=1e-6)

    def test_cover_and_allpairs(self, ex):
        cov = q1(ex, "MATCH (n:N) WHERE n.id IN [0,1,2] "
                     "WITH collect(n) AS c RETURN apoc.algo.cover(c)")
        assert len(cov) == 3  # the triangle's edges
        ap = q1(ex, "RETURN apoc.algo.allPairs()")
        assert {"source", "target", "distance"} <= set(ap[0].keys())


class TestGraphSurface:
    def test_node_functions(self, ex):
        assert q1(ex, "MATCH (a:N {id:2}) RETURN apoc.node.degree(a)") == 3
        assert q1(ex, "MATCH (a:N {id:2}) "
                      "RETURN apoc.node.degreeOut(a)") == 2
        assert q1(ex, "MATCH (a:N {id:2}) "
                      "RETURN apoc.node.relationshipTypes(a)") == ["R"]
        assert q1(ex, "MATCH (a:N {id:2}), (b:N {id:3}) "
                      "RETURN apoc.node.connected(a, b)") is True
        ns = q1(ex, "MATCH (a:N {id:2}) RETURN apoc.node.neighbors(a)")
        assert sorted(n.properties["id"] for n in ns) == [0, 1, 3]

    def test_rel_functions(self, ex):
        assert q1(ex, "MATCH (:N {id:0})-[r]->(:N {id:1}) "
                      "RETURN apoc.rel.startNode(r).id") == 0
        assert q1(ex, "MATCH (:N {id:0})-[r]->(:N {id:1}) "
                      "RETURN apoc.rel.isLoop(r)") is False
        assert q1(ex, "MATCH (a:N {id:0})-[r]->(b:N {id:1}) "
                      "RETURN apoc.rel.otherNode(r, a).id") == 1

    def test_label_functions(self, ex):
        assert q1(ex, "RETURN apoc.label.count('N')") == 7
        assert q1(ex, "RETURN apoc.label.list()") == ["N"]
        assert q1(ex, "MATCH (a:N {id:0}) "
                      "RETURN apoc.label.format(a)") == ":N"

    def test_neighbors_hops(self, ex):
        assert q1(ex, "MATCH (a:N {id:0}) "
                      "RETURN apoc.neighbors.count(a, 'R>', 2)") == 2
        at2 = q1(ex, "MATCH (a:N {id:0}) "
                     "RETURN apoc.neighbors.atHop(a, 'R>', 2)")
        assert [n.properties["id"] for n in at2] == [2]

    def test_meta(self, ex):
        st = q1(ex, "RETURN apoc.meta.stats()")
        assert st["nodeCount"] == 7 and st["relCount"] == 7
        assert q1(ex, "RETURN apoc.meta.nodeLabels()") == ["N"]
        assert q1(ex, "RETURN apoc.meta.relTypes()") == ["R"]
        props = q1(ex, "RETURN apoc.meta.nodeTypeProperties()")
        assert {"nodeType": "N", "propertyName": "id"} in props

    def test_search(self, ex):
        assert len(q1(ex, "RETURN apoc.search.prefix('N', 'name', 'node')")
                   ) == 7
        assert q1(ex, "RETURN apoc.search.didYouMean('N', 'name', "
                      "'node00', 1)") == ["node0"]
        r = q1(ex, "RETURN apoc.search.range('N', 'id', 2, 4)")
        assert sorted(n.properties["id"] for n in r) == [2, 3, 4]

    def test_label_exists_keeps_node_form(self, ex):
        """Regression: the ctx table must not shadow the original
        apoc.label.exists(node, label)."""
        assert q1(ex, "MATCH (a:N {id:0}) "
                      "RETURN apoc.label.exists(a, 'N')") is True

    def test_json_set_through_lists(self, ex):
        assert q1(ex, "RETURN apoc.json.set({a: [{b: 1}]}, "
                      "'$.a[0].b', 2)") == {"a": [{"b": 2}]}
        assert q1(ex, "RETURN apoc.json.delete({a: [1, 2, 3]}, "
                      "'$.a[1]')") == {"a": [1, 3]}

    def test_neighbors_one_way_type_checked(self, ex):
        from nornicdb_tpu.errors import CypherRuntimeError

        with pytest.raises(CypherRuntimeError, match="expects a node"):
            ex.execute("RETURN apoc.node.neighborsIn(42)")
        out = q1(ex, "MATCH (a:N {id:2}) "
                     "RETURN apoc.node.neighborsOut(a)")
        assert sorted(n.properties["id"] for n in out) == [0, 3]

    def test_spatial(self, ex):
        d = q1(ex, "RETURN apoc.spatial.haversineDistance("
                   "{latitude: 59.91, longitude: 10.75}, "
                   "{latitude: 60.39, longitude: 5.32})")
        assert 295_000 < d < 320_000  # Oslo-Bergen ~305 km
        gh = q1(ex, "RETURN apoc.spatial.encodeGeohash("
                    "{latitude: 57.64911, longitude: 10.40744}, 11)")
        assert gh == "u4pruydqqvj"
        dec = q1(ex, "RETURN apoc.spatial.decodeGeohash('u4pruydqqvj')")
        assert dec["latitude"] == pytest.approx(57.64911, abs=1e-3)
        v = q1(ex, "RETURN apoc.spatial.vincentyDistance("
                   "{latitude: 0, longitude: 0}, "
                   "{latitude: 0, longitude: 1})")
        assert v == pytest.approx(111_319.49, rel=1e-3)
