"""APOC IO/orchestration tail (apoc_io.py): cypher subqueries,
export/import round trips, loaders, virtual graphs, triggers, periodic
registry, and category leftovers."""

import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


@pytest.fixture()
def ex():
    ex = CypherExecutor(NamespacedEngine(MemoryEngine(), "io"))
    ex.execute("CREATE (:P {id: 1, name: 'a'})-[:R {w: 1}]->"
               "(:P {id: 2, name: 'b'})")
    return ex


def q1(ex, s, p=None):
    return ex.execute(s, p or {}).rows[0][0]


class TestCypherSubqueries:
    def test_run_and_first_column(self, ex):
        rows = q1(ex, "RETURN apoc.cypher.run("
                      "'MATCH (p:P) RETURN p.id AS id ORDER BY id')")
        assert [r["id"] for r in rows] == [1, 2]
        assert q1(ex, "RETURN apoc.cypher.runFirstColumnSingle("
                      "'MATCH (p:P) RETURN count(p)')") == 2
        assert q1(ex, "RETURN apoc.cypher.runFirstColumnMany("
                      "'MATCH (p:P) RETURN p.id ORDER BY p.id')") == [1, 2]

    def test_run_many(self, ex):
        out = q1(ex, "RETURN apoc.cypher.runMany("
                     "'RETURN 1 AS a; RETURN 2 AS b')")
        assert len(out) == 2 and out[1]["rows"] == [[2]]

    def test_validate_and_parse(self, ex):
        assert q1(ex, "RETURN apoc.cypher.validate('MATCH (n RETURN n')")
        p = q1(ex, "RETURN apoc.cypher.parse('MATCH (n) RETURN n')")
        assert p["clauses"] == ["MatchClause", "ReturnClause"]

    def test_subquery_sees_writes_not_cached(self, ex):
        n0 = q1(ex, "RETURN apoc.cypher.runFirstColumnSingle("
                    "'MATCH (p:P) RETURN count(p)')")
        ex.execute("CREATE (:P {id: 3})")
        n1 = q1(ex, "RETURN apoc.cypher.runFirstColumnSingle("
                    "'MATCH (p:P) RETURN count(p)')")
        assert (n0, n1) == (2, 3)


class TestExportImport:
    def test_json_round_trip(self, ex):
        js = q1(ex, "RETURN apoc.export.jsonAll()")
        ex2 = CypherExecutor(NamespacedEngine(MemoryEngine(), "io2"))
        out = ex2.execute("RETURN apoc.import.json($j)",
                          {"j": js}).rows[0][0]
        assert out == {"nodes": 2, "relationships": 1}
        assert ex2.execute("MATCH (:P {id:1})-[r:R]->(:P {id:2}) "
                           "RETURN r.w").rows == [["1"]] or \
            ex2.execute("MATCH (:P)-[r:R]->(:P) RETURN count(r)"
                        ).rows == [[1]]

    def test_graphml_round_trip(self, ex):
        gml = q1(ex, "RETURN apoc.export.graphmlAll()")
        ex2 = CypherExecutor(NamespacedEngine(MemoryEngine(), "io3"))
        out = ex2.execute("RETURN apoc.import.graphml($g)",
                          {"g": gml}).rows[0][0]
        assert out["nodes"] == 2 and out["relationships"] == 1

    def test_csv_round_trip(self, ex):
        csvs = q1(ex, "RETURN apoc.export.csvAll()")
        assert "_id,_labels" in csvs["nodes"]
        ex2 = CypherExecutor(NamespacedEngine(MemoryEngine(), "io4"))
        out = ex2.execute(
            "RETURN apoc.import.csv($n, $r)",
            {"n": csvs["nodes"], "r": csvs["relationships"]}).rows[0][0]
        assert out["nodes"] == 2 and out["relationships"] == 1

    def test_cypher_script_export(self, ex):
        script = q1(ex, "RETURN apoc.export.cypherAll()")
        assert "CREATE" in script and "_import_id" in script
        ex2 = CypherExecutor(NamespacedEngine(MemoryEngine(), "io5"))
        out = ex2.execute("RETURN apoc.import.cypher($s)",
                          {"s": script}).rows[0][0]
        assert out["statements"] == 3
        assert ex2.execute("MATCH (:P)-[r:R]->(:P) RETURN count(r)"
                           ).rows == [[1]]

    def test_import_helpers(self, ex):
        assert q1(ex, "RETURN apoc.import.parseCsvLine('a,\"b,c\",d')"
                  ) == ["a", "b,c", "d"]
        assert q1(ex, "RETURN apoc.import.convertType('42', 'int')") == 42
        v = q1(ex, "RETURN apoc.import.validateSchema("
                   "[{a: '1'}, {b: '2'}], {a: 'int'})")
        assert v["valid"] is False and "row 1" in v["errors"][0]


class TestLoaders:
    def test_local_formats(self, ex):
        assert q1(ex, "RETURN apoc.load.csv('a,b\\n1,2')") == \
            [{"a": "1", "b": "2"}]
        assert q1(ex, "RETURN apoc.load.json('{\"x\": 1}')") == {"x": 1}
        assert q1(ex, "RETURN apoc.load.jsonArray('[1,2]')") == [1, 2]
        assert q1(ex, "RETURN apoc.load.jsonSchema("
                      "'{\"a\": 1, \"b\": [\"x\"]}')") == \
            {"a": "int", "b": ["str"]}

    def test_html(self, ex):
        h = q1(ex, "RETURN apoc.load.html('<html><title>T</title>"
                   "<a href=\"/x\">l</a><p>body text</p></html>')")
        assert h["title"] == "T"
        assert h["links"] == ["/x"]
        assert "body text" in h["text"]

    def test_external_placeholders(self, ex):
        # reference behavior: external loaders acknowledge with empty
        # results (apoc/load/load.go placeholders)
        assert q1(ex, "RETURN apoc.load.kafka('b', 't', {})") == []
        assert q1(ex, "RETURN apoc.load.jdbc('url', 'q')") == []
        assert q1(ex, "RETURN apoc.load.s3('bucket')") == []


class TestVirtualGraph:
    def test_from_and_stats(self, ex):
        st = q1(ex, "MATCH (a:P)-[r]->(b:P) RETURN apoc.graph.stats("
                    "apoc.graph.from([a, b], [r], 'g'))")
        assert st["nodeCount"] == 2 and st["relCount"] == 1
        assert st["labels"] == ["P"]

    def test_from_document(self, ex):
        doc = q1(ex, "RETURN apoc.graph.fromDocument('"
                     '{"name": "root", "children": [{"name": "kid"}]}'
                     "')")
        assert len(doc["nodes"]) == 2
        assert doc["relationships"][0].type == "CHILDREN"

    def test_validate_dangling(self, ex):
        bad = q1(ex, "MATCH (a:P)-[r]->(b:P) RETURN apoc.graph.validate("
                     "apoc.graph.from([a], [r], 'g'))")
        assert bad["valid"] is False and len(
            bad["danglingRelationships"]) == 1


class TestTriggerPeriodic:
    def test_trigger_function_surface(self, ex):
        q1(ex, "RETURN apoc.trigger.add('t1', 'RETURN 1')")
        assert q1(ex, "RETURN apoc.trigger.count()") == 1
        assert q1(ex, "RETURN apoc.trigger.isEnabled('t1')") is True
        q1(ex, "RETURN apoc.trigger.pause('t1')")
        assert q1(ex, "RETURN apoc.trigger.isEnabled('t1')") is False
        exported = q1(ex, "RETURN apoc.trigger.export()")
        q1(ex, "RETURN apoc.trigger.removeAll()")
        assert q1(ex, "RETURN apoc.trigger.count()") == 0
        assert q1(ex, "RETURN apoc.trigger.import($d)",
                  {"d": exported}) == 1
        q1(ex, "RETURN apoc.trigger.removeAll()")

    def test_periodic_registry(self, ex):
        q1(ex, "RETURN apoc.periodic.submit('j1', 'RETURN 1')")
        jobs = q1(ex, "RETURN apoc.periodic.list()")
        assert any(j["name"] == "j1" for j in jobs)
        assert q1(ex, "RETURN apoc.periodic.cancel('j1')") is True

    def test_periodic_truncate(self, ex):
        out = q1(ex, "RETURN apoc.periodic.truncate()")
        assert out["deleted"] == 2
        assert q1(ex, "MATCH (n) RETURN count(n)") == 0


class TestPathProcedures:
    def test_shortest_path_procedure(self, ex):
        ex.execute("MATCH (b:P {id:2}) CREATE (b)-[:R]->(:P {id: 3})")
        r = ex.execute("MATCH (a:P {id:1}), (b:P {id:3}) "
                       "CALL apoc.path.shortestPath(a, b) YIELD path "
                       "RETURN length(path)").rows
        assert r == [[2]]
        r2 = ex.execute("MATCH (a:P {id:1}) "
                        "CALL apoc.path.expandConfig(a, {maxLevel: 2}) "
                        "YIELD path RETURN count(path)").rows
        assert r2[0][0] >= 2

    def test_all_shortest_paths(self, ex):
        # diamond: two equal-length paths
        ex.execute("CREATE (:Q {id: 1})")
        ex.execute("MATCH (a:Q {id:1}) CREATE (a)-[:S]->(:Q {id: 2}), "
                   "(a)-[:S]->(:Q {id: 3})")
        ex.execute("MATCH (b:Q {id:2}), (c:Q {id:3}) "
                   "CREATE (b)-[:S]->(:Q {id: 4})")
        ex.execute("MATCH (c:Q {id:3}), (d:Q {id:4}) "
                   "CREATE (c)-[:S]->(d)")
        r = ex.execute("MATCH (a:Q {id:1}), (d:Q {id:4}) "
                       "CALL apoc.path.allShortestPaths(a, d) YIELD path "
                       "RETURN count(path)").rows
        assert r == [[2]]


class TestReviewRegressions:
    def test_trigger_ctx_names_reachable_via_call(self, ex):
        rows = ex.execute("CALL apoc.trigger.install('t9', 'RETURN 1') "
                          "YIELD name RETURN name").rows
        assert rows == [["t9"]]
        shown = ex.execute("CALL apoc.trigger.show() YIELD name "
                           "RETURN name").rows
        assert ["t9"] in shown
        q1(ex, "RETURN apoc.trigger.removeAll()")

    def test_meta_constraints_not_cached_stale(self, ex):
        assert q1(ex, "RETURN apoc.meta.constraints()") == []
        q1(ex, "RETURN apoc.schema.createUniqueConstraint('MC', 'k')")
        assert len(q1(ex, "RETURN apoc.meta.constraints()")) == 1

    def test_from_cypher_executes_once(self, ex):
        q1(ex, "RETURN apoc.graph.fromCypher('CREATE (x:Zz) RETURN x')")
        assert q1(ex, "MATCH (z:Zz) RETURN count(z)") == 1

    def test_shortest_path_follows_incoming_edges(self, ex):
        ex.execute("CREATE (:U {id: 1})")
        ex.execute("MATCH (u:U {id:1}) CREATE (:U {id: 2})-[:B]->(u)")
        rows = ex.execute("MATCH (a:U {id:1}), (b:U {id:2}) "
                          "CALL apoc.path.shortestPath(a, b) YIELD path "
                          "RETURN length(path)").rows
        assert rows == [[1]]

    def test_empty_procedure_result_zero_rows(self, ex):
        rows = ex.execute("CALL apoc.schema.nodeConstraints() "
                          "YIELD name RETURN name").rows
        assert rows == []

    def test_try_acquire_reentrant_rollback_accounting(self, ex):
        import threading

        from nornicdb_tpu.query.apoc_admin import LOCKS

        assert LOCKS.acquire(["re-a"], timeout=1.0)
        hold = threading.Event()
        release = threading.Event()

        def holder():
            LOCKS.acquire(["re-b"], timeout=1.0)
            hold.set()
            release.wait(5.0)
            LOCKS.release(["re-b"])

        t = threading.Thread(target=holder)
        t.start()
        hold.wait(5.0)
        try:
            assert LOCKS.try_acquire(["re-a", "re-b"]) is False
            # the original hold must still be counted
            assert LOCKS.is_locked("re-a") is True
        finally:
            release.set()
            t.join(5.0)
            LOCKS.release(["re-a"])


class TestLeftovers:
    def test_map(self, ex):
        assert q1(ex, "RETURN apoc.map.get({a: 1}, 'a')") == 1
        assert q1(ex, "RETURN apoc.map.get({a: 1}, 'z', 9)") == 9
        assert q1(ex, "RETURN apoc.map.dropNullValues({a: 1, b: null})"
                  ) == {"a": 1}
        assert q1(ex, "RETURN apoc.map.unflatten({`a.b`: 1})") == \
            {"a": {"b": 1}}
        assert q1(ex, "RETURN apoc.map.setPairs([['a', 1], ['b', 2]])"
                  ) == {"a": 1, "b": 2}

    def test_node_rel_write_forms(self, ex):
        ex.execute("MATCH (p:P {id:1}) "
                   "RETURN apoc.node.setProperty(p, 'extra', 7)")
        assert q1(ex, "MATCH (p:P {id:1}) RETURN p.extra") == 7
        ex.execute("MATCH (p:P {id:1}) RETURN apoc.label.add(p, 'Z')")
        assert q1(ex, "MATCH (p:P {id:1}) RETURN labels(p)") == ["P", "Z"]
        ex.execute("MATCH (p:P {id:1}) "
                   "RETURN apoc.label.replace(p, 'Z', 'Y')")
        assert q1(ex, "MATCH (p:P {id:1}) RETURN labels(p)") == ["P", "Y"]

    def test_lock_with_lock(self, ex):
        out = q1(ex, "MATCH (p:P {id:1}) "
                     "RETURN apoc.lock.withLock([p], 'RETURN 42 AS v')")
        assert out == [{"v": 42}]
        # lock must be released afterwards
        assert q1(ex, "MATCH (p:P {id:1}) "
                      "RETURN apoc.lock.isLocked(p)") is False

    def test_hashing(self, ex):
        # cityhash64 delegates to fnv1a64 (reference hashing.go:302)
        assert q1(ex, "RETURN apoc.hashing.cityhash64('x')") == \
            q1(ex, "RETURN apoc.hashing.fnv1a64('x')")
        a = q1(ex, "RETURN apoc.hashing.xxhash32('hello')")
        b = q1(ex, "RETURN apoc.hashing.xxhash32('hello', 1)")
        assert a != b and 0 <= a <= 0xFFFFFFFF

    def test_merge_pattern_and_rollback(self, ex):
        out = q1(ex, "RETURN apoc.merge.pattern(['A'], {k: 1}, 'REL', "
                     "['B'], {k: 2})")
        assert out["rel"].type == "REL"
        snap = q1(ex, "MATCH (a:A) RETURN apoc.merge.snapshot(a)")
        ex.execute("MATCH (a:A) SET a.k = 99")
        ex.execute("MATCH (a:A) RETURN apoc.merge.rollback(a, $s)",
                   {"s": snap})
        assert q1(ex, "MATCH (a:A) RETURN a.k") == 1
