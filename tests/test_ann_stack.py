"""IVF-PQ / IVF-HNSW / ANN profiles / vectorspace / rerank tests.

Reference: pkg/search (ivfpq_index.go, ivf_hnsw_candidate_gen.go,
ann_quality.go, rerank.go) and pkg/vectorspace (registry.go).
"""

import numpy as np
import pytest

from nornicdb_tpu.search import (
    IVFHNSWIndex,
    IVFPQIndex,
    PROFILES,
    LLMReranker,
    LocalReranker,
    current_profile,
)
from nornicdb_tpu.vectorspace import (
    CHUNK_VECTOR_NAME,
    SpaceKey,
    VectorSpaceRegistry,
)


def _clustered_vectors(n_per=50, n_clusters=4, dims=32, seed=0):
    """Well-separated clusters so ANN recall is testable."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_clusters, dims)) * 10
    items = []
    for c in range(n_clusters):
        pts = centers[c] + rng.standard_normal((n_per, dims)) * 0.1
        for i, p in enumerate(pts):
            items.append((f"c{c}-{i}", p.astype(np.float32)))
    return items


class TestIVFPQ:
    def test_train_encode_search_recall(self):
        items = _clustered_vectors()
        vecs = np.asarray([v for _, v in items])
        idx = IVFPQIndex(n_subspaces=8, n_clusters=4, nprobe=2)
        idx.train(vecs)
        idx.add_batch(items)
        assert len(idx) == len(items)
        # querying with a member vector finds same-cluster neighbors
        hits = idx.search(items[0][1], k=5)
        assert len(hits) == 5
        assert all(h.startswith("c0-") for h, _ in hits)

    def test_untrained_raises(self):
        idx = IVFPQIndex()
        with pytest.raises(RuntimeError):
            idx.add_batch([("a", [1.0, 2.0])])

    def test_dims_divisibility_enforced(self):
        idx = IVFPQIndex(n_subspaces=7)
        with pytest.raises(ValueError):
            idx.train(np.random.default_rng(0).standard_normal((10, 32)))

    def test_remove_and_upsert(self):
        items = _clustered_vectors(n_per=10)
        idx = IVFPQIndex(n_subspaces=8, n_clusters=4)
        idx.train(np.asarray([v for _, v in items]))
        idx.add_batch(items)
        assert idx.remove("c0-0")
        assert not idx.remove("c0-0")  # already gone
        assert len(idx) == len(items) - 1
        assert all(h != "c0-0" for h, _ in idx.search(items[0][1], k=10))
        # re-adding resurrects
        idx.add_batch([items[0]])
        assert len(idx) == len(items)

    def test_save_load_roundtrip(self, tmp_path):
        items = _clustered_vectors(n_per=10)
        idx = IVFPQIndex(n_subspaces=8, n_clusters=4)
        idx.train(np.asarray([v for _, v in items]))
        idx.add_batch(items)
        path = str(tmp_path / "pq")
        idx.save(path)
        loaded = IVFPQIndex.load(path)
        assert len(loaded) == len(idx)
        a = [h for h, _ in idx.search(items[5][1], k=5)]
        b = [h for h, _ in loaded.search(items[5][1], k=5)]
        assert a == b

    def test_compression_ratio(self):
        items = _clustered_vectors(n_per=25, dims=32)
        idx = IVFPQIndex(n_subspaces=8, n_clusters=4)
        idx.train(np.asarray([v for _, v in items]))
        idx.add_batch(items)
        raw = len(items) * 32 * 4
        compressed = idx._codes.nbytes
        assert compressed * 10 < raw  # 8 bytes vs 128 bytes per vector


class TestIVFHNSW:
    def test_build_and_search(self):
        items = _clustered_vectors()
        idx = IVFHNSWIndex(n_clusters=4, nprobe=2)
        idx.build(items)
        assert len(idx) == len(items)
        hits = idx.search(items[0][1], k=5)
        assert hits[0][0] == "c0-0"
        assert all(h.startswith("c0-") for h, _ in hits)

    def test_incremental_add_and_remove(self):
        items = _clustered_vectors(n_per=10)
        idx = IVFHNSWIndex(n_clusters=4, nprobe=2)
        idx.build(items)
        new_vec = items[0][1] + 0.01
        idx.add("newbie", new_vec)
        hits = idx.search(new_vec, k=3)
        assert "newbie" in [h for h, _ in hits]
        assert idx.remove("newbie")
        assert "newbie" not in [h for h, _ in idx.search(new_vec, k=3)]

    def test_save_load_roundtrip(self, tmp_path):
        items = _clustered_vectors(n_per=10)
        idx = IVFHNSWIndex(n_clusters=4, nprobe=2)
        idx.build(items)
        idx.save(str(tmp_path / "ivf"))
        loaded = IVFHNSWIndex.load(str(tmp_path / "ivf"))
        assert len(loaded) == len(idx)
        a = [h for h, _ in idx.search(items[3][1], k=5)]
        b = [h for h, _ in loaded.search(items[3][1], k=5)]
        assert set(a) == set(b)


class TestANNQuality:
    def test_profiles_exist(self):
        assert set(PROFILES) == {"fast", "balanced", "accurate",
                                 "compressed", "cagra"}
        assert PROFILES["compressed"].index_kind == "ivfpq"
        assert PROFILES["cagra"].index_kind == "cagra"
        assert (PROFILES["accurate"].hnsw_ef_search
                > PROFILES["fast"].hnsw_ef_search)

    def test_cagra_profile_params(self):
        p = PROFILES["cagra"]
        assert p.cagra_itopk & (p.cagra_itopk - 1) == 0  # pow2
        assert p.cagra_degree >= 16
        assert p.cagra_min_n > 0

    def test_cagra_shards_env(self, monkeypatch):
        from nornicdb_tpu.search.ann_quality import cagra_shards_from_env

        monkeypatch.delenv("NORNICDB_CAGRA_SHARDS", raising=False)
        assert cagra_shards_from_env() == 1
        monkeypatch.setenv("NORNICDB_CAGRA_SHARDS", "4")
        assert cagra_shards_from_env() == 4
        monkeypatch.setenv("NORNICDB_CAGRA_SHARDS", "junk")
        assert cagra_shards_from_env() == 1

    def test_env_selection(self, monkeypatch):
        monkeypatch.setenv("NORNICDB_VECTOR_ANN_QUALITY", "accurate")
        assert current_profile().name == "accurate"
        monkeypatch.setenv("NORNICDB_VECTOR_ANN_QUALITY", "garbage")
        assert current_profile().name == "balanced"  # fallback

    def test_explicit_name_wins(self):
        assert current_profile("fast").name == "fast"


class TestCagraProfileRecall:
    """ISSUE 2 satellite: ANN recall regression gate for the cagra
    profile on the standard clustered corpus — recall@10 >= 0.95."""

    def test_recall_at_10_on_clustered_corpus(self):
        from nornicdb_tpu.search.cagra import CagraIndex

        items = _clustered_vectors(n_per=500, n_clusters=4, dims=32)
        vecs = np.asarray([v for _, v in items], dtype=np.float32)
        idx = CagraIndex(min_n=256)
        idx.add_batch(items)
        assert idx.build()

        rng = np.random.default_rng(8)
        nq = 50
        qrows = rng.choice(len(items), nq, replace=False)
        qs = vecs[qrows] + 0.1 * rng.standard_normal(
            (nq, vecs.shape[1])).astype(np.float32)
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        qn = qs / np.linalg.norm(qs, axis=1, keepdims=True)
        gt = np.argsort(-(qn @ vn.T), axis=1)[:, :10]
        gt_sets = [{items[j][0] for j in row} for row in gt]
        res = idx.search_batch(qs, 10)
        hit = sum(len({h for h, _ in res[qi]} & gt_sets[qi])
                  for qi in range(nq))
        assert hit / (nq * 10) >= 0.95

    def test_registry_cagra_backend(self, monkeypatch):
        from nornicdb_tpu.search.cagra import CagraIndex as CI

        reg = VectorSpaceRegistry()
        sp = reg.get_or_create(database="x", vector_name="g",
                               backend="cagra")
        assert isinstance(sp.ensure_index(), CI)


class TestVectorSpaceRegistry:
    def test_register_get_drop(self):
        reg = VectorSpaceRegistry()
        sp = reg.get_or_create(database="db1", dims=128)
        assert reg.get(sp.key) is sp
        # same key -> same space
        assert reg.get_or_create(database="db1", dims=128) is sp
        chunk = reg.get_or_create(database="db1",
                                  vector_name=CHUNK_VECTOR_NAME, dims=128)
        assert chunk is not sp
        assert len(reg.list("db1")) == 2
        assert reg.drop_database("db1") == 2
        assert reg.list() == []

    def test_backend_resolution(self):
        reg = VectorSpaceRegistry()
        brute = reg.get_or_create(database="x", backend="brute")
        from nornicdb_tpu.search.vector_index import BruteForceIndex

        assert isinstance(brute.ensure_index(), BruteForceIndex)
        pq = reg.get_or_create(database="x", vector_name="pq",
                               backend="ivfpq")
        from nornicdb_tpu.search.ivfpq import IVFPQIndex as PQ

        assert isinstance(pq.ensure_index(), PQ)

    def test_unknown_backend_rejected(self):
        reg = VectorSpaceRegistry()
        with pytest.raises(ValueError):
            reg.register(SpaceKey(), backend="warp-drive")


class TestRerank:
    def _candidates(self):
        return [
            {"id": "a", "score": 0.9,
             "properties": {"content": "cooking pasta recipes"}},
            {"id": "b", "score": 0.8,
             "properties": {"content": "tpu compiler internals"}},
            {"id": "c", "score": 0.7,
             "properties": {"content": "tpu kernel tuning guide"}},
        ]

    def test_local_reranker_lexical(self):
        rr = LocalReranker(alpha=0.0)  # lexical only
        out = rr.rerank("tpu kernel tuning", self._candidates())
        assert out[0]["id"] == "c"
        assert out[0]["rerank_score"] >= out[-1]["rerank_score"]

    def test_local_reranker_with_embeddings(self):
        rr = LocalReranker(alpha=1.0)  # cosine only
        cands = self._candidates()
        cands[0]["_embedding"] = [1.0, 0.0]
        cands[1]["_embedding"] = [0.0, 1.0]
        cands[2]["_embedding"] = [0.9, 0.1]
        out = rr.rerank("q", cands, query_embedding=[0.0, 1.0])
        assert out[0]["id"] == "b"

    def test_llm_reranker_orders_by_model(self):
        from nornicdb_tpu.heimdall import Manager, ModelSpec

        mgr = Manager()
        mgr.register(ModelSpec(name="e", backend="echo",
                               options={"replies": ['["c", "a", "b"]']}))
        rr = LLMReranker(mgr, model="e")
        out = rr.rerank("q", self._candidates())
        assert [c["id"] for c in out] == ["c", "a", "b"]

    def test_llm_reranker_fails_open(self):
        from nornicdb_tpu.heimdall import Manager, ModelSpec

        mgr = Manager()
        mgr.register(ModelSpec(name="e", backend="echo",
                               options={"replies": ["not json at all"]}))
        rr = LLMReranker(mgr, model="e")
        out = rr.rerank("q", self._candidates())
        assert [c["id"] for c in out] == ["a", "b", "c"]  # untouched

    def test_service_integration(self):
        import nornicdb_tpu
        from nornicdb_tpu.search.service import SearchService

        db = nornicdb_tpu.open()
        try:
            svc = SearchService(db.storage,
                                reranker=LocalReranker(alpha=0.0))
            from nornicdb_tpu.storage.types import Node

            for i, text in enumerate(
                ["tpu kernels", "pasta cooking", "tpu tuning deep dive"]
            ):
                n = Node(id=f"n{i}", labels=["Doc"],
                         properties={"content": text})
                db.storage.create_node(n)
                svc.index_node(n)
            out = svc.search("tpu tuning", limit=2)
            assert out[0]["id"] == "n2"
            assert "rerank_score" in out[0]
        finally:
            db.close()


class TestReviewRegressions:
    def test_ivfpq_trains_on_duplicate_vectors(self):
        """kmeans++ must not crash when residual subvectors coincide."""
        v = np.ones((50, 16), dtype=np.float32)
        items = [(f"d{i}", v[i]) for i in range(50)]
        idx = IVFPQIndex(n_subspaces=4, n_clusters=2)
        idx.train(v)  # all-duplicate: zero D^2 weights everywhere
        idx.add_batch(items)
        assert len(idx.search(v[0], k=3)) == 3

    def test_ivfpq_empty_batch_noop(self):
        idx = IVFPQIndex(n_subspaces=4, n_clusters=2)
        idx.train(np.random.default_rng(0)
                  .standard_normal((20, 16)).astype(np.float32))
        idx.add_batch([])  # must not crash
        assert len(idx) == 0

    def test_ivf_hnsw_save_clears_stale_clusters(self, tmp_path):
        items = _clustered_vectors(n_per=10)
        idx = IVFHNSWIndex(n_clusters=4, nprobe=4)
        idx.build(items)
        d = str(tmp_path / "ivf")
        idx.save(d)
        # rebuild with a disjoint, smaller dataset and save again
        small = _clustered_vectors(n_per=5, n_clusters=2, seed=9)
        small = [(f"new-{i}", v) for i, (_, v) in enumerate(small)]
        idx2 = IVFHNSWIndex(n_clusters=2, nprobe=2)
        idx2.build(small)
        idx2.save(d)
        loaded = IVFHNSWIndex.load(d)
        assert len(loaded) == len(small)
        assert all(e.startswith("new-") for e in loaded._where)

    def test_vectorspace_concurrent_ensure_index(self):
        import threading as th

        from nornicdb_tpu.vectorspace import VectorSpaceRegistry

        reg = VectorSpaceRegistry()
        sp = reg.get_or_create(database="r", backend="brute")
        got = []
        barrier = th.Barrier(8)

        def grab():
            barrier.wait()
            got.append(sp.ensure_index())

        threads = [th.Thread(target=grab) for _ in range(8)]
        for t in threads: t.start()
        for t in threads: t.join()
        assert all(g is got[0] for g in got)

    def test_reranker_receives_precomputed_embedding(self):
        import nornicdb_tpu
        from nornicdb_tpu.search.service import SearchService
        from nornicdb_tpu.storage.types import Node

        received = {}

        class Spy:
            def rerank(self, query, candidates, query_embedding=None,
                       limit=None):
                received["qv"] = query_embedding
                return candidates[:limit]

        db = nornicdb_tpu.open()
        try:
            svc = SearchService(db.storage, reranker=Spy())
            n = Node(id="n0", labels=["Doc"],
                     properties={"content": "hello"},
                     embedding=[1.0, 0.0])
            db.storage.create_node(n)
            svc.index_node(n)
            svc.search("hello", limit=1, query_embedding=[1.0, 0.0])
            assert received["qv"] is not None
        finally:
            db.close()

    def test_ivfpq_save_empty_roundtrip(self, tmp_path):
        idx = IVFPQIndex(n_subspaces=4, n_clusters=2)
        idx.train(np.random.default_rng(0)
                  .standard_normal((20, 16)).astype(np.float32))
        path = str(tmp_path / "empty")
        idx.save(path)  # trained but no points
        loaded = IVFPQIndex.load(path)
        assert len(loaded) == 0
        assert loaded.search([0.0] * 16, k=3) == []

    def test_ivfpq_untrained_save_rejected(self, tmp_path):
        with pytest.raises(RuntimeError):
            IVFPQIndex().save(str(tmp_path / "x"))

    def test_ivf_hnsw_ef_search_persisted(self, tmp_path):
        items = _clustered_vectors(n_per=5)
        idx = IVFHNSWIndex(n_clusters=2, ef_search=99, ef_construction=77)
        idx.build(items)
        d = str(tmp_path / "ef")
        idx.save(d)
        loaded = IVFHNSWIndex.load(d)
        assert loaded.ef_search == 99
        assert loaded.ef_construction == 77
        # cluster graphs restore their ef params too
        sub = next(iter(loaded.clusters.values()))
        assert sub.ef_search == 99 and sub.ef_construction == 77

    def test_ivfpq_bulk_add_matches_incremental(self):
        items = _clustered_vectors(n_per=10)
        a = IVFPQIndex(n_subspaces=8, n_clusters=4)
        a.train(np.asarray([v for _, v in items]))
        a.add_batch(items)
        b = IVFPQIndex(n_subspaces=8, n_clusters=4)
        b.train(np.asarray([v for _, v in items]))
        for it in items:
            b.add_batch([it])
        qa = [h for h, _ in a.search(items[7][1], k=5)]
        qb = [h for h, _ in b.search(items[7][1], k=5)]
        assert qa == qb


class TestIVFPQScaleRecall:
    """Scale recall gate (VERDICT r3 task 4): the r3 curves were flat at
    recall ~0.26 for nprobe 1->8 because toy unit tests never asserted
    recall at scale. This test pins the full pipeline — coarse probing
    must actually reach the true neighbors' cells (coarse_hit_rate), and
    the ADC+exact-rerank stage must rank them (recall@10)."""

    def test_recall_at_50k_256d(self):
        rng = np.random.default_rng(11)
        n, d, centers = 50_000, 256, 128
        cent = (rng.standard_normal((centers, d)) * 2.0).astype(np.float32)
        assign = rng.integers(0, centers, n)
        vecs = (cent[assign]
                + rng.standard_normal((n, d)).astype(np.float32))
        ids = [f"v{i}" for i in range(n)]
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)

        idx = IVFPQIndex(n_subspaces=32, n_clusters=64, nprobe=8,
                         keep_vectors=True, min_refine_pool=512)
        idx.train(vecs[:10_000])
        idx.add_batch(list(zip(ids, vecs)))

        nq = 50
        qrows = rng.choice(n, nq, replace=False)
        qs = vecs[qrows] + 0.3 * rng.standard_normal((nq, d)).astype(
            np.float32)
        qn = qs / np.linalg.norm(qs, axis=1, keepdims=True)
        gt = np.argsort(-(qn @ vn.T), axis=1)[:, :10]
        gt_ids = [[f"v{j}" for j in row] for row in gt]

        # stage 1: the probed cells must contain the true neighbors
        hit_rate = idx.coarse_hit_rate(qs, gt_ids, nprobe=8)
        assert hit_rate >= 0.9, f"coarse probing misses cells: {hit_rate}"

        # stage 2: end-to-end recall@10 with the exact-rerank stage
        hit = 0
        for qi in range(nq):
            res = {h for h, _ in idx.search(qs[qi], k=10, nprobe=8)}
            hit += len(res & set(gt_ids[qi]))
        recall = hit / (nq * 10)
        assert recall >= 0.85, f"recall@10 {recall}"

        # nprobe must MOVE recall (the r3 bug signature was a flat curve)
        hit1 = 0
        for qi in range(nq):
            res = {h for h, _ in idx.search(qs[qi], k=10, nprobe=1)}
            hit1 += len(res & set(gt_ids[qi]))
        assert hit / (nq * 10) > hit1 / (nq * 10) - 0.02

    def test_refine_store_off_still_works(self):
        items = _clustered_vectors(n_per=30)
        idx = IVFPQIndex(n_subspaces=8, n_clusters=4, keep_vectors=False)
        idx.train(np.asarray([v for _, v in items]))
        idx.add_batch(items)
        hits = idx.search(items[0][1], k=5)
        assert len(hits) == 5
        assert all(h.startswith("c0-") for h, _ in hits)

    def test_refine_save_load_keeps_vectors(self, tmp_path):
        items = _clustered_vectors(n_per=10)
        idx = IVFPQIndex(n_subspaces=8, n_clusters=4, keep_vectors=True)
        idx.train(np.asarray([v for _, v in items]))
        idx.add_batch(items)
        path = str(tmp_path / "pq.npz")
        idx.save(path)
        back = IVFPQIndex.load(path)
        assert back.keep_vectors and back._vecs is not None
        assert [h for h, _ in back.search(items[3][1], k=5)] == \
            [h for h, _ in idx.search(items[3][1], k=5)]


class TestSeededBuild:
    """Seed-first + adaptive bulk beam (VERDICT r3 task 5): the seeded
    build must deliver wall-clock savings WITHOUT giving up recall —
    the bulk phase uses a halved construction beam over the seeded
    backbone, and recall must stay within noise of the full-beam
    unseeded build."""

    def _corpus(self, n=4000, d=64, centers=32, seed=3):
        rng = np.random.default_rng(seed)
        cent = (rng.standard_normal((centers, d)) * 2.0).astype(np.float32)
        assign = rng.integers(0, centers, n)
        vecs = (cent[assign]
                + rng.standard_normal((n, d)).astype(np.float32))
        # seeds: a few members of every topic (what BM25 high-IDF
        # seeding produces on topical text)
        seeds = []
        for c in range(centers):
            rows = np.nonzero(assign == c)[0][:4]
            seeds.extend(f"v{r}" for r in rows)
        return vecs, seeds

    def test_seeded_recall_parity_with_smaller_bulk_beam(self):
        from nornicdb_tpu.search.hnsw import HNSWIndex

        vecs, seeds = self._corpus()
        items = [(f"v{i}", v) for i, v in enumerate(vecs)]
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        rng = np.random.default_rng(9)
        qrows = rng.choice(len(vecs), 50, replace=False)
        qs = vecs[qrows] + 0.3 * rng.standard_normal(
            (50, vecs.shape[1])).astype(np.float32)
        qn = qs / np.linalg.norm(qs, axis=1, keepdims=True)
        gt = np.argsort(-(qn @ vn.T), axis=1)[:, :10]
        gt_sets = [set(f"v{j}" for j in row) for row in gt]

        def recall(index):
            hit = 0
            for qi in range(len(qs)):
                res = {h for h, _ in index.search(qs[qi], k=10, ef=64)}
                hit += len(res & gt_sets[qi])
            return hit / (len(qs) * 10)

        full = HNSWIndex(ef_construction=128)
        full.build(items)
        seeded = HNSWIndex(ef_construction=128)
        seeded.build(items, seed_ids=seeds)
        r_full, r_seeded = recall(full), recall(seeded)
        assert r_seeded >= r_full - 0.03, (r_seeded, r_full)


class _ConnectOnlyLib:
    """Proxy exposing only the connect kernel — forces the numpy wave
    search while keeping the native link phase, so connect parity can be
    pinned in isolation."""

    def __init__(self, lib):
        self._lib = lib
        self.hnsw_connect = lib.hnsw_connect


class TestNativeConnect:
    """The native connect kernel (native/nornichnsw.cpp) must produce
    EXACTLY the graph the Python link phase produces — same diversity
    selection, same back-link pruning, same tie-breaks. (The native
    WAVE SEARCH is a different algorithm than the numpy batched search
    — classic per-query heaps vs expand-every-beam-entry — so full
    native builds are gated on recall, below, not graph equality.)"""

    def test_native_connect_matches_python_graph(self, monkeypatch):
        from nornicdb_tpu.search import hnsw_native
        from nornicdb_tpu.search.hnsw import HNSWIndex

        lib = hnsw_native.get_lib()
        if lib is None:
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(17)
        vecs = rng.standard_normal((3000, 64)).astype(np.float32)
        items = [(f"v{i}", v) for i, v in enumerate(vecs)]

        monkeypatch.setattr(hnsw_native, "get_lib",
                            lambda: _ConnectOnlyLib(lib))
        native = HNSWIndex(ef_construction=96)
        native.build(items)

        monkeypatch.setattr(hnsw_native, "get_lib", lambda: None)
        python = HNSWIndex(ef_construction=96)
        python.build(items)

        assert len(native._nbrL) == len(python._nbrL)
        for lv in range(len(native._nbrL)):
            np.testing.assert_array_equal(
                native._cntL[lv], python._cntL[lv], err_msg=f"cnt lv{lv}")
            np.testing.assert_array_equal(
                native._nbrL[lv], python._nbrL[lv], err_msg=f"nbr lv{lv}")

    def test_native_wave_search_build_recall(self, monkeypatch):
        """Full native build (search + connect) must match the Python
        build's recall on the same data — the wave-search kernel is a
        different traversal, so quality, not graph bytes, is the
        contract."""
        from nornicdb_tpu.search import hnsw_native
        from nornicdb_tpu.search.hnsw import HNSWIndex

        lib = hnsw_native.get_lib()
        if lib is None or not hasattr(lib, "hnsw_wave_search"):
            pytest.skip("native wave search unavailable")
        rng = np.random.default_rng(23)
        n, d = 4000, 64
        vecs = rng.standard_normal((n, d)).astype(np.float32)
        items = [(f"v{i}", v) for i, v in enumerate(vecs)]
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        nq = 100
        qs = vecs[rng.choice(n, nq, replace=False)] + \
            0.1 * rng.standard_normal((nq, d)).astype(np.float32)
        qn = qs / np.linalg.norm(qs, axis=1, keepdims=True)
        gt = np.argsort(-(qn @ vn.T), axis=1)[:, :10]
        gt_sets = [{f"v{j}" for j in row} for row in gt]

        def recall(index):
            hit = 0
            for qi in range(nq):
                res = {h[0] for h in index.search(qs[qi], k=10)}
                hit += len(res & gt_sets[qi])
            return hit / (nq * 10)

        native = HNSWIndex(ef_construction=96)
        native.build(items)
        r_native = recall(native)

        monkeypatch.setattr(hnsw_native, "get_lib", lambda: None)
        python = HNSWIndex(ef_construction=96)
        python.build(items)
        r_python = recall(python)
        assert r_native >= r_python - 0.03, (r_native, r_python)
        assert r_native >= 0.85, r_native
