"""Replication tests: multiple replicas in one process over loopback
transport or direct handler invocation (reference pattern:
pkg/replication/replication_test.go, scenario_test.go, ha_standby
handlers directly callable ha_standby.go:736-779)."""

import threading
import time

import pytest

from nornicdb_tpu.replication import (
    ClusterTransport,
    HAPrimary,
    HAStandby,
    NotPrimaryError,
    RaftNode,
    ReplicatedEngine,
    ReplicationConfig,
    Role,
)
from nornicdb_tpu.storage import MemoryEngine, WAL, WALEngine
from nornicdb_tpu.storage.types import Edge, Node


def make_wal_engine(tmp_path, name):
    wal = WAL(str(tmp_path / name))
    return WALEngine(MemoryEngine(), wal)


class TestTransport:
    def test_request_response(self):
        t1 = ClusterTransport("a")
        t2 = ClusterTransport("b")
        t2.register_handler("ping", lambda m: {"ok": True, "echo": m["x"]})
        t1.start()
        t2.start()
        try:
            r = t1.request(t2.addr, {"type": "ping", "x": 42})
            assert r == {"ok": True, "echo": 42}
            # unknown type -> error reply, not hang
            r = t1.request(t2.addr, {"type": "nope"})
            assert r["ok"] is False
        finally:
            t1.close()
            t2.close()

    def test_broadcast_tolerates_dead_peer(self):
        t1 = ClusterTransport("a")
        t2 = ClusterTransport("b")
        t2.register_handler("hb", lambda m: {"ok": True})
        t1.start()
        t2.start()
        try:
            dead = ("127.0.0.1", 1)  # nothing listens there
            replies = t1.broadcast([t2.addr, dead], {"type": "hb"}, timeout=0.5)
            assert replies[t2.addr] == {"ok": True}
            assert replies[dead] is None
        finally:
            t1.close()
            t2.close()


class TestHAStandby:
    def _pair(self, tmp_path, sync="async"):
        tp = ClusterTransport("primary")
        ts = ClusterTransport("standby")
        tp.start()
        ts.start()
        ep = make_wal_engine(tmp_path, "p")
        es = make_wal_engine(tmp_path, "s")
        cfg_p = ReplicationConfig(
            mode="ha_standby", sync=sync, node_id="primary",
            peers=[ts.addr], heartbeat_interval=0.1, failover_timeout=0.5,
        )
        cfg_s = ReplicationConfig(
            mode="ha_standby", node_id="standby",
            heartbeat_interval=0.1, failover_timeout=0.5,
        )
        primary = HAPrimary(ep, tp, cfg_p)
        standby = HAStandby(es, ts, cfg_s, primary_addr=tp.addr)
        return primary, standby, tp, ts

    def test_wal_streaming_converges(self, tmp_path):
        primary, standby, tp, ts = self._pair(tmp_path, sync="quorum")
        try:
            eng = ReplicatedEngine(primary.engine, primary)
            eng.create_node(Node(id="n1", labels=["X"], properties={"a": 1}))
            eng.create_edge(Edge(id="e1", start_node="n1", end_node="n1",
                                 type="SELF", properties={}))
            # quorum mode: standby already has it
            assert standby.engine.get_node("n1").properties["a"] == 1
            assert standby.engine.get_edge("e1").type == "SELF"
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()

    def test_async_streaming_converges(self, tmp_path):
        primary, standby, tp, ts = self._pair(tmp_path, sync="async")
        primary.start()
        try:
            eng = ReplicatedEngine(primary.engine, primary)
            for i in range(10):
                eng.create_node(Node(id=f"n{i}", labels=[], properties={}))
            deadline = time.time() + 5
            while time.time() < deadline:
                if standby.engine.count_nodes() == 10:
                    break
                time.sleep(0.05)
            assert standby.engine.count_nodes() == 10
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()

    def test_standby_rejects_writes(self, tmp_path):
        primary, standby, tp, ts = self._pair(tmp_path)
        try:
            with pytest.raises(NotPrimaryError):
                standby.apply("create_node", {"id": "x", "labels": [],
                                              "properties": {}})
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()

    def test_fencing_rejects_stale_epoch(self, tmp_path):
        primary, standby, tp, ts = self._pair(tmp_path)
        try:
            # direct handler invocation (no sockets)
            standby.epoch = 5
            r = standby.handle_wal_batch({"epoch": 3, "records": []})
            assert r["ok"] is False and "fenced" in r["error"]
            r = standby.handle_heartbeat({"epoch": 3})
            assert r["ok"] is False
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()

    def test_auto_failover_promotes_and_fences(self, tmp_path):
        primary, standby, tp, ts = self._pair(tmp_path)
        promoted = threading.Event()
        standby.on_promote = lambda s: promoted.set()
        try:
            # primary never heartbeats (not started) -> standby takes over
            standby.start(monitor=True)
            assert promoted.wait(timeout=5.0)
            assert standby.role is Role.PRIMARY
            # old primary was fenced via transport
            assert primary.role is Role.STANDBY
            assert primary.epoch == standby.epoch
            # deposed primary now rejects writes
            with pytest.raises(NotPrimaryError):
                primary.apply("create_node", {"id": "x", "labels": [],
                                              "properties": {}})
            # promoted standby accepts them
            standby.apply("create_node", {"id": "y", "labels": [],
                                          "properties": {}})
            assert standby.engine.has_node("y")
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()

    def test_catch_up_after_rejoin(self, tmp_path):
        primary, standby, tp, ts = self._pair(tmp_path)
        try:
            # primary writes while the standby is "down" (stream not
            # started), then the standby rejoins and pulls the backlog
            for i in range(5):
                primary.engine.create_node(
                    Node(id=f"m{i}", labels=[], properties={})
                )
            assert standby.engine.count_nodes() == 0
            n = standby.catch_up()
            assert n == 5
            assert standby.engine.count_nodes() == 5
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()


class TestRaft:
    def _cluster(self, n=3):
        transports = [ClusterTransport(f"r{i}") for i in range(n)]
        for t in transports:
            t.start()
        addrs = [t.addr for t in transports]
        engines = [MemoryEngine() for _ in range(n)]
        nodes = []
        from nornicdb_tpu.replication.ha_standby import _op_args

        for i, t in enumerate(transports):
            cfg = ReplicationConfig(
                mode="raft", node_id=f"r{i}",
                peers=[a for j, a in enumerate(addrs) if j != i],
                heartbeat_interval=0.1, election_timeout=(0.3, 0.6),
            )
            eng = engines[i]
            def apply_fn(op, data, _eng=eng):
                getattr(_eng, op)(*_op_args(op, data))
            nodes.append(RaftNode(t, cfg, apply_fn))
        return nodes, transports, engines

    def _wait_leader(self, nodes, timeout=10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            leaders = [n for n in nodes if n.role is Role.PRIMARY]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.05)
        raise AssertionError("no single leader elected")

    def test_elects_single_leader(self):
        nodes, transports, _ = self._cluster(3)
        try:
            for n in nodes:
                n.start()
            leader = self._wait_leader(nodes)
            assert leader.term >= 1
        finally:
            for n in nodes: n.close()
            for t in transports: t.close()

    def test_replicates_committed_writes(self):
        nodes, transports, engines = self._cluster(3)
        try:
            for n in nodes:
                n.start()
            leader = self._wait_leader(nodes)
            leader.apply("create_node", {"id": "a", "labels": ["L"],
                                         "properties": {"v": 7}})
            # committed on leader's engine immediately
            li = nodes.index(leader)
            assert engines[li].get_node("a").properties["v"] == 7
            # followers converge via subsequent heartbeats
            deadline = time.time() + 5
            while time.time() < deadline:
                if all(e.has_node("a") for e in engines):
                    break
                time.sleep(0.05)
            assert all(e.has_node("a") for e in engines)
        finally:
            for n in nodes: n.close()
            for t in transports: t.close()

    def test_follower_rejects_writes_with_leader_hint(self):
        nodes, transports, _ = self._cluster(3)
        try:
            for n in nodes:
                n.start()
            leader = self._wait_leader(nodes)
            follower = next(n for n in nodes if n is not leader)
            # wait until the follower knows the leader
            deadline = time.time() + 3
            while time.time() < deadline and follower.leader_id is None:
                time.sleep(0.05)
            with pytest.raises(NotPrimaryError) as ei:
                follower.apply("create_node", {"id": "x", "labels": [],
                                               "properties": {}})
            assert ei.value.leader == leader.config.node_id
        finally:
            for n in nodes: n.close()
            for t in transports: t.close()

    def test_heartbeat_does_not_truncate_follower_log(self):
        """Regression: a stale AppendEntries (empty heartbeat with old
        prev_log_index) must not drop committed entries."""
        nodes, _, _ = self._cluster(1)
        node = nodes[0]
        try:
            node.term = 2
            node.log = [{"term": 1, "op": "x", "data": {}},
                        {"term": 2, "op": "y", "data": {}}]
            r = node.handle_append_entries({
                "term": 2, "leader": "L", "prev_log_index": 0,
                "prev_log_term": 0, "entries": [], "leader_commit": 0,
            })
            assert r["ok"] is True
            assert len(node.log) == 2  # untouched
            assert r["match_index"] == 0  # only claims what was sent
        finally:
            node.close()

    def test_vote_denied_for_stale_log(self):
        nodes, _, _ = self._cluster(1)
        node = nodes[0]
        try:
            node.log = [{"term": 3, "op": "x", "data": {}}]
            node.term = 3
            r = node.handle_request_vote({
                "term": 4, "candidate": "c",
                "last_log_index": 0, "last_log_term": 0,
            })
            assert r["vote_granted"] is False
        finally:
            node.close()


class TestDBLevelReplication:
    """Facade wiring: nornicdb_tpu.open(..., replication=cfg) builds the
    …→[Replicated]→Namespaced chain (reference: db.go:931)."""

    def test_ha_pair_through_facade(self, tmp_path):
        import nornicdb_tpu
        from nornicdb_tpu.replication.transport import ClusterTransport

        # standby first (so we know its addr), primary second
        standby_db = nornicdb_tpu.open(
            str(tmp_path / "s"), engine="python",
            replication=ReplicationConfig(
                mode="ha_standby", ha_role="standby", node_id="s",
            ),
        )
        s_addr = standby_db._cluster_transport.addr
        primary_db = nornicdb_tpu.open(
            str(tmp_path / "p"), engine="python",
            replication=ReplicationConfig(
                mode="ha_standby", ha_role="primary", node_id="p",
                sync="quorum", peers=[s_addr],
            ),
        )
        try:
            primary_db.cypher("CREATE (n:Doc {title: 'hello'})")
            # quorum write is already on the standby's engine
            found = [
                n for n in standby_db._base.all_nodes()
                if n.properties.get("title") == "hello"
            ]
            assert len(found) == 1
            # standby rejects writes end-to-end
            with pytest.raises(NotPrimaryError):
                standby_db.cypher("CREATE (n:Doc {title: 'nope'})")
        finally:
            primary_db.close()
            standby_db.close()

    def test_replication_requires_wal_engine(self):
        import nornicdb_tpu

        with pytest.raises(ValueError):
            nornicdb_tpu.open(
                None,
                replication=ReplicationConfig(mode="ha_standby"),
            )

    def test_async_writes_rejected_with_ha(self, tmp_path):
        import nornicdb_tpu

        with pytest.raises(ValueError, match="async_writes"):
            nornicdb_tpu.open(
                str(tmp_path / "x"), engine="python", async_writes=True,
                replication=ReplicationConfig(mode="ha_standby"),
            )

    def test_promoted_standby_streams_to_remaining_replicas(self, tmp_path):
        """After failover the new primary must replicate, not just apply
        locally (regression for single-copy-after-failover)."""
        from nornicdb_tpu.replication.transport import ClusterTransport
        from nornicdb_tpu.storage import WAL, WALEngine, MemoryEngine

        t1 = ClusterTransport("s1"); t2 = ClusterTransport("s2")
        t1.start(); t2.start()
        e1 = WALEngine(MemoryEngine(), WAL(str(tmp_path / "s1")))
        e2 = WALEngine(MemoryEngine(), WAL(str(tmp_path / "s2")))
        s1 = HAStandby(e1, t1, ReplicationConfig(
            node_id="s1", sync="quorum", peers=[t2.addr],
            heartbeat_interval=0.1), primary_addr=None)
        s2 = HAStandby(e2, t2, ReplicationConfig(
            node_id="s2", heartbeat_interval=0.1), primary_addr=None)
        try:
            s1.promote()
            assert s1.role is Role.PRIMARY
            s1.apply("create_node", {"id": "post-failover", "labels": [],
                                     "properties": {}})
            # quorum streaming: already on the second replica
            assert e2.has_node("post-failover")
        finally:
            s1.close(); s2.close(); t1.close(); t2.close()
