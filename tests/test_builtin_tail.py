"""Long-tail builtin functions (reference parity: functions_eval_math.go,
functions_eval_functions.go, kalman_functions.go)."""

import json
import math

import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


@pytest.fixture()
def ex():
    return CypherExecutor(NamespacedEngine(MemoryEngine(), "tail"))


def q1(ex, s, p=None):
    return ex.execute(s, p or {}).rows[0][0]


CASES = [
    ("RETURN sinh(0)", 0.0),
    ("RETURN cosh(0)", 1.0),
    ("RETURN tanh(0)", 0.0),
    ("RETURN power(2, 10)", 1024),
    ("RETURN toInt('42')", 42),
    ("RETURN lower('AbC')", "abc"),
    ("RETURN upper('AbC')", "ABC"),
    ("RETURN lpad('7', 3, '0')", "007"),
    ("RETURN rpad('ab', 4, '-')", "ab--"),
    ("RETURN lpad('longer', 3, '0')", "longer"),
    ("RETURN indexOf([1,2,3], 2)", 1),
    ("RETURN indexOf([1,2,3], 9)", -1),
    ("RETURN indexOf('hello', 'll')", 2),
    ("RETURN nullif(5, 5)", None),
    ("RETURN nullif(5, 6)", 5),
    ("RETURN format('%s has %s items', 'cart', 3)", "cart has 3 items"),
    ("RETURN format('%v/%v', 1, 2)", "1/2"),
    ("RETURN slice([1,2,3,4], 1, 3)", [2, 3]),
    ("RETURN slice([1,2,3,4], -2)", [3, 4]),
    ("RETURN slice([1,2,3,4], 2, 99)", [3, 4]),
    ("RETURN extract(x IN [1,2,3] | x * 2)", [2, 4, 6]),
    ("RETURN filter(x IN [1,2,3,4] WHERE x > 2)", [3, 4]),
    ("RETURN date.year(date('2026-07-30'))", 2026),
    ("RETURN date.quarter(date('2026-07-30'))", 3),
    ("RETURN date.dayOfWeek(date('2026-07-30'))", 4),  # Thursday
    ("RETURN datetime.hour(datetime('2026-07-30T14:05:00Z'))", 14),
    ("RETURN datetime.second(datetime('2026-07-30T14:05:33Z'))", 33),
    ("RETURN point.x(point({x: 3, y: 4}))", 3.0),
    ("RETURN point.y(point({x: 3, y: 4}))", 4.0),
    ("RETURN point.srid(point({x: 3, y: 4}))", 7203),
    ("RETURN point.latitude(point({latitude: 60, longitude: 10}))", 60.0),
    ("RETURN point.withinDistance(point({x:0,y:0}), point({x:3,y:4}), 5.1)",
     True),
    ("RETURN point.withinDistance(point({x:0,y:0}), point({x:3,y:4}), 4.9)",
     False),
]


@pytest.mark.parametrize("query,expected", CASES)
def test_builtin(ex, query, expected):
    got = q1(ex, query)
    if isinstance(expected, float):
        assert got == pytest.approx(expected)
    else:
        assert got == expected


NULL_EDGE_CASES = [
    # cross-CRS distance is null, not a crash
    ("RETURN point.withinDistance(point({x:1,y:2}), "
     "point({latitude:1,longitude:2}), 10)", None),
    ("RETURN date.dayOfYear(date('2020-03-05'))", 65),
    ("RETURN slice([1,2,3], null)", None),
    ("RETURN lpad('x', 5, null)", "    x"),
    ("RETURN nullif(0, false)", 0),
    ("RETURN nullif(1, true)", 1),
]


@pytest.mark.parametrize("query,expected", NULL_EDGE_CASES)
def test_null_edges(ex, query, expected):
    assert q1(ex, query) == expected


def test_power_edge_cases(ex):
    assert math.isnan(q1(ex, "RETURN power(-2, 0.5)"))
    assert q1(ex, "RETURN power(0, -1)") == float("inf")
    assert q1(ex, "RETURN power(null, 2)") is None


def test_time_truncate(ex):
    assert q1(ex, "RETURN toString(time.truncate('hour', time('14:05:33Z')))"
              ).startswith("14:00")
    assert q1(ex, "RETURN toString(localtime.truncate('minute', "
                  "localtime('14:05:33')))").startswith("14:05:00")


def test_vector_similarity(ex):
    assert q1(ex, "RETURN vector.similarity.cosine([1,0],[1,0])") == \
        pytest.approx(1.0)
    assert q1(ex, "RETURN vector.similarity.cosine([1,0],[0,1])") == \
        pytest.approx(0.0)
    assert q1(ex, "RETURN vector.similarity.euclidean([0,0],[3,4])") == \
        pytest.approx(1.0 / 6.0)
    # length mismatch -> null, not crash
    assert q1(ex, "RETURN vector.similarity.cosine([1,0],[1])") is None


def test_geometry(ex):
    square = ("polygon([point({x:0,y:0}),point({x:10,y:0}),"
              "point({x:10,y:10}),point({x:0,y:10})])")
    assert q1(ex, f"RETURN point.contains({square}, point({{x:5,y:5}}))") \
        is True
    assert q1(ex, f"RETURN point.contains({square}, point({{x:15,y:5}}))") \
        is False
    assert q1(ex, f"RETURN point.intersects(point({{x:5,y:5}}), {square})") \
        is True
    ls = q1(ex, "RETURN linestring([point({x:0,y:0}), point({x:1,y:1})])")
    assert ls["type"] == "linestring" and len(ls["points"]) == 2


def test_kalman_basic_smooths(ex):
    state = q1(ex, "RETURN kalman.init()")
    # feed a constant signal with one outlier; filtered value must stay
    # closer to the signal than the outlier
    for m in [10.0, 10.0, 10.0, 10.0]:
        r = ex.execute("RETURN kalman.process($m, $s) AS r",
                       {"m": m, "s": state}).rows[0][0]
        state = r["state"]
    r = ex.execute("RETURN kalman.process(100.0, $s) AS r",
                   {"s": state}).rows[0][0]
    assert r["value"] < 60.0  # outlier damped
    assert isinstance(q1(ex, "RETURN kalman.state($s)", {"s": r["state"]}),
                      float)
    # reset keeps configured noise but zeroes the estimate
    fresh = q1(ex, "RETURN kalman.reset($s)", {"s": r["state"]})
    assert json.loads(fresh)["x"] == 0.0


def test_kalman_invalid_state_fails_open(ex):
    r = ex.execute("RETURN kalman.process(5.0, 'not json') AS r").rows[0][0]
    assert r["value"] == 5.0 and r["error"] == "invalid state"


def test_kalman_velocity_tracks_trend(ex):
    state = q1(ex, "RETURN kalman.velocity.init()")
    # linear ramp: velocity estimate must become positive, prediction
    # ahead of current position
    for i in range(12):
        r = ex.execute("RETURN kalman.velocity.process($m, $s) AS r",
                       {"m": float(i), "s": state}).rows[0][0]
        state = r["state"]
    assert r["velocity"] > 0.5
    pred = q1(ex, "RETURN kalman.velocity.predict($s, 5)", {"s": state})
    assert pred > r["value"]


def test_kalman_adaptive_switches_on_trend(ex):
    state = q1(ex, "RETURN kalman.adaptive.init({hysteresis: 3})")
    mode = "basic"
    for i in range(20):
        r = ex.execute("RETURN kalman.adaptive.process($m, $s) AS r",
                       {"m": float(i * 2), "s": state}).rows[0][0]
        state = r["state"]
        mode = r["mode"]
    assert mode == "velocity"  # strong ramp forces velocity mode


def test_degree_functions(ex):
    ex.execute("CREATE (:P {id:1})-[:R]->(:P {id:2})")
    ex.execute("MATCH (a:P {id:1}), (b:P {id:2}) CREATE (b)-[:S]->(a)")
    assert ex.execute("MATCH (p:P {id:1}) RETURN outDegree(p)").rows == [[1]]
    assert ex.execute("MATCH (p:P {id:1}) RETURN inDegree(p)").rows == [[1]]
    assert ex.execute("MATCH (p:P {id:1}) RETURN degree(p)").rows == [[2]]
    assert q1(ex, "RETURN degree(null)") == 0
    assert ex.execute(
        "MATCH (p:P {id:1}) RETURN hasLabels(p, ['P'])").rows == [[True]]
    assert ex.execute(
        "MATCH (p:P {id:1}) RETURN hasLabels(p, ['P', 'Q'])").rows == [[False]]
