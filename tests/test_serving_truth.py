"""Serving-tier truth (ISSUE 10): per-query tier attribution, the
unified degrade ledger, and the online shadow-parity auditor.

The contracts under test:

- every device-served search/graph query is counted in
  ``nornicdb_served_tier_total{surface,tier}`` and stamps ``served_by``
  on its trace — **rider-accurate**: one rider of a coalesced hybrid
  batch whose live-filter forced a host re-fuse counts ``host`` while
  its batch-mates keep the device tier;
- ladder step-downs land structured records (normalized reason
  vocabulary) in the ledger ring served at ``/admin/degrades``;
- the shadow auditor re-executes sampled device answers on the host
  reference off the hot path: an injected device/host mismatch drops
  the parity gauge, writes a flight-recorder repro dump and surfaces in
  ``/readyz``; with quarantine enabled the tier steps down its existing
  ladder and recovers once the breach clears;
- with auditing enabled at the default sample rate the instrumented
  serving path stays within the established ≤ 2x + 1 ms/op budget and
  the auditor never blocks a dispatch.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from nornicdb_tpu import obs
from nornicdb_tpu.obs import audit
from nornicdb_tpu.obs.metrics import REGISTRY
from nornicdb_tpu.search.bm25 import BM25Index
from nornicdb_tpu.search.microbatch import MicroBatcher
from nornicdb_tpu.search.vector_index import BruteForceIndex

VOCAB = [f"term{i}" for i in range(64)]
D = 32


def _served(surface, tier):
    fam = REGISTRY.get("nornicdb_served_tier_total")
    child = fam.children().get((surface, tier))
    return child.value if child is not None else 0.0


def _counter_value(name, key):
    fam = REGISTRY.get(name)
    if fam is None:
        return 0.0
    child = fam.children().get(tuple(str(v) for v in key))
    return child.value if child is not None else 0.0


@pytest.fixture(autouse=True)
def _reset_auditor():
    audit.AUDITOR.set_sample_rate(None)
    audit.AUDITOR.set_quarantine(None)
    audit.AUDITOR.reset()
    yield
    audit.AUDITOR.set_sample_rate(None)
    audit.AUDITOR.set_quarantine(None)
    audit.AUDITOR.reset()


# ---------------------------------------------------------------------------
# taxonomy + vocabulary
# ---------------------------------------------------------------------------


class TestTaxonomy:
    def test_tiers_partition_into_contract_classes(self):
        for surface, tiers in audit.TIERS.items():
            assert tiers[-1] in (audit.TIER_HOST, audit.TIER_CACHED)
            for t in tiers:
                assert t in audit.ALL_TIERS
        for t in audit.ALL_TIERS:
            if t in (audit.TIER_HOST, audit.TIER_CACHED,
                     audit.TIER_SHED):
                # host is the reference, cached is generation-fresh,
                # shed never served an answer (ISSUE 15) — none carry
                # a parity contract
                continue
            exact = t in audit.EXACT_TIERS
            stat = t in audit.STATISTICAL_FLOORS
            assert exact != stat, t  # exactly one contract class
        # the ISSUE's named examples exist under their surfaces
        assert "hybrid_walk_quant" in audit.TIERS["hybrid"]
        assert "hybrid_brute_f32" in audit.TIERS["hybrid"]
        assert "vector_pq" in audit.TIERS["vector"]
        assert "graph_chain_device" in audit.TIERS["graph"]

    def test_floors(self):
        assert audit.tier_floor("graph_chain_device") == 1.0
        assert audit.tier_floor("hybrid_brute_f32") == 1.0
        assert audit.tier_floor("hybrid_walk_f32") == 0.95
        assert audit.tier_floor("vector_pq") == 0.95

    def test_legacy_events_normalize_onto_the_vocabulary(self):
        for event, reason in audit._LEGACY_REASONS.items():
            assert reason in audit.REASONS, (event, reason)
        assert audit.normalize_reason("exact_fallback_itopk") \
            == "itopk_exceeded"
        assert audit.normalize_reason("quant_fallback_changelog") \
            == "changelog_overrun"
        # vocabulary values pass through; unknowns map to error
        for r in audit.REASONS:
            assert audit.normalize_reason(r) == r
        assert audit.normalize_reason("brand_new_event") == "error"

    def test_parity_of(self):
        p = audit.ShadowAuditor.parity_of
        assert p(["a", "b", "c"], ["a", "b", "c"], 3, exact=True) == 1.0
        assert p(["a", "c", "b"], ["a", "b", "c"], 3, exact=True) \
            == pytest.approx(1 / 3)
        # recall ignores order
        assert p(["a", "c", "b"], ["a", "b", "c"], 3, exact=False) == 1.0
        assert p(["x", "y"], ["a", "b"], 2, exact=False) == 0.0
        # host found nothing: agreeing is parity 1, extras are not
        assert p([], [], 5, exact=True) == 1.0
        assert p(["a"], [], 5, exact=True) == 0.0


# ---------------------------------------------------------------------------
# auditor unit behavior
# ---------------------------------------------------------------------------


class TestAuditorUnit:
    def test_rate_parsing(self):
        assert audit._parse_rate("0") == 0.0
        assert audit._parse_rate("off") == 0.0
        assert audit._parse_rate("") == 0.0
        assert audit._parse_rate("1/256") == pytest.approx(1 / 256)
        assert audit._parse_rate("0.5") == 0.5
        assert audit._parse_rate("on") == pytest.approx(1 / 256)
        assert audit._parse_rate("garbage") == 0.0

    def test_sampling_interval_and_budget(self):
        a = audit.ShadowAuditor(rate=0.5, max_qps=1000.0)
        enq = [a.maybe_sample("vector", "vector_brute_f32", ["a"], 1,
                              lambda: ["a"]) for _ in range(10)]
        assert sum(enq) == 5  # every 2nd query at rate 1/2
        a.flush()
        # budget: 1 token/s cap — the second sample inside the same
        # second must be dropped, counted, and never block
        b = audit.ShadowAuditor(rate=1.0, max_qps=1.0)
        assert b.maybe_sample("vector", "vector_brute_f32", ["a"], 1,
                              lambda: ["a"])
        dropped0 = _counter_value("nornicdb_audit_dropped_total",
                                  ("budget",))
        assert not b.maybe_sample("vector", "vector_brute_f32", ["a"],
                                  1, lambda: ["a"])
        assert _counter_value("nornicdb_audit_dropped_total",
                              ("budget",)) == dropped0 + 1

    def test_queue_full_drops_without_blocking(self):
        gate = threading.Event()
        a = audit.ShadowAuditor(rate=1.0, max_qps=1e9, queue_cap=2)

        def slow_ref():
            gate.wait(5)
            return ["a"]

        dropped0 = _counter_value("nornicdb_audit_dropped_total",
                                  ("queue_full",))
        t0 = time.perf_counter()
        results = [a.maybe_sample("vector", "vector_brute_f32", ["a"],
                                  1, slow_ref) for _ in range(8)]
        elapsed = time.perf_counter() - t0
        gate.set()
        a.flush()
        # the worker may have drained at most a couple while enqueuing;
        # the rest must drop — and the WHOLE loop never blocks on the
        # slow reference execution
        assert elapsed < 1.0
        assert results.count(False) >= 4
        assert _counter_value("nornicdb_audit_dropped_total",
                              ("queue_full",)) > dropped0

    def test_host_and_cached_tiers_never_sampled(self):
        a = audit.ShadowAuditor(rate=1.0, max_qps=1e9)
        assert not a.maybe_sample("hybrid", "host", ["a"], 1,
                                  lambda: ["a"])
        assert not a.maybe_sample("hybrid", "cached", ["a"], 1,
                                  lambda: ["a"])

    def test_concurrent_write_drops_sample_instead_of_mismatch(self):
        """A write landing between sampling and the reference replay
        (or during it) makes the comparison meaningless: the sample is
        dropped as ``stale`` — never scored as a device mismatch."""
        a = audit.ShadowAuditor(rate=1.0, max_qps=1e9)
        gen = {"v": 1}
        dropped0 = _counter_value("nornicdb_audit_dropped_total",
                                  ("stale",))
        assert a.maybe_sample(
            "vector", "vector_brute_f32", ["a"], 1,
            ref=lambda: ["TOTALLY-DIFFERENT"],
            versions=dict(gen), versions_now=lambda: {"v": gen["v"]})
        gen["v"] = 2  # the "write" lands before the worker replays
        a.flush()
        time.sleep(0.1)
        assert a.mismatches == 0 and a.sampled == 0
        assert _counter_value("nornicdb_audit_dropped_total",
                              ("stale",)) == dropped0 + 1
        # unchanged versions still score normally
        assert a.maybe_sample(
            "vector", "vector_brute_f32", ["a"], 1, ref=lambda: ["a"],
            versions=dict(gen), versions_now=lambda: dict(gen))
        a.flush()
        time.sleep(0.1)
        assert a.sampled == 1 and a.mismatches == 0

    def test_ref_error_is_a_drop_not_a_mismatch(self):
        a = audit.ShadowAuditor(rate=1.0, max_qps=1e9)

        def boom():
            raise RuntimeError("ref failed")

        assert a.maybe_sample("vector", "vector_brute_f32", ["a"], 1,
                              boom)
        a.flush()
        time.sleep(0.1)
        assert a.mismatches == 0
        assert a.sampled == 0


# ---------------------------------------------------------------------------
# tier attribution through the serving paths
# ---------------------------------------------------------------------------


def _vector_service(n=24, seed=3):
    from nornicdb_tpu.search.service import SearchService

    rng = np.random.default_rng(seed)
    svc = SearchService()
    for i in range(n):
        svc.vectors.add(f"v{i}", rng.standard_normal(D)
                        .astype(np.float32))
    return svc, rng


class TestVectorTierAttribution:
    def test_microbatched_ride_counts_and_stamps_brute_tier(self):
        svc, rng = _vector_service()
        q = rng.standard_normal(D).astype(np.float32)
        before = _served("vector", "vector_brute_f32")
        with obs.trace("wire", method="/test") as root:
            hits = svc.vector_search_candidates(q, 5)
        assert hits
        assert _served("vector", "vector_brute_f32") == before + 1
        assert root.attrs.get("served_by") == "vector_brute_f32"
        # per-tier latency histogram observed this rider
        fam = REGISTRY.get("nornicdb_served_tier_seconds")
        child = fam.children().get(("vector", "vector_brute_f32"))
        assert child is not None and child.snapshot()["count"] >= 1

    def test_exact_path_counts_brute_tier(self):
        svc, rng = _vector_service()
        q = rng.standard_normal(D).astype(np.float32)
        before = _served("vector", "vector_brute_f32")
        svc.vector_search_candidates(q, 5, exact=True)
        assert _served("vector", "vector_brute_f32") == before + 1

    def test_hnsw_counts_host_tier(self):
        svc, rng = _vector_service(n=32)
        from nornicdb_tpu.search.hnsw import HNSWIndex

        items = [(f"v{i}", svc.vectors.get(f"v{i}")) for i in range(32)]
        idx = HNSWIndex(m=4, ef_search=16)
        idx.build(items)
        svc.hnsw = idx
        before = _served("vector", "host")
        svc.vector_search_candidates(
            rng.standard_normal(D).astype(np.float32), 5)
        assert _served("vector", "host") == before + 1

    def test_tier_stage_split_recorded(self):
        svc, rng = _vector_service()
        svc.vector_search_candidates(
            rng.standard_normal(D).astype(np.float32), 5)
        fam = REGISTRY.get("nornicdb_tier_stage_seconds")
        kids = fam.children()
        assert ("vector_brute_f32", "device_dispatch") in kids
        assert ("vector_brute_f32", "coalesce_wait") in kids


def _hybrid_walk_service(monkeypatch, n=320, seed=59):
    """Service whose fused hybrid serves the WALK tier: clustered
    corpus, walk_min_n below the corpus size, inline builds."""
    from nornicdb_tpu.search.service import SearchService
    from nornicdb_tpu.storage import MemoryEngine
    from nornicdb_tpu.storage.types import Node

    monkeypatch.setenv("NORNICDB_HYBRID_MIN_N", "50")
    monkeypatch.setenv("NORNICDB_HYBRID_INLINE_BUILD", "1")
    monkeypatch.setenv("NORNICDB_HYBRID_WALK_MIN_N", "100")
    rng = np.random.default_rng(seed)
    cent = (rng.standard_normal((8, D)) * 2.0).astype(np.float32)
    store = MemoryEngine()
    svc = SearchService(storage=store)
    for i in range(n):
        text = " ".join(rng.choice(VOCAB, size=int(rng.integers(3, 10))))
        node = Node(id=f"n{i}", labels=["Doc"],
                    properties={"content": text},
                    embedding=list(
                        (cent[i % 8] + 0.4 * rng.standard_normal(D))
                        .astype(np.float32)))
        store.create_node(node)
        svc.index_node(node)
    return svc, cent, rng


class TestRiderAccurateMidBatchDegrade:
    """ISSUE 10 satellite: a coalesced hybrid batch where ONE rider's
    live-filter forces the host re-fuse must count one host-tier and
    N-1 device-tier queries, with matching ``served_by`` spans."""

    def test_one_rider_degrades_neighbors_keep_walk_tier(
            self, monkeypatch):
        svc, cent, rng = _hybrid_walk_service(monkeypatch)
        # first search builds the fused pipeline + walk graph
        warm = svc.search("term1 term2", limit=5,
                          query_embedding=cent[1])
        assert warm is not None
        fh = svc._fused
        assert fh is not None and fh.cagra is not None \
            and fh.cagra.graph_built
        # freeze rebuild cadence: the tombstone below must be served
        # through the stale graph's live-filter, not a rebuild
        fh.cagra.rebuild_stale_frac = 1e9
        # victim: a doc rider 0 will rank top-1 (it IS the query)
        victim_emb = np.asarray(svc.vectors.get("n0"), np.float32)
        svc.remove_node("n0")

        n_riders = 4
        barrier = threading.Barrier(n_riders)
        spans = [None] * n_riders
        results = [None] * n_riders

        def rider(i):
            emb = victim_emb if i == 0 else cent[(i % 7) + 1]
            with obs.trace("wire", method=f"/rider{i}") as root:
                barrier.wait(5)
                results[i] = svc.search(
                    f"term{i} term{i + 1}", limit=5,
                    query_embedding=np.asarray(emb, np.float32))
            spans[i] = root

        host0 = _served("hybrid", "host")
        walk0 = _served("hybrid", "hybrid_walk_f32")
        threads = [threading.Thread(target=rider, args=(i,))
                   for i in range(n_riders)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20)
        assert all(r is not None for r in results)
        # rider 0's live-filter correction made ITS row host; the
        # other riders kept the walk tier — rider-accurate counts
        assert _served("hybrid", "host") == host0 + 1
        assert _served("hybrid", "hybrid_walk_f32") == walk0 + 3
        assert spans[0].attrs.get("served_by") == "host"
        for i in range(1, n_riders):
            assert spans[i].attrs.get("served_by") == "hybrid_walk_f32", i
        # the batch's live-filter step-down landed in the ledger
        recent = audit.degrade_snapshot(limit=20)
        assert any(r["reason"] == "live_filter"
                   and r["from_tier"] == "hybrid_walk_f32"
                   and r["to_tier"] == "host" for r in recent)

    def test_host_served_query_counts_once_not_twice(self, monkeypatch):
        """A fused-eligible query that fell to the host hybrid path
        counts ONE hybrid:host serve — the nested vector ride inside
        it is a sub-dispatch, not a second served query."""
        from nornicdb_tpu.search.service import SearchService
        from nornicdb_tpu.storage import MemoryEngine
        from nornicdb_tpu.storage.types import Node

        monkeypatch.setenv("NORNICDB_HYBRID_FUSED", "0")  # host serves
        rng = np.random.default_rng(23)
        store = MemoryEngine()
        svc = SearchService(storage=store)
        for i in range(30):
            node = Node(id=f"h{i}", labels=["Doc"],
                        properties={"content": f"term{i % 5} body"},
                        embedding=list(rng.standard_normal(D)
                                       .astype(np.float32)))
            store.create_node(node)
            svc.index_node(node)
        host0 = _served("hybrid", "host")
        vec0 = sum(c.value for (s, _t), c in
                   REGISTRY.get("nornicdb_served_tier_total")
                   .children().items() if s == "vector")
        svc.search("term1 term2", limit=5,
                   query_embedding=rng.standard_normal(D)
                   .astype(np.float32))
        assert _served("hybrid", "host") == host0 + 1
        vec1 = sum(c.value for (s, _t), c in
                   REGISTRY.get("nornicdb_served_tier_total")
                   .children().items() if s == "vector")
        assert vec1 == vec0  # no second increment for the same query

    def test_brute_tier_counts_when_walk_disabled(self, monkeypatch):
        svc, cent, rng = _hybrid_walk_service(monkeypatch, n=160)
        monkeypatch.setenv("NORNICDB_HYBRID_WALK_MIN_N", "100000")
        svc._fused = None  # re-wrap under the new walk floor
        before = _served("hybrid", "hybrid_brute_f32")
        with obs.trace("wire", method="/t") as root:
            svc.search("term3 term4", limit=5, query_embedding=cent[2])
        assert _served("hybrid", "hybrid_brute_f32") == before + 1
        assert root.attrs.get("served_by") == "hybrid_brute_f32"


# ---------------------------------------------------------------------------
# degrade ledger
# ---------------------------------------------------------------------------


class TestDegradeLedger:
    def test_cagra_itopk_fallback_lands_structured_record(self):
        from nornicdb_tpu.search.cagra import CagraIndex

        rng = np.random.default_rng(11)
        idx = CagraIndex(min_n=32, itopk=16, n_seeds=32, hash_bits=10)
        idx.add_batch([(f"v{i}", rng.standard_normal(16)
                        .astype(np.float32)) for i in range(64)])
        assert idx.build()
        before = _counter_value(
            "nornicdb_degrade_total",
            ("vector", "vector_walk_f32", "vector_brute_f32",
             "itopk_exceeded"))
        with obs.trace("wire", method="/t") as root:
            idx.search_batch(rng.standard_normal((1, 16))
                             .astype(np.float32), k=32)
        assert _counter_value(
            "nornicdb_degrade_total",
            ("vector", "vector_walk_f32", "vector_brute_f32",
             "itopk_exceeded")) == before + 1
        rec = next(r for r in audit.degrade_snapshot(20)
                   if r["reason"] == "itopk_exceeded")
        # schema: every ledger record carries the full edge + versions
        assert rec["surface"] == "vector"
        assert rec["from_tier"] == "vector_walk_f32"
        assert rec["to_tier"] == "vector_brute_f32"
        assert "ts" in rec and "index" in rec
        assert "build_seq" in rec["versions"]
        assert rec["trace_id"]  # grafted into the owning trace
        assert "degrade" in root.span_names()

    def test_ring_is_bounded(self):
        ledger = audit.DegradeLedger(capacity=16)
        for i in range(40):
            ledger.record({"reason": f"r{i % 3}"})
        assert ledger.recorded == 40
        snap = ledger.snapshot(limit=100)
        assert len(snap) == 16
        assert snap[0]["reason"] == "r0"  # newest (i=39) first


# ---------------------------------------------------------------------------
# HTTP admin + readyz surfaces
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serving():
    import nornicdb_tpu
    from nornicdb_tpu.api.http_server import HttpServer

    db = nornicdb_tpu.open(auto_embed=False)
    rng = np.random.default_rng(21)
    for i in range(24):
        db.store(f"doc {i} term{i % 7}", node_id=f"st-{i}",
                 embedding=list(rng.standard_normal(D)
                                .astype(np.float32)))
    db.search.search("term1", mode="text")  # stand up the indexes
    http = HttpServer(db, port=0).start()
    yield {"db": db, "http": http}
    http.stop()
    db.close()


def _http_get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return json.loads(resp.read())


def _readyz(port):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=5) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestAdminSurfaces:
    def test_admin_degrades_schema(self, serving):
        audit.record_degrade("hybrid", "hybrid_walk_f32",
                             "hybrid_brute_f32", "underfill",
                             index="test:deg", versions={"g": 1})
        doc = _http_get(serving["http"].port, "/admin/degrades")
        assert set(doc) >= {"recorded", "capacity", "by_reason",
                            "degrades"}
        assert doc["recorded"] >= 1
        assert doc["degrades"][0]["ts"] >= doc["degrades"][-1]["ts"]
        rec = next(r for r in doc["degrades"]
                   if r.get("index") == "test:deg")
        assert set(rec) >= {"ts", "surface", "from_tier", "to_tier",
                            "reason"}
        assert rec["reason"] in audit.REASONS
        assert doc["by_reason"].get("underfill", 0) >= 1
        # /admin/degrades/<limit> truncates
        doc2 = _http_get(serving["http"].port, "/admin/degrades/1")
        assert len(doc2["degrades"]) <= 1

    def test_telemetry_carries_tier_mix_and_parity(self, serving):
        db = serving["db"]
        db.search.vector_search_candidates(
            np.zeros(D, np.float32) + 0.1, 3)
        doc = _http_get(serving["http"].port, "/admin/telemetry")
        assert "tiers" in doc and "parity" in doc
        assert doc["tiers"].get("vector", {}).get(
            "vector_brute_f32", 0) >= 1
        assert set(doc["parity"]) >= {"enabled", "sample_rate",
                                      "sampled", "mismatches", "tiers",
                                      "quarantine"}


class TestInjectedMismatch:
    """Acceptance: a monkeypatched device answer produces a
    parity-gauge drop, a flight-recorder repro dump, and a /readyz
    reason."""

    def test_mismatch_gauge_dump_and_readyz(self, serving, monkeypatch,
                                            tmp_path):
        from nornicdb_tpu.obs import slo

        db = serving["db"]
        svc = db.search
        monkeypatch.setenv("NORNICDB_OBS_DUMP_DIR", str(tmp_path))
        monkeypatch.setenv("NORNICDB_AUDIT_WINDOW", "8")
        monkeypatch.setenv("NORNICDB_AUDIT_MIN_SAMPLES", "2")
        monkeypatch.setenv("NORNICDB_AUDIT_DUMP_INTERVAL_S", "0")
        monkeypatch.setattr(slo, "_engine", None)  # pick up dump dir
        audit.AUDITOR.set_sample_rate(1.0)

        orig = svc.vectors.search_batch

        def mangled(queries, k=10, exact=False):
            out = orig(queries, k, exact=exact)
            if exact:
                return out  # the host reference stays honest
            return [list(reversed(row)) for row in out]

        monkeypatch.setattr(svc.vectors, "search_batch", mangled)
        rng = np.random.default_rng(77)
        for _ in range(4):
            svc.vector_search_candidates(
                rng.standard_normal(D).astype(np.float32), 5)
        audit.AUDITOR.flush()
        time.sleep(0.2)

        fam = REGISTRY.get("nornicdb_parity_ratio")
        child = fam.children().get(("vector", "vector_brute_f32"))
        assert child is not None and child.value < 1.0
        assert _counter_value("nornicdb_audit_mismatch_total",
                              ("vector", "vector_brute_f32")) >= 1
        # self-contained repro dump through the PR 5 flight recorder
        dumps = sorted(glob.glob(str(tmp_path / "flightrec-*.jsonl")))
        assert dumps, os.listdir(tmp_path)
        lines = [json.loads(ln) for ln in
                 open(dumps[-1], encoding="utf-8")]
        meta = lines[0]
        assert meta["reason"].startswith(
            "parity_mismatch:vector_brute_f32")
        repro = next(ln for ln in lines if ln["kind"] == "parity_repro")
        rec = repro["record"]
        assert rec["tier"] == "vector_brute_f32"
        assert rec["device_ids"] and rec["host_ids"]
        assert rec["device_ids"] != rec["host_ids"]
        assert "versions" in rec and rec["parity"] < 1.0
        # the dump also carries the tier mix / degrade / parity state
        kinds = {ln["kind"] for ln in lines}
        assert {"tiers", "degrades", "parity"} <= kinds
        # sustained breach surfaces in /readyz
        status, doc = _readyz(serving["http"].port)
        assert status == 503
        assert any(r.startswith("parity_breach:vector:vector_brute_f32")
                   for r in doc["reasons"])
        assert doc["checks"]["parity_breaches"] >= 1
        # clears once the device answers heal and the window refills
        monkeypatch.setattr(svc.vectors, "search_batch", orig)
        for _ in range(16):
            svc.vector_search_candidates(
                rng.standard_normal(D).astype(np.float32), 5)
        audit.AUDITOR.flush()
        time.sleep(0.2)
        status, doc = _readyz(serving["http"].port)
        assert status == 200, doc


class TestQuarantine:
    """With quarantine enabled a breached tier steps down its existing
    ladder (the real serving gate, not a mock) and recovers after the
    breach clears."""

    def test_walk_tier_steps_down_and_recovers(self, monkeypatch):
        from nornicdb_tpu.search.hybrid_fused import FusedHybrid
        from nornicdb_tpu.search.microbatch import pow2_bucket
        from nornicdb_tpu.search.bm25 import tokenize

        monkeypatch.setenv("NORNICDB_AUDIT_WINDOW", "4")
        monkeypatch.setenv("NORNICDB_AUDIT_MIN_SAMPLES", "2")
        monkeypatch.setenv("NORNICDB_AUDIT_QUARANTINE_S", "1.0")
        audit.AUDITOR.set_sample_rate(1.0)
        audit.AUDITOR.set_quarantine(True)

        rng = np.random.default_rng(13)
        cent = (rng.standard_normal((4, D)) * 2.0).astype(np.float32)
        bm25 = BM25Index()
        brute = BruteForceIndex()
        for i in range(200):
            words = rng.choice(VOCAB, size=6)
            bm25.index(f"d{i}", " ".join(words))
            brute.add(f"d{i}", cent[i % 4]
                      + 0.4 * rng.standard_normal(D).astype(np.float32))
        fh = FusedHybrid(bm25, brute, min_n=1, walk_min_n=1)
        assert fh.build()
        fh.cagra.min_n = 1
        assert fh.cagra.build()

        def rows(n=1):
            kq = pow2_bucket(16)
            extras = [{"tokens": tokenize("term1 term2"), "n_cand": 16,
                       "w": (1.0, 1.0)} for _ in range(n)]
            embs = np.asarray([cent[0]] * n, np.float32)
            return fh.search_batch(embs, kq, extras)

        assert rows()[0]["served_by"] == "hybrid_walk_f32"
        # breach the walk tier: injected bad parity samples
        quarantined_at = time.time()
        for _ in range(3):
            audit.AUDITOR.maybe_sample(
                "hybrid", "hybrid_walk_f32", ["x", "y", "z"], 3,
                lambda: ["a", "b", "c"])
        audit.AUDITOR.flush()
        deadline = time.time() + 5
        while not audit.parity_breaches() and time.time() < deadline:
            time.sleep(0.01)
        assert audit.parity_breaches()
        assert not audit.tier_allowed("hybrid_walk_f32")
        # the tier steps DOWN its ladder: brute-fused serves, ledger
        # records the quarantine step
        row = rows()[0]
        assert row["served_by"] == "hybrid_brute_f32"
        assert any(r["reason"] == "quarantine"
                   and r["from_tier"] == "hybrid_walk_f32"
                   for r in audit.degrade_snapshot(10))
        # after the quarantine window the tier re-probes; good samples
        # heal the window and the breach clears
        time.sleep(max(0.0, quarantined_at + 1.1 - time.time()))
        assert audit.tier_allowed("hybrid_walk_f32")
        assert rows()[0]["served_by"] == "hybrid_walk_f32"
        for _ in range(8):
            audit.AUDITOR.maybe_sample(
                "hybrid", "hybrid_walk_f32", ["a", "b", "c"], 3,
                lambda: ["a", "b", "c"])
        audit.AUDITOR.flush()
        time.sleep(0.2)
        assert not audit.parity_breaches()
        assert rows()[0]["served_by"] == "hybrid_walk_f32"


# ---------------------------------------------------------------------------
# overhead guard (acceptance): auditing on, hot path within budget
# ---------------------------------------------------------------------------


class TestOverheadGuard:
    def test_audited_search_path_within_budget(self):
        """The tier-attributed + audit-sampled serving path (counter,
        tier histogram, stage split, sampling decision at the default
        1/256 rate) vs the same path with telemetry disabled. Budget:
        ≤ 2x + 1 ms/op — the same guard the obs layers are held to."""
        idx = BruteForceIndex()
        rng = np.random.default_rng(11)
        vecs = rng.standard_normal((512, D)).astype(np.float32)
        idx.add_batch([(f"v{i}", vecs[i]) for i in range(512)])
        mb = MicroBatcher(idx.search_batch, surface="t-audit",
                          tier_surface="vector")
        n = 300

        def one(i):
            with obs.trace("wire", method="/audited"):
                hits = mb.search(vecs[i % 512], 10)
                if audit.sampling_active():
                    tier = audit.last_served()
                    if tier:
                        audit.maybe_sample(
                            "vector", tier, [h for h, _ in hits], 10,
                            lambda: [h for h, _ in hits])

        def measure():
            for i in range(30):
                one(i)
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for i in range(n):
                    one(i)
                best = min(best, time.perf_counter() - t0)
            return best

        audit.AUDITOR.set_sample_rate(1.0 / 256.0)
        t_on = measure()
        audit.AUDITOR.flush()
        obs.set_enabled(False)
        try:
            t_off = measure()
        finally:
            obs.set_enabled(True)
            audit.AUDITOR.set_sample_rate(None)
        per_op_on = t_on / n
        per_op_off = t_off / n
        assert per_op_on <= 2.0 * per_op_off + 1e-3, (
            f"audited {per_op_on * 1e6:.1f}us/op vs "
            f"bare {per_op_off * 1e6:.1f}us/op")


# ---------------------------------------------------------------------------
# catalog lint extensions + sentinel gates
# ---------------------------------------------------------------------------


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestCatalogLintExtensions:
    def _lint(self):
        import sys

        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import check_metrics_catalog as lint
        finally:
            sys.path.pop(0)
        return lint

    def test_tier_and_reason_vocabulary_documented(self):
        lint = self._lint()
        with open(os.path.join(REPO, "docs", "observability.md"),
                  encoding="utf-8") as f:
            doc = f.read()
        tiers, reasons = lint.tier_vocabulary()
        assert not lint.missing_terms(doc, tiers)
        assert not lint.missing_terms(doc, reasons)

    def test_declared_kinds_documented_fresh_process(self):
        """Dispatch kinds must come from a FRESH interpreter: the suite
        process has recorded runtime shapes (test kinds, microbatch)
        that are not part of the import-time declared vocabulary."""
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "check_metrics_catalog.py")],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        verdict = json.loads(proc.stdout.strip().splitlines()[-1])
        assert verdict["missing_kinds"] == [], verdict
        assert verdict["missing_tiers"] == [], verdict
        assert verdict["missing_reasons"] == [], verdict
        assert proc.returncode == 0, verdict

    def test_lint_flags_undocumented_vocabulary(self):
        lint = self._lint()
        doc = "served_tier_total only mentions hybrid_brute_f32 here"
        missing = lint.missing_terms(doc, ["hybrid_brute_f32",
                                           "vector_pq"])
        assert missing == ["vector_pq"]
        # substring of a documented name must not pass
        assert lint.missing_terms("hybrid_brute_f32_extra",
                                  ["hybrid_brute_f32"]) \
            == ["hybrid_brute_f32"]

    def test_parity_gauge_and_degrade_families_registered(self):
        for name in ("nornicdb_parity_ratio",
                     "nornicdb_audit_sampled_total",
                     "nornicdb_audit_mismatch_total",
                     "nornicdb_served_tier_total",
                     "nornicdb_degrade_total"):
            assert REGISTRY.get(name) is not None, name


class TestSentinelShadowParity:
    def _run(self, artifact, extra_args=()):
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "bench_sentinel.py"),
             "--baseline", artifact, "--artifact", artifact,
             *extra_args],
            capture_output=True, text=True)
        return proc

    def test_extraction_and_absolute_gates(self, tmp_path):
        import sys

        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import bench_sentinel as bs
        finally:
            sys.path.pop(0)
        doc = {"load": {"shadow_parity": {"exact": 1.0,
                                          "statistical": 0.96}}}
        m = bs.extract_metrics(doc)
        assert m["shadow_parity_exact"] == 1.0
        assert m["shadow_parity_statistical"] == 0.96
        summ = {"summary": True,
                "load": {"shadow_parity_exact": 0.99,
                         "shadow_parity_statistical": 0.9}}
        m2 = bs.extract_metrics(summ)
        assert m2["shadow_parity_exact"] == 0.99
        # exact gates ABSOLUTELY at 1.0 even with no baseline metric
        verdict = bs.compare({"shadow_parity_exact": 0.99}, {})
        assert verdict["verdict"] == "regression"
        assert verdict["flagged"][0]["metric"] == "shadow_parity_exact"
        # statistical floor 0.95
        verdict = bs.compare({"shadow_parity_statistical": 0.9}, {})
        assert verdict["verdict"] == "regression"
        verdict = bs.compare({"shadow_parity_exact": 1.0,
                              "shadow_parity_statistical": 0.96}, {})
        assert verdict["verdict"] == "pass"
        # missing on both sides: skipped, never failed
        verdict = bs.compare({}, {})
        assert "shadow_parity_exact" in verdict["skipped"]
