"""HTTP embedding providers + trained Heimdall checkpoint (VERDICT r1
item 10; reference: pkg/embed/embed.go:342 NewOllama, :640 NewOpenAI;
pkg/heimdall shipping a real SLM)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from nornicdb_tpu.embed import (
    EmbedHTTPError,
    OllamaEmbedder,
    OpenAIEmbedder,
    make_http_embedder,
)


class _MockHandler(BaseHTTPRequestHandler):
    """Speaks both the Ollama and OpenAI embedding wire contracts."""

    fail_next = 0  # 5xx injections
    seen_auth = []

    def log_message(self, *a):  # noqa: D102
        pass

    def do_POST(self):  # noqa: N802
        body = json.loads(self.rfile.read(
            int(self.headers["Content-Length"])))
        if _MockHandler.fail_next > 0:
            _MockHandler.fail_next -= 1
            self.send_response(503)
            self.end_headers()
            self.wfile.write(b"overloaded")
            return
        if self.path == "/api/embeddings":
            vec = self._vec(body["prompt"])
            doc = {"embedding": vec}
        elif self.path == "/v1/embeddings":
            _MockHandler.seen_auth.append(
                self.headers.get("Authorization"))
            data = [
                {"index": i, "embedding": self._vec(t)}
                for i, t in enumerate(body["input"])
            ]
            data.reverse()  # clients must honor the index field
            doc = {"data": data, "model": body["model"]}
        elif self.path == "/v1/bad-shape/embeddings":
            doc = {"data": []}
        else:
            self.send_response(404)
            self.end_headers()
            return
        payload = json.dumps(doc).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    @staticmethod
    def _vec(text):
        rng = np.random.default_rng(abs(hash(text)) % (2**32))
        return [round(float(x), 6) for x in rng.standard_normal(8)]


@pytest.fixture(scope="module")
def mock_server():
    srv = HTTPServer(("127.0.0.1", 0), _MockHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


class TestOllamaProvider:
    def test_embed_roundtrip(self, mock_server):
        e = OllamaEmbedder(base_url=mock_server, model="test-model")
        v = e.embed("hello world")
        assert len(v) == 8
        assert v == e.embed("hello world")  # deterministic mock
        assert v != e.embed("different")

    def test_batch(self, mock_server):
        e = OllamaEmbedder(base_url=mock_server)
        vs = e.embed_batch(["a", "b"])
        assert len(vs) == 2 and vs[0] != vs[1]

    def test_retries_on_5xx(self, mock_server):
        e = OllamaEmbedder(base_url=mock_server, retries=2)
        _MockHandler.fail_next = 1
        assert len(e.embed("after retry")) == 8

    def test_hard_failure_raises(self):
        e = OllamaEmbedder(base_url="http://127.0.0.1:1", retries=0,
                           timeout=0.5)
        with pytest.raises(EmbedHTTPError):
            e.embed("x")


class TestOpenAIProvider:
    def test_batch_order_restored_from_index(self, mock_server):
        e = OpenAIEmbedder(api_key="sk-test", base_url=mock_server + "/v1")
        vs = e.embed_batch(["first", "second", "third"])
        # mock reverses data; index field must restore order
        assert vs[0] == OllamaEmbedder(base_url=mock_server).embed("first")

    def test_bearer_auth_header_sent(self, mock_server):
        _MockHandler.seen_auth.clear()
        e = OpenAIEmbedder(api_key="sk-secret", base_url=mock_server + "/v1")
        e.embed("x")
        assert _MockHandler.seen_auth == ["Bearer sk-secret"]

    def test_wrong_cardinality_raises(self, mock_server):
        e = OpenAIEmbedder(base_url=mock_server + "/v1/bad-shape")
        with pytest.raises(EmbedHTTPError):
            e.embed("x")

    def test_factory(self, mock_server):
        assert isinstance(make_http_embedder("ollama", base_url=mock_server),
                          OllamaEmbedder)
        assert isinstance(make_http_embedder("openai"), OpenAIEmbedder)
        with pytest.raises(ValueError):
            make_http_embedder("huggingface")


class TestEndToEndIngestViaHTTPProvider:
    def test_store_embed_search(self, mock_server):
        """ingest -> embed via HTTP provider -> hybrid search (VERDICT
        done-criterion for item 10)."""
        import nornicdb_tpu

        db = nornicdb_tpu.open(
            embedder=OllamaEmbedder(base_url=mock_server))
        db.store("the aurora appears over northern norway", node_id="a1")
        db.store("submarine cables cross the atlantic", node_id="a2")
        db.flush()
        hits = db.recall("aurora norway")
        assert hits and hits[0]["id"] == "a1"
        db.close()


class TestHeimdallCheckpoint:
    @property
    def CKPT(self):
        from nornicdb_tpu.heimdall.train import default_checkpoint_path

        path = default_checkpoint_path()
        assert path is not None, "committed checkpoint missing"
        return path

    def test_checkpoint_loads_and_generates_corpus_text(self):
        from nornicdb_tpu.heimdall.model import DecoderModel
        from nornicdb_tpu.heimdall.train import load_params

        cfg, params = load_params(self.CKPT)
        m = DecoderModel(cfg, params)
        out = m.generate("vector search runs on", max_tokens=40,
                         temperature=0.0)
        # trained on DEFAULT_CORPUS: the greedy completion must finish
        # the memorized sentence (non-noise, deterministic)
        assert "tpu" in out, f"unexpected completion {out!r}"
        assert out == m.generate("vector search runs on", max_tokens=40,
                                 temperature=0.0)

    def test_roundtrip_save_load_identical(self, tmp_path):
        from nornicdb_tpu.heimdall.train import load_params, save_params

        cfg, params = load_params(self.CKPT)
        p2 = str(tmp_path / "copy.npz")
        save_params(p2, cfg, params)
        cfg2, params2 = load_params(p2)
        assert cfg == cfg2
        np.testing.assert_array_equal(np.asarray(params["embed"]),
                                      np.asarray(params2["embed"]))

    def test_training_reduces_loss(self):
        from nornicdb_tpu.heimdall.model import DecoderConfig
        from nornicdb_tpu.heimdall.train import DEFAULT_CORPUS, train

        cfg = DecoderConfig(d_model=32, n_heads=2, n_layers=1, d_ff=64,
                            max_seq=64)
        _, l10 = train(DEFAULT_CORPUS, cfg, steps=10, seed=1)
        _, l80 = train(DEFAULT_CORPUS, cfg, steps=80, seed=1)
        assert l80 < l10


def test_jax_generator_defaults_to_committed_checkpoint():
    """The serving path (not just tests) must load the trained weights."""
    from nornicdb_tpu.heimdall.generators import JAXGenerator

    g = JAXGenerator()
    out = g.generate("vector search runs on", max_tokens=40, temperature=0.0)
    assert "tpu" in out, f"generator served random weights: {out!r}"
