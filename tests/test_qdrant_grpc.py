"""Qdrant-compatible surface + native gRPC service tests.

Reference: pkg/qdrantgrpc tests (collections_service_test.go,
points_service_test.go, points_extended_test.go) and pkg/nornicgrpc.
"""

import json

import numpy as np
import pytest

import nornicdb_tpu
from nornicdb_tpu.api.qdrant import QdrantCompat, QdrantError, _match_filter
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


@pytest.fixture()
def compat():
    return QdrantCompat(NamespacedEngine(MemoryEngine(), "test"))


def _mk_points(n, dims=8, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "id": str(i),
            "vector": list(map(float, rng.standard_normal(dims))),
            "payload": {"city": "oslo" if i % 2 == 0 else "bergen",
                        "rank": i},
        }
        for i in range(n)
    ]


class TestQdrantCompat:
    def test_collection_lifecycle(self, compat):
        assert compat.create_collection("docs", {"size": 8,
                                                 "distance": "Cosine"})
        assert compat.list_collections() == ["docs"]
        info = compat.get_collection("docs")
        assert info["points_count"] == 0
        assert info["config"]["params"]["vectors"]["size"] == 8
        with pytest.raises(QdrantError):
            compat.create_collection("docs")
        assert compat.delete_collection("docs")
        assert compat.list_collections() == []

    def test_upsert_search_roundtrip(self, compat):
        compat.create_collection("docs", {"size": 8})
        pts = _mk_points(20)
        assert compat.upsert_points("docs", pts) == 20
        assert compat.count_points("docs") == 20
        # searching with point 3's own vector returns it first
        hits = compat.search_points("docs", pts[3]["vector"], limit=3)
        assert hits[0]["id"] == "3"
        assert hits[0]["score"] > 0.99
        assert hits[0]["payload"]["rank"] == 3

    def test_upsert_rejects_wrong_dims(self, compat):
        compat.create_collection("docs", {"size": 8})
        with pytest.raises(QdrantError):
            compat.upsert_points("docs", [{"id": "1", "vector": [1.0, 2.0]}])

    def test_filtered_search(self, compat):
        compat.create_collection("docs", {"size": 8})
        pts = _mk_points(20)
        compat.upsert_points("docs", pts)
        hits = compat.search_points(
            "docs", pts[0]["vector"], limit=5,
            query_filter={"must": [{"key": "city",
                                    "match": {"value": "bergen"}}]},
        )
        assert hits and all(h["payload"]["city"] == "bergen" for h in hits)

    def test_retrieve_delete_scroll(self, compat):
        compat.create_collection("docs", {"size": 8})
        compat.upsert_points("docs", _mk_points(10))
        got = compat.retrieve_points("docs", ["1", "5", "nope"])
        assert {p["id"] for p in got} == {"1", "5"}
        assert compat.delete_points("docs", ["1"]) == 1
        assert compat.count_points("docs") == 9
        page = compat.scroll_points("docs", limit=4)
        assert len(page["points"]) == 4
        assert page["next_page_offset"] is not None

    def test_index_rebuilt_after_restart(self, compat):
        """Collection + points persist in storage; index rebuilds lazily
        (reference: vector_index_cache.go)."""
        compat.create_collection("docs", {"size": 8})
        pts = _mk_points(5)
        compat.upsert_points("docs", pts)
        fresh = QdrantCompat(compat.storage)  # same storage, empty cache
        hits = fresh.search_points("docs", pts[2]["vector"], limit=1)
        assert hits[0]["id"] == "2"

    def test_missing_collection_404(self, compat):
        with pytest.raises(QdrantError) as ei:
            compat.count_points("ghost")
        assert ei.value.status == 404


class TestQdrantFilters:
    def test_range_and_must_not(self):
        p = {"rank": 7, "city": "oslo"}
        assert _match_filter(p, {"must": [{"key": "rank",
                                           "range": {"gte": 5, "lt": 10}}]})
        assert not _match_filter(p, {"must_not": [
            {"key": "city", "match": {"value": "oslo"}}]})
        assert _match_filter(p, {"should": [
            {"key": "city", "match": {"any": ["oslo", "bergen"]}}]})

    def test_nested_key(self):
        p = {"meta": {"lang": "no"}}
        assert _match_filter(p, {"must": [{"key": "meta.lang",
                                           "match": {"value": "no"}}]})


class TestQdrantREST:
    @pytest.fixture()
    def server(self):
        from nornicdb_tpu.api.http_server import HttpServer

        db = nornicdb_tpu.open()
        srv = HttpServer(db, port=0).start()
        yield srv
        srv.stop()
        db.close()

    def _req(self, server, method, path, body=None):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_rest_roundtrip(self, server):
        st, r = self._req(server, "PUT", "/collections/docs",
                          {"vectors": {"size": 4, "distance": "Cosine"}})
        assert st == 200 and r["result"] is True and r["status"] == "ok"
        st, r = self._req(server, "GET", "/collections")
        assert [c["name"] for c in r["result"]["collections"]] == ["docs"]
        st, r = self._req(server, "PUT", "/collections/docs/points", {
            "points": [
                {"id": 1, "vector": [1, 0, 0, 0], "payload": {"t": "a"}},
                {"id": 2, "vector": [0, 1, 0, 0], "payload": {"t": "b"}},
            ]
        })
        assert st == 200 and r["result"]["status"] == "completed"
        st, r = self._req(server, "POST", "/collections/docs/points/search",
                          {"vector": [1, 0, 0, 0], "limit": 1})
        assert st == 200
        assert r["result"][0]["id"] == 1 or str(r["result"][0]["id"]) == "1"
        st, r = self._req(server, "POST", "/collections/docs/points/count", {})
        assert r["result"]["count"] == 2
        st, r = self._req(server, "GET", "/collections/ghost")
        assert st == 404

    def test_rest_query_api(self, server):
        self._req(server, "PUT", "/collections/q",
                  {"vectors": {"size": 4}})
        self._req(server, "PUT", "/collections/q/points", {
            "points": [{"id": "a", "vector": [0, 0, 1, 0]}]})
        st, r = self._req(server, "POST", "/collections/q/points/query",
                          {"query": [0, 0, 1, 0], "limit": 1})
        assert st == 200 and r["result"]["points"][0]["id"] == "a"


class TestGrpcServices:
    @pytest.fixture()
    def setup(self):
        import grpc

        from nornicdb_tpu.api.grpc_server import GrpcServer
        from nornicdb_tpu.api.proto import nornic_pb2 as pb

        db = nornicdb_tpu.open()
        srv = GrpcServer(db, port=0).start()
        channel = grpc.insecure_channel(srv.address)
        yield db, srv, channel, pb
        channel.close()
        srv.stop()
        db.close()

    def _call(self, channel, service, method, request, resp_cls):
        rpc = channel.unary_unary(
            f"/nornic.v1.{service}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        return rpc(request, timeout=10)

    def test_qdrant_grpc_roundtrip(self, setup):
        db, srv, channel, pb = setup
        r = self._call(channel, "QdrantService", "CreateCollection",
                       pb.CreateCollectionRequest(collection="g",
                                                  vector_size=4),
                       pb.AckResponse)
        assert r.ok
        r = self._call(channel, "QdrantService", "Upsert",
                       pb.UpsertRequest(collection="g", points=[
                           pb.Point(id="p1", vector=[1, 0, 0, 0],
                                    payload_json='{"k": 1}'),
                           pb.Point(id="p2", vector=[0, 1, 0, 0]),
                       ]), pb.AckResponse)
        assert r.ok
        r = self._call(channel, "QdrantService", "SearchPoints",
                       pb.SearchPointsRequest(collection="g",
                                              vector=[1, 0, 0, 0],
                                              limit=1, with_payload=True),
                       pb.SearchPointsResponse)
        assert r.points[0].id == "p1"
        assert json.loads(r.points[0].payload_json) == {"k": 1}
        r = self._call(channel, "QdrantService", "CountPoints",
                       pb.CollectionRequest(collection="g"),
                       pb.CountResponse)
        assert r.count == 2
        r = self._call(channel, "QdrantService", "ListCollections",
                       pb.Empty(), pb.ListCollectionsResponse)
        assert list(r.collections) == ["g"]

    def test_native_search_grpc(self, setup):
        db, srv, channel, pb = setup
        rng = np.random.default_rng(0)
        for i in range(10):
            db.store(f"text {i}", node_id=f"n{i}",
                     embedding=list(map(float, rng.standard_normal(16))))
        db.search.build_indexes()
        target = db.storage.get_node("n4").embedding
        r = self._call(channel, "SearchService", "Search",
                       pb.SearchRequest(vector=target, limit=3),
                       pb.SearchResponse)
        assert r.hits[0].node_id == "n4"
        assert r.hits[0].score > 0.99


class TestReviewRegressions:
    def test_points_exempt_from_embed_queue_and_native_search(self):
        """Embedding-ownership rule: qdrant nodes are never queued for
        embedding nor indexed into the native hybrid search."""
        from nornicdb_tpu.embed.queue import embed_exempt
        from nornicdb_tpu.search.service import SearchService
        from nornicdb_tpu.storage.types import Node

        point = Node(id="qdrant/c/1", labels=["_Qdrant:c"],
                     properties={"payload": {"x": 1}})
        assert embed_exempt(point)
        eng = NamespacedEngine(MemoryEngine(), "t")
        svc = SearchService(eng)
        svc.index_node(point)
        assert len(svc.vectors) == 0
        assert svc.stats.indexed_docs == 0

    def test_shared_compat_across_surfaces(self):
        """REST and gRPC must share one index cache (stale-cache bug)."""
        db = nornicdb_tpu.open()
        try:
            from nornicdb_tpu.api.http_server import HttpServer
            from nornicdb_tpu.api.grpc_server import GrpcServer

            http = HttpServer(db, port=0)
            g = GrpcServer(db, port=0)
            assert http.qdrant is g.qdrant_servicer.compat
        finally:
            db.close()

    def test_dot_and_euclid_distances(self, compat):
        compat.create_collection("dot", {"size": 2, "distance": "Dot"})
        compat.upsert_points("dot", [
            {"id": "small", "vector": [1.0, 0.0]},
            {"id": "big", "vector": [10.0, 0.0]},
        ])
        hits = compat.search_points("dot", [1.0, 0.0], limit=2)
        # dot product rewards magnitude; cosine would tie these
        assert hits[0]["id"] == "big"
        assert hits[0]["score"] == pytest.approx(10.0)

        compat.create_collection("eu", {"size": 2, "distance": "Euclid"})
        compat.upsert_points("eu", [
            {"id": "near", "vector": [1.0, 1.0]},
            {"id": "far", "vector": [5.0, 5.0]},
        ])
        hits = compat.search_points("eu", [0.0, 0.0], limit=2,
                                    score_threshold=3.0)
        # threshold is a max distance for Euclid: 'far' is excluded
        assert [h["id"] for h in hits] == ["near"]

    def test_unsupported_distance_rejected(self, compat):
        with pytest.raises(QdrantError):
            compat.create_collection("bad", {"size": 2,
                                             "distance": "Manhattan"})

    def test_upsert_batch_atomic_validation(self, compat):
        compat.create_collection("atomic", {"size": 2})
        with pytest.raises(QdrantError):
            compat.upsert_points("atomic", [
                {"id": "1", "vector": [1.0, 0.0]},
                {"id": "2", "vector": [1.0, 0.0, 0.0]},  # bad dims
            ])
        assert compat.count_points("atomic") == 0  # nothing applied

    def test_upsert_infers_dims_when_unconfigured(self, compat):
        compat.create_collection("nodim")
        with pytest.raises(QdrantError):
            compat.upsert_points("nodim", [
                {"id": "1", "vector": [1.0, 0.0]},
                {"id": "2", "vector": [1.0]},  # inconsistent
            ])

    def test_selective_filter_fills_limit(self, compat):
        """Progressive widening: a 10%-selective filter must still fill
        the requested limit."""
        import numpy as np

        compat.create_collection("wide", {"size": 4})
        rng = np.random.default_rng(0)
        pts = [
            {"id": str(i),
             "vector": list(map(float, rng.standard_normal(4))),
             "payload": {"rare": i % 10 == 0}}
            for i in range(500)
        ]
        compat.upsert_points("wide", pts)
        hits = compat.search_points(
            "wide", pts[0]["vector"], limit=20,
            query_filter={"must": [{"key": "rare",
                                    "match": {"value": True}}]})
        assert len(hits) == 20
        assert all(h["payload"]["rare"] for h in hits)

    def test_empty_vector_is_validation_error(self, compat):
        compat.create_collection("v", {"size": 2})
        with pytest.raises(QdrantError):
            compat.search_points("v", [], limit=1)

    def test_grpc_auth_token(self):
        import grpc

        from nornicdb_tpu.api.grpc_server import GrpcServer
        from nornicdb_tpu.api.proto import nornic_pb2 as pb

        db = nornicdb_tpu.open()
        srv = GrpcServer(db, port=0, auth_token="s3cret").start()
        try:
            ch = grpc.insecure_channel(srv.address)
            rpc = ch.unary_unary(
                "/nornic.v1.QdrantService/ListCollections",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.ListCollectionsResponse.FromString)
            with pytest.raises(grpc.RpcError) as ei:
                rpc(pb.Empty(), timeout=5)
            assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
            # with the token it works
            r = rpc(pb.Empty(), timeout=5,
                    metadata=(("authorization", "Bearer s3cret"),))
            assert list(r.collections) == []
            ch.close()
        finally:
            srv.stop()
            db.close()


class TestGrpcErrorMapping:
    def test_missing_collection_maps_to_not_found(self):
        import grpc

        from nornicdb_tpu.api.grpc_server import GrpcServer
        from nornicdb_tpu.api.proto import nornic_pb2 as pb

        db = nornicdb_tpu.open()
        srv = GrpcServer(db, port=0).start()
        try:
            ch = grpc.insecure_channel(srv.address)
            rpc = ch.unary_unary(
                "/nornic.v1.QdrantService/CountPoints",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.CountResponse.FromString)
            with pytest.raises(grpc.RpcError) as ei:
                rpc(pb.CollectionRequest(collection="ghost"), timeout=5)
            assert ei.value.code() == grpc.StatusCode.NOT_FOUND
            ch.close()
        finally:
            srv.stop()
            db.close()

    def test_raw_cache_invalidated_on_upsert(self, compat):
        compat.create_collection("dotc", {"size": 2, "distance": "Dot"})
        compat.upsert_points("dotc", [{"id": "a", "vector": [1.0, 0.0]}])
        assert compat.search_points("dotc", [1.0, 0.0], limit=2)[0]["id"] == "a"
        compat.upsert_points("dotc", [{"id": "b", "vector": [5.0, 0.0]}])
        hits = compat.search_points("dotc", [1.0, 0.0], limit=2)
        assert hits[0]["id"] == "b"  # cache saw the new point
        compat.delete_points("dotc", ["b"])
        hits = compat.search_points("dotc", [1.0, 0.0], limit=2)
        assert [h["id"] for h in hits] == ["a"]
