"""Aux command surfaces: OAuth 2.0 provider + OpenAPI/swagger docs
(reference: cmd/oauth-provider, cmd/swagger-ui)."""

import json
import urllib.parse
import urllib.request

import pytest

from nornicdb_tpu.api.oauth_provider import OAuthProvider


@pytest.fixture()
def provider():
    p = OAuthProvider(port=0).start()  # ephemeral port
    yield p
    p.stop()


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, r.read().decode()


def _post(url, form):
    data = urllib.parse.urlencode(form).encode()
    req = urllib.request.Request(url, data=data, headers={
        "Content-Type": "application/x-www-form-urlencoded"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


class TestOAuthProvider:
    def test_discovery(self, provider):
        status, body = _get(
            f"{provider.issuer}/.well-known/oauth-authorization-server")
        assert status == 200
        d = json.loads(body)
        assert d["token_endpoint"] == \
            f"{provider.issuer}/oauth2/v1/token"
        assert d["grant_types_supported"] == ["authorization_code"]

    def test_full_authorization_code_flow(self, provider):
        # 1. authorize: consent form renders
        status, body = _get(
            f"{provider.issuer}/oauth2/v1/authorize?response_type=code"
            f"&client_id=nornicdb&redirect_uri=http://app/cb&state=xyz")
        assert status == 200 and "<form" in body

        # 2. consent: approve -> redirect carrying the code
        import http.client

        parsed = urllib.parse.urlparse(provider.issuer)
        conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                          timeout=10)
        conn.request("POST", "/oauth2/v1/consent",
                     urllib.parse.urlencode({
                         "client_id": "nornicdb",
                         "redirect_uri": "http://app/cb",
                         "state": "xyz", "user_id": "demo"}),
                     {"Content-Type":
                      "application/x-www-form-urlencoded"})
        resp = conn.getresponse()
        assert resp.status == 302
        location = resp.getheader("Location")
        qs = urllib.parse.parse_qs(urllib.parse.urlparse(location).query)
        assert qs["state"] == ["xyz"]
        code = qs["code"][0]

        # 3. token exchange
        status, body, _ = _post(f"{provider.issuer}/oauth2/v1/token", {
            "grant_type": "authorization_code", "code": code,
            "client_id": "nornicdb", "client_secret": "nornicdb-secret",
            "redirect_uri": "http://app/cb"})
        assert status == 200
        token = json.loads(body)["access_token"]

        # 4. userinfo with the bearer token
        status, body = _get(f"{provider.issuer}/oauth2/v1/userinfo",
                            {"Authorization": f"Bearer {token}"})
        assert status == 200
        assert json.loads(body)["preferred_username"] == "demo"

        # 5. codes are single-use
        status, body, _ = _post(f"{provider.issuer}/oauth2/v1/token", {
            "grant_type": "authorization_code", "code": code,
            "client_id": "nornicdb", "client_secret": "nornicdb-secret",
            "redirect_uri": "http://app/cb"})
        assert status == 400
        assert json.loads(body)["error"] == "invalid_grant"

    def test_bad_client_secret_rejected(self, provider):
        code = provider.issue_code("nornicdb", "http://app/cb", "demo")
        status, body, _ = _post(f"{provider.issuer}/oauth2/v1/token", {
            "grant_type": "authorization_code", "code": code,
            "client_id": "nornicdb", "client_secret": "wrong",
            "redirect_uri": "http://app/cb"})
        assert status == 400
        assert json.loads(body)["error"] == "invalid_client"

    def test_redirect_uri_must_match(self, provider):
        code = provider.issue_code("nornicdb", "http://app/cb", "demo")
        out = provider.exchange("authorization_code", code, "nornicdb",
                                "nornicdb-secret", "http://evil/cb")
        assert out == {"error": "invalid_grant"}

    def test_redirect_allowlist_exact_origin(self, provider):
        """Lookalike hosts, malformed ports, and scheme changes are
        rejected; portless allowlist entries accept any port on that
        exact host (dev servers move ports)."""
        assert provider.redirect_allowed("http://localhost:3000/cb") is True
        assert provider.redirect_allowed(
            "http://localhost.evil.example/cb") is False
        assert provider.redirect_allowed(
            "http://localhost:99999/cb") is False  # port out of range
        assert provider.redirect_allowed("http://h:abc/") is False
        assert provider.redirect_allowed("https://localhost/cb") is False
        pinned = OAuthProvider(
            allowed_redirects=["https://app.example:8443/cb"])
        assert pinned.redirect_allowed(
            "https://app.example:8443/cb/done") is True
        assert pinned.redirect_allowed(
            "https://app.example:9000/cb") is False

    def test_userinfo_rejects_bad_token(self, provider):
        try:
            _get(f"{provider.issuer}/oauth2/v1/userinfo",
                 {"Authorization": "Bearer nope"})
            assert False, "expected 401"
        except urllib.error.HTTPError as e:
            assert e.code == 401


class TestOpenApiDocs:
    def test_spec_and_docs_served(self):
        import nornicdb_tpu
        from nornicdb_tpu.api.http_server import HttpServer

        db = nornicdb_tpu.open()
        srv = HttpServer(db, port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            status, body = _get(f"{base}/openapi.json")
            assert status == 200
            spec = json.loads(body)
            assert spec["openapi"].startswith("3.")
            assert "/db/{database}/tx/commit" in spec["paths"]
            status, body = _get(f"{base}/swagger")
            assert status == 200 and body.startswith("<!doctype")
            assert "nornicdb-tpu HTTP API" in body
        finally:
            srv.stop()
            db.close()

    def test_cli_has_oauth_subcommand(self):
        from nornicdb_tpu.cli import _build_parser

        parser = _build_parser()
        args = parser.parse_args(["oauth-provider", "--port", "0"])
        assert args.command == "oauth-provider" and args.port == 0


class TestDiagnostics:
    def test_search_stage_timings_opt_in(self, monkeypatch):
        import nornicdb_tpu

        monkeypatch.setenv("NORNICDB_TPU_SEARCH_DIAG", "1")
        db = nornicdb_tpu.open()
        try:
            db.store("the capital of norway is oslo", node_id="a")
            db.flush()
            assert db.recall("oslo")
            t = db.search.stats.last_timings
            assert {"bm25_ms", "fuse_ms", "enrich_rerank_ms"} <= set(t)
            assert all(v >= 0 for v in t.values())
        finally:
            db.close()

    def test_search_timings_absent_by_default(self, monkeypatch):
        import nornicdb_tpu

        monkeypatch.delenv("NORNICDB_TPU_SEARCH_DIAG", raising=False)
        db = nornicdb_tpu.open()
        try:
            db.store("bergen by the fjord", node_id="b")
            db.flush()
            db.recall("fjord")
            assert db.search.stats.last_timings == {}
        finally:
            db.close()

    def test_search_diag_zero_means_off_and_stale_cleared(
            self, monkeypatch):
        import nornicdb_tpu

        db = nornicdb_tpu.open()
        try:
            db.store("stavanger oil town", node_id="s")
            db.flush()
            monkeypatch.setenv("NORNICDB_TPU_SEARCH_DIAG", "1")
            db.recall("oil")
            assert db.search.stats.last_timings
            # "0" disables (env-flag convention), and stale timings are
            # cleared on the next search rather than served forever
            monkeypatch.setenv("NORNICDB_TPU_SEARCH_DIAG", "0")
            db.recall("oil")
            assert db.search.stats.last_timings == {}
        finally:
            db.close()

    def test_debug_profile_endpoint(self):
        import nornicdb_tpu
        from nornicdb_tpu.api.http_server import HttpServer

        db = nornicdb_tpu.open()
        db.cypher("CREATE (:X {id: 1})-[:R]->(:X {id: 2})")
        srv = HttpServer(db, port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            body = json.dumps({
                "statement": "MATCH (x:X)-[:R]->(y:X) RETURN count(y)",
                "repeat": 20}).encode()
            req = urllib.request.Request(
                f"{base}/debug/profile", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=15) as r:
                out = json.loads(r.read())
            assert out["repeat"] == 20 and out["rows"] == 1
            assert out["wall_ms"] > 0
            assert any("execute" in f["function"]
                       for f in out["top_frames"])
            # missing statement -> 400, not a crash
            req2 = urllib.request.Request(
                f"{base}/debug/profile", data=b"{}",
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req2, timeout=15)
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            # repeat <= 0 clamps to 1; non-integer repeat -> 400
            body3 = json.dumps({"statement": "RETURN 1",
                                "repeat": 0}).encode()
            req3 = urllib.request.Request(
                f"{base}/debug/profile", data=body3,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req3, timeout=15) as r:
                assert json.loads(r.read())["repeat"] == 1
            body4 = json.dumps({"statement": "RETURN 1",
                                "repeat": "abc"}).encode()
            req4 = urllib.request.Request(
                f"{base}/debug/profile", data=body4,
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req4, timeout=15)
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            # syntactically invalid statement -> client error, not 500
            body5 = json.dumps({"statement": "MATCH ("}).encode()
            req5 = urllib.request.Request(
                f"{base}/debug/profile", data=body5,
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req5, timeout=15)
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            srv.stop()
            db.close()
