"""Walk-fused hybrid tier (ISSUE 6): the CAGRA greedy walk as the
vector half of the fused BM25+RRF pipeline.

The contract under test is **walk-parity**: the walk tier is
approximate by construction, so instead of the brute tier's
rank-identity gate its fused top-k must stay within recall@10
tolerance of the host hybrid reference (the sentinel's absolute floor
is 0.95), every freshness gap must degrade DOWN the ladder —
walk-fused -> brute-fused -> host — never to a wrong answer, and the
sharded walk-fused merge must be bit-identical to the single-device
reference loop on the virtual CPU meshes.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

import jax

from nornicdb_tpu.search.bm25 import BM25Index, tokenize
from nornicdb_tpu.search.hybrid_fused import FusedHybrid
from nornicdb_tpu.search.microbatch import pow2_bucket
from nornicdb_tpu.search.rrf import rrf_fuse
from nornicdb_tpu.search.vector_index import BruteForceIndex

VOCAB = [f"term{i}" for i in range(64)]
D = 32
RECALL_FLOOR = 0.95  # the sentinel's absolute walk-parity floor

QUERIES = [
    "term1 term2 term3",
    "term4 term9 term11 term12",
    "term7 term8",
    "term0 term63",
    "term5 term5 term5 term6",
    "term13 term14 term15 term16 term17",
    "term20",
    "term21 term22",
    "term23 term24 term25",
    "term30 term31 term32 term33",
    "term2 textonly0",
    "zzz qqq nothing",           # empty lexical side
    "term6 missingword",
    "term34 term35",
]


def _corpus(n=500, seed=7, centers=8, text_only=8):
    """Clustered corpus — the regime the graph walk serves (a k-NN
    graph over isotropic noise has no structure to navigate)."""
    rng = np.random.default_rng(seed)
    cent = (rng.standard_normal((centers, D)) * 2.0).astype(np.float32)
    bm25 = BM25Index()
    brute = BruteForceIndex()
    for i in range(n):
        words = rng.choice(VOCAB, size=int(rng.integers(3, 12)))
        bm25.index(f"d{i}", " ".join(words))
        brute.add(f"d{i}", cent[i % centers]
                  + 0.4 * rng.standard_normal(D).astype(np.float32))
    for i in range(text_only):
        bm25.index(f"t{i}", f"term1 term2 textonly{i % 3}")
    return bm25, brute, cent, rng


def _walk_pipeline(bm25, brute, n_shards=1, **kw):
    fh = FusedHybrid(bm25, brute, n_shards=n_shards, min_n=1,
                     walk_min_n=1, **kw)
    assert fh.build()
    fh.cagra.min_n = 1
    assert fh.cagra.build()
    return fh


def _fused_rows(fh, queries, embs, overfetch, weights=(1.0, 1.0)):
    kq = pow2_bucket(overfetch)
    extras = [{"tokens": tokenize(q), "n_cand": overfetch,
               "w": tuple(weights)} for q in queries]
    return fh.search_batch(np.asarray(embs, np.float32), kq, extras)


def _host_top(bm25, brute, query, emb, overfetch, weights=()):
    lex = bm25.search(query, overfetch)
    vec = brute.search_batch(
        np.asarray([emb], np.float32), overfetch)[0]
    if lex and vec:
        return rrf_fuse([lex, vec], weights=list(weights),
                        limit=overfetch)
    return lex or vec


def _recall10(fh, bm25, brute, queries, embs, overfetch,
              weights=(1.0, 1.0), expect_tier="walk"):
    rows = _fused_rows(fh, queries, embs, overfetch, weights)
    total = 0.0
    for qi, row in enumerate(rows):
        assert row is not None, f"query {qi} fell back to host"
        if expect_tier is not None:
            assert row["tier"] == expect_tier, (qi, row["tier"])
        host = _host_top(bm25, brute, queries[qi], embs[qi], overfetch,
                         weights)[:10]
        host_ids = {e for e, _ in host}
        got = {e for e, _ in row["fused"][:10]}
        total += len(host_ids & got) / max(len(host_ids), 1)
    return total / len(queries)


def _embs(cent, rng, nq):
    idx = rng.integers(0, len(cent), nq)
    return (cent[idx]
            + 0.4 * rng.standard_normal((nq, D))).astype(np.float32)


# ---------------------------------------------------------------------------
# walk-parity corpus
# ---------------------------------------------------------------------------


class TestWalkParityCorpus:
    def test_recall_tolerance_single_device(self):
        bm25, brute, cent, rng = _corpus()
        fh = _walk_pipeline(bm25, brute)
        embs = _embs(cent, rng, len(QUERIES))
        assert _recall10(fh, bm25, brute, QUERIES, embs, 30) \
            >= RECALL_FLOOR

    def test_recall_with_weights(self):
        bm25, brute, cent, rng = _corpus(seed=11)
        fh = _walk_pipeline(bm25, brute)
        qs = QUERIES[:8]
        embs = _embs(cent, rng, len(qs))
        for w in ((2.0, 0.5), (0.3, 3.0)):
            assert _recall10(fh, bm25, brute, qs, embs, 30,
                             weights=w) >= RECALL_FLOOR

    def test_tombstones_filtered_and_recall_kept(self):
        bm25, brute, cent, rng = _corpus(seed=13)
        fh = _walk_pipeline(bm25, brute)
        dead = {f"d{i}" for i in range(0, 120, 4)}
        for eid in dead:
            bm25.remove(eid)
            brute.remove(eid)
        qs = QUERIES[:8]
        embs = _embs(cent, rng, len(qs))
        rows = _fused_rows(fh, qs, embs, 30)
        for row in rows:
            assert row is not None
            served = {e for e, _ in row["vec"]} \
                | {e for e, _ in row["fused"]}
            assert not (dead & served), "tombstoned id served"
        assert _recall10(fh, bm25, brute, qs, embs, 30,
                         expect_tier=None) >= RECALL_FLOOR

    def test_k_exceeds_walk_pool_degrades_to_brute(self):
        """overfetch deeper than itopk can't come from the walk pool:
        the batch serves the exact tier, rank-identical to host."""
        bm25, brute, cent, rng = _corpus(120, seed=17, text_only=0)
        fh = _walk_pipeline(bm25, brute)
        qs = QUERIES[:4]
        embs = _embs(cent, rng, len(qs))
        rows = _fused_rows(fh, qs, embs, 500)
        for qi, row in enumerate(rows):
            assert row is not None and row["tier"] == "brute"
            host = _host_top(bm25, brute, qs[qi], embs[qi], 500)
            assert [e for e, _ in row["fused"]] == \
                [e for e, _ in host], qi

    def test_text_only_docs_still_fuse(self):
        """Docs with no vector join as lexical-only candidates (the
        l2g = -1 branch) and can still win the fused ranking."""
        bm25, brute, cent, rng = _corpus(seed=19)
        fh = _walk_pipeline(bm25, brute)
        rows = _fused_rows(fh, ["term1 term2 textonly0"],
                           _embs(cent, rng, 1), 30)
        assert rows[0] is not None and rows[0]["tier"] == "walk"
        lex_ids = {e for e, _ in rows[0]["lex"]}
        assert any(e.startswith("t") for e in lex_ids)
        fused_ids = {e for e, _ in rows[0]["fused"]}
        assert any(e.startswith("t") for e in fused_ids)


# ---------------------------------------------------------------------------
# sharded: mesh bit-identity vs the single-device reference
# ---------------------------------------------------------------------------


class TestWalkShardedParity:
    def setup_method(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs the virtual multi-device CPU mesh")

    def _run(self, shards):
        bm25, brute, cent, rng = _corpus(600, seed=23)
        fh = _walk_pipeline(bm25, brute, n_shards=shards)
        assert fh.cagra._graph["shards"] == shards
        assert "mesh" in fh.lex._snap and "mesh" in fh.cagra._graph
        qs = QUERIES
        embs = _embs(cent, rng, len(qs))
        assert _recall10(fh, bm25, brute, qs, embs, 30) >= RECALL_FLOOR

    def test_two_shards(self):
        self._run(2)

    def test_four_shards(self):
        self._run(4)

    def test_mesh_bit_identical_to_reference(self):
        import jax.numpy as jnp

        from nornicdb_tpu.ops.similarity import l2_normalize
        from nornicdb_tpu.search.hybrid_fused import (
            _holder,
            _walk_fused_sharded_impl,
        )

        bm25, brute, cent, rng = _corpus(600, seed=29)
        fh = _walk_pipeline(bm25, brute, n_shards=2)
        snap = fh.lex._snap
        g = fh.cagra._graph
        qs = QUERIES[:4]
        embs = _embs(cent, rng, len(qs))
        fh.lex.refresh_alive(snap)
        toks = [tokenize(q) for q in qs]
        b = len(qs)
        ptr, urow, sel, avgdl = fh.lex.plan(snap, toks, b)
        l2g = fh._ensure_walk_map(snap, g)
        lex_base = (jnp.asarray(ptr), jnp.asarray(urow),
                    jnp.asarray(sel), snap["post_doc"],
                    snap["post_tf"], snap["doc_len"], snap["alive"])
        qn = l2_normalize(jnp.asarray(embs))
        tail = (jnp.asarray(np.full(b, 30, np.int32)),
                jnp.asarray(np.ones(b, np.float32)),
                jnp.asarray(np.ones(b, np.float32)))
        wctx = {"g": g, "l2g": l2g, "iters": g["iters"],
                "width": fh.cagra.search_width,
                "itopk": fh.cagra.itopk,
                "hash_bits": fh.cagra.hash_bits,
                "n_seeds": fh.cagra.n_seeds}
        kp = fh.cagra.itopk
        mesh_out = _walk_fused_sharded_impl(
            *lex_base, l2g, jnp.float32(avgdl), qn, g["matrix"],
            g["adj"], g["validf"], *tail, kq=kp, rrf_k=60,
            iters=wctx["iters"], width=wctx["width"],
            itopk=wctx["itopk"], hash_bits=wctx["hash_bits"],
            n_seeds=wctx["n_seeds"], mesh_holder=_holder(snap["mesh"]))
        loop_out = fh._walk_shard_loop(snap, g, lex_base, l2g, avgdl,
                                       qn, tail, kp, wctx)
        for a_arr, b_arr in zip(mesh_out, loop_out):
            a_np, b_np = np.asarray(a_arr), np.asarray(b_arr)
            if a_np.dtype.kind == "f":
                np.testing.assert_array_equal(
                    a_np.view(np.int32), b_np.view(np.int32))
            else:
                np.testing.assert_array_equal(a_np, b_np)


# ---------------------------------------------------------------------------
# freshness ladder: walk -> brute-fused -> host, read-your-writes
# ---------------------------------------------------------------------------


class TestWalkFreshnessLadder:
    def test_read_your_writes_upsert_visible(self):
        bm25, brute, cent, rng = _corpus(seed=31)
        fh = _walk_pipeline(bm25, brute)
        bm25.index("fresh", "term1 term2 veryfreshterm")
        brute.add("fresh", cent[1])
        rows = _fused_rows(fh, ["term1 veryfreshterm"],
                           np.asarray([cent[1]]), 30)
        assert rows[0] is not None and rows[0]["tier"] == "walk"
        assert any(e == "fresh" for e, _ in rows[0]["lex"])
        assert any(e == "fresh" for e, _ in rows[0]["vec"])
        assert any(e == "fresh" for e, _ in rows[0]["fused"])

    def test_updated_vector_rescored_exactly(self):
        """The walk scored the pre-update vector; the delta side-scan
        must replace it with the exact post-update cosine."""
        bm25, brute, cent, rng = _corpus(seed=37)
        fh = _walk_pipeline(bm25, brute)
        brute.add("d1", cent[2])  # update: move d1 onto center 2
        q = cent[2] / np.linalg.norm(cent[2])
        rows = _fused_rows(fh, ["term1 term2"], np.asarray([cent[2]]),
                           30)
        assert rows[0] is not None and rows[0]["tier"] == "walk"
        vec = dict(rows[0]["vec"])
        assert "d1" in vec
        stored = brute.get("d1")
        exact = float(q @ (stored / np.linalg.norm(stored)))
        assert vec["d1"] == pytest.approx(exact, rel=1e-5)

    def test_delete_landing_mid_batch_still_filtered(self):
        """A remove() racing the batch's host-side planning window must
        still be live-filtered from the walk output: ``stale`` reads
        the LIVE mutation counter after ``delta_block`` drains the
        changelog, so a tombstone landing after an earlier counter
        capture can't compare clean and ride the walk to the caller."""
        bm25, brute, cent, rng = _corpus(seed=47)
        fh = _walk_pipeline(bm25, brute)
        emb = brute.get("d5").copy()  # walk top-1 by construction
        orig_plan = fh.lex.plan
        fired = []

        def plan_hook(snap, token_rows, b):
            if not fired:  # delete mid-batch, before the walk gate
                fired.append(True)
                bm25.remove("d5")
                brute.remove("d5")
            return orig_plan(snap, token_rows, b)

        fh.lex.plan = plan_hook
        try:
            rows = _fused_rows(fh, ["term1 term2"],
                               np.asarray([emb]), 30)
        finally:
            del fh.lex.plan
        assert fired and rows[0] is not None
        assert "d5" not in {e for e, _ in rows[0]["vec"]}, \
            rows[0]["tier"]

    def test_changelog_overrun_degrades_to_brute_then_host(self):
        """Vector changelog overrun -> brute-fused (rank-identical);
        lexical changelog overrun on top -> host path (rows None)."""
        bm25, brute, cent, rng = _corpus(seed=41)
        # pin rebuild cadence so the ladder (not a rebuild) serves
        fh = _walk_pipeline(bm25, brute, rebuild_stale_frac=1e9)
        fh.cagra.rebuild_stale_frac = 1e9
        cap = brute.changelog_cap()
        churn = (cent[rng.integers(0, len(cent), cap + 10)]
                 + 0.4 * rng.standard_normal((cap + 10, D))
                 ).astype(np.float32)
        for i in range(cap + 10):
            brute.add(f"x{i}", churn[i])
        q = "term1 term2"
        emb = cent[1]
        rows = _fused_rows(fh, [q], np.asarray([emb]), 30)
        assert rows[0] is not None and rows[0]["tier"] == "brute"
        host = _host_top(bm25, brute, q, emb, 30)
        assert [e for e, _ in rows[0]["fused"]] == \
            [e for e, _ in host]
        # now overrun the lexical changelog too -> host serves
        for i in range(bm25.changelog_cap() + 10):
            bm25.index(f"y{i}", "term5 bulkchurn")
        rows = _fused_rows(fh, [q], np.asarray([emb]), 30)
        assert rows[0] is None

    def test_pending_graph_build_serves_brute(self):
        bm25, brute, cent, rng = _corpus(seed=43)
        fh = FusedHybrid(bm25, brute, min_n=1, walk_min_n=1,
                         build_inline=False)
        assert fh.cagra is not None and not fh.cagra.graph_built
        fh.lex.build()  # lexical snapshot ready; graph still missing
        rows = _fused_rows(fh, ["term1 term2"],
                           np.asarray([cent[1]]), 30)
        # first batch kicked the background build; it must have served
        # the exact tier (or host) — never a walk over a missing graph
        assert rows[0] is None or rows[0]["tier"] == "brute"
        deadline = time.time() + 10
        while not fh.cagra.graph_built and time.time() < deadline:
            time.sleep(0.02)
        assert fh.cagra.graph_built
        rows = _fused_rows(fh, ["term1 term2"],
                           np.asarray([cent[1]]), 30)
        assert rows[0] is not None and rows[0]["tier"] == "walk"

    def test_underfill_redispatches_exact(self):
        """Mass deletes cluster the walk output on tombstones; the
        under-fill veto re-dispatches through the exact tier instead of
        serving short lists."""
        from nornicdb_tpu.obs import REGISTRY

        bm25, brute, cent, rng = _corpus(400, seed=47, text_only=0)
        fh = _walk_pipeline(bm25, brute, rebuild_stale_frac=1e9)
        fh.cagra.rebuild_stale_frac = 1e9
        for i in range(360):
            brute.remove(f"d{i}")  # bm25 keeps them: lex side intact
        q = "term1 term2 term3"
        emb = cent[1]
        before = _counter(REGISTRY, "nornicdb_hybrid_fused_events_total",
                          "walk_underfill_brute")
        rows = _fused_rows(fh, [q], np.asarray([emb]), 30)
        after = _counter(REGISTRY, "nornicdb_hybrid_fused_events_total",
                         "walk_underfill_brute")
        assert rows[0] is not None and rows[0]["tier"] == "brute"
        assert after == before + 1
        host = _host_top(bm25, brute, q, emb, 30)
        assert [e for e, _ in rows[0]["fused"]] == \
            [e for e, _ in host]

    def test_foreign_brute_graph_never_binds(self):
        """A graph wrapping a DIFFERENT brute index (a background
        build that raced an index reload) must be refused at wrap and
        at rebind — its row ids belong to a discarded corpus."""
        from nornicdb_tpu.search.cagra import CagraIndex

        bm25, brute, cent, rng = _corpus(seed=79)
        other = BruteForceIndex()
        other.add("z", np.ones(D, np.float32))
        foreign = CagraIndex(brute=other, min_n=1)
        fh = FusedHybrid(bm25, brute, min_n=1, walk_min_n=1,
                         cagra=foreign)
        assert fh.cagra is not foreign
        assert fh.cagra._brute is brute
        assert fh.rebind_cagra(foreign) is False
        assert fh.cagra is not foreign

    def test_graph_rebuild_rebinds_join_map(self):
        """A background graph rebuild produces a new row space; the
        l2g map (keyed on build_seq) must rebind on the next batch —
        the stale-wrapper lifecycle the PR 2 ANN wrapper already has."""
        bm25, brute, cent, rng = _corpus(seed=53)
        fh = _walk_pipeline(bm25, brute)
        _fused_rows(fh, ["term1 term2"], np.asarray([cent[1]]), 30)
        snap = fh.lex._snap
        tok0, _ = snap["row_maps"]["l2g"]
        assert tok0 == fh.cagra._graph["build_seq"]
        brute.add("newdoc", cent[3])
        bm25.index("newdoc", "term1 newdocterm")
        assert fh.cagra.build()  # the "background rebuild completed"
        rows = _fused_rows(fh, ["term1 newdocterm"],
                           np.asarray([cent[3]]), 30)
        assert rows[0] is not None and rows[0]["tier"] == "walk"
        tok1, _ = snap["row_maps"]["l2g"]
        assert tok1 == fh.cagra._graph["build_seq"] != tok0
        assert any(e == "newdoc" for e, _ in rows[0]["vec"])


def _counter(registry, name, event):
    text = registry.render()
    needle = f'{name}{{event="{event}"}} '
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.split()[-1])
    return 0.0


def _strategy_count(registry, strategy):
    text = registry.render()
    needle = f'nornicdb_search_strategy_total{{strategy="{strategy}"}} '
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.split()[-1])
    return 0.0


# ---------------------------------------------------------------------------
# service wiring: the third hybrid tier + lifecycle
# ---------------------------------------------------------------------------


def _make_service(store, rng, cent, n=200):
    from nornicdb_tpu.search.service import SearchService
    from nornicdb_tpu.storage.types import Node

    svc = SearchService(storage=store)
    for i in range(n):
        text = " ".join(rng.choice(VOCAB, size=int(rng.integers(3, 10))))
        node = Node(id=f"n{i}", labels=["Doc"],
                    properties={"content": text},
                    embedding=list(
                        (cent[i % len(cent)] + 0.4
                         * rng.standard_normal(D)).astype(np.float32)))
        store.create_node(node)
        svc.index_node(node)
    return svc


class TestServiceWalkTier:
    def _env(self, monkeypatch, walk_min_n="100"):
        monkeypatch.setenv("NORNICDB_HYBRID_MIN_N", "50")
        monkeypatch.setenv("NORNICDB_HYBRID_INLINE_BUILD", "1")
        monkeypatch.setenv("NORNICDB_HYBRID_WALK_MIN_N", walk_min_n)

    def test_walk_strategy_counter_and_recall(self, monkeypatch):
        from nornicdb_tpu.obs import REGISTRY
        from nornicdb_tpu.storage import MemoryEngine

        self._env(monkeypatch)
        rng = np.random.default_rng(59)
        cent = (rng.standard_normal((8, D)) * 2.0).astype(np.float32)
        store = MemoryEngine()
        svc = _make_service(store, rng, cent)
        qv = (cent[1] + 0.4 * rng.standard_normal(D)).astype(np.float32)
        before = _strategy_count(REGISTRY, "hybrid_walk_fused")
        res = svc.search("term1 term2 term3", limit=10,
                         query_embedding=qv)
        after = _strategy_count(REGISTRY, "hybrid_walk_fused")
        assert after == before + 1
        assert svc._fused is not None and svc._fused.cagra is not None
        monkeypatch.setenv("NORNICDB_HYBRID_FUSED", "0")
        svc2 = _make_service(store, rng, cent, n=0)
        for node in store.all_nodes():
            svc2.index_node(node)
        host = svc2.search("term1 term2 term3", limit=10,
                           query_embedding=qv)
        got = {r["id"] for r in res}
        want = {r["id"] for r in host}
        assert len(got & want) / max(len(want), 1) >= RECALL_FLOOR

    def test_walk_span_with_iters_attrs(self, monkeypatch):
        from nornicdb_tpu.obs import tracing
        from nornicdb_tpu.storage import MemoryEngine

        self._env(monkeypatch)
        rng = np.random.default_rng(61)
        cent = (rng.standard_normal((8, D)) * 2.0).astype(np.float32)
        svc = _make_service(MemoryEngine(), rng, cent)
        qv = (cent[2] + 0.4 * rng.standard_normal(D)).astype(np.float32)
        with tracing.trace("walk.test") as root:
            svc.search("term1 term2 term3", limit=5,
                       query_embedding=qv)
        names = root.span_names()
        assert "vector.walk" in names
        assert "lexical.score" in names and "fuse" in names

        def find(span, name):
            if span.name == name:
                return span
            for c in span.children:
                hit = find(c, name)
                if hit is not None:
                    return hit
            return None

        walk_span = find(root, "vector.walk")
        assert walk_span.attrs.get("iters") >= 1
        assert walk_span.attrs.get("itopk") >= 16

    def test_brute_tier_below_walk_floor(self, monkeypatch):
        """Corpus under NORNICDB_HYBRID_WALK_MIN_N keeps the exact
        matmul tier (rank-identical fused path, PR 4 contract)."""
        from nornicdb_tpu.obs import REGISTRY
        from nornicdb_tpu.storage import MemoryEngine

        self._env(monkeypatch, walk_min_n="1000000")
        rng = np.random.default_rng(67)
        cent = (rng.standard_normal((8, D)) * 2.0).astype(np.float32)
        svc = _make_service(MemoryEngine(), rng, cent)
        qv = (cent[1] + 0.4 * rng.standard_normal(D)).astype(np.float32)
        before = _strategy_count(REGISTRY, "hybrid_fused")
        svc.search("term1 term2", limit=5, query_embedding=qv)
        after = _strategy_count(REGISTRY, "hybrid_fused")
        assert after == before + 1

    def test_rebuild_cagra_rebinds_shared_graph(self, monkeypatch):
        """The strategy machine building its CAGRA tier rebinds the
        fused wrapper onto the new graph IN PLACE — one graph in HBM,
        one rebuild cadence, and the lexical snapshot keeps serving
        (the _ensure_fused lifecycle satellite)."""
        from nornicdb_tpu.storage import MemoryEngine

        self._env(monkeypatch)
        monkeypatch.setenv("NORNICDB_VECTOR_ANN_QUALITY", "cagra")
        rng = np.random.default_rng(71)
        cent = (rng.standard_normal((8, D)) * 2.0).astype(np.float32)
        store = MemoryEngine()
        svc = _make_service(store, rng, cent)
        qv = (cent[1] + 0.4 * rng.standard_normal(D)).astype(np.float32)
        svc.search("term1 term2", limit=5, query_embedding=qv)
        f0 = svc._fused
        assert f0 is not None
        own_graph = f0.cagra
        # strategy switch builds the service graph
        svc.hnsw_threshold = 10
        svc._maybe_switch_strategy()
        assert svc.cagra is not None and svc.cagra is not own_graph
        svc.search("term1 term2 term3", limit=5, query_embedding=qv)
        assert svc._fused is f0, "lexical snapshot was torn down"
        assert f0.cagra is svc.cagra, "graph not shared"
        # the rebound walk tier serves from the SERVICE graph
        snap = f0.lex._snap
        tok, _ = snap["row_maps"]["l2g"]
        assert tok == svc.cagra._graph["build_seq"]

    def test_reload_rebinds_fused_wrapper(self, monkeypatch, tmp_path):
        """load_indexes swaps the index objects; the next search must
        re-wrap onto them — the old pipeline (old row->slot maps) can
        never serve the discarded corpus."""
        from nornicdb_tpu.search.service import SearchService
        from nornicdb_tpu.storage import MemoryEngine
        from nornicdb_tpu.storage.types import Node

        self._env(monkeypatch)
        rng = np.random.default_rng(73)
        cent = (rng.standard_normal((8, D)) * 2.0).astype(np.float32)
        store = MemoryEngine()
        svc = SearchService(storage=store,
                            persist_dir=str(tmp_path / "idx"))
        for i in range(120):
            text = " ".join(rng.choice(VOCAB,
                                       size=int(rng.integers(3, 10))))
            node = Node(id=f"n{i}", labels=["Doc"],
                        properties={"content": text},
                        embedding=list(
                            (cent[i % 8] + 0.4
                             * rng.standard_normal(D))
                            .astype(np.float32)))
            store.create_node(node)
            svc.index_node(node)
        qv = (cent[1] + 0.4 * rng.standard_normal(D)).astype(np.float32)
        svc.search("term1 term2", limit=5, query_embedding=qv)
        f0 = svc._fused
        assert f0 is not None
        assert svc.save_indexes()
        assert svc.load_indexes()
        assert svc._fused is None, "wrapper survived reload"
        res = svc.search("term1 term2", limit=5, query_embedding=qv)
        assert res
        f1 = svc._fused
        assert f1 is not None and f1 is not f0
        assert f1.brute is svc.vectors and f1.bm25 is svc.bm25
