"""Adversarial interleaving tests for the replication plane (VERDICT
r4 #7; reference analog: pkg/replication/chaos_test.go — failover under
concurrent writes, fencing, out-of-order delivery).

Covered interleaving classes:
- failover (promotion + fencing) while writer threads are mid-storm on
  the primary: every quorum-acked write survives on the new primary
- fence racing in-flight applies: after fence returns, the deposed
  primary accepts nothing, and writers migrate to the new primary
- wal batches delivered out of order from concurrent threads: the
  standby's reorder buffer must converge to the in-order state
- raft: committed writes survive a leader change forced mid-storm
"""

import threading
import time

import pytest

from nornicdb_tpu.replication import (
    ClusterTransport,
    HAPrimary,
    HAStandby,
    NotPrimaryError,
    ReplicatedEngine,
    ReplicationConfig,
    Role,
)
from nornicdb_tpu.storage import MemoryEngine, WAL, WALEngine
from nornicdb_tpu.storage.types import Node


def _wal_engine(tmp_path, name):
    return WALEngine(MemoryEngine(), WAL(str(tmp_path / name)))


def _pair(tmp_path, sync="quorum", failover_timeout=0.5):
    tp = ClusterTransport("primary")
    ts = ClusterTransport("standby")
    tp.start()
    ts.start()
    cfg_p = ReplicationConfig(
        mode="ha_standby", sync=sync, node_id="primary",
        peers=[ts.addr], heartbeat_interval=0.1,
        failover_timeout=failover_timeout,
    )
    cfg_s = ReplicationConfig(
        mode="ha_standby", node_id="standby",
        heartbeat_interval=0.1, failover_timeout=failover_timeout,
    )
    primary = HAPrimary(_wal_engine(tmp_path, "p"), tp, cfg_p)
    standby = HAStandby(_wal_engine(tmp_path, "s"), ts, cfg_s,
                        primary_addr=tp.addr)
    return primary, standby, tp, ts


class TestFailoverUnderWrites:
    def test_promotion_mid_storm_keeps_all_acked_writes(self, tmp_path):
        """8 writers hammer the primary in quorum mode; mid-storm the
        standby is promoted (which fences the old primary). Every write
        that ACKED before or during the storm must exist on the promoted
        standby; writers that got NotPrimaryError/ConnectionError after
        the fence simply stop — but none of their acked history may be
        lost."""
        primary, standby, tp, ts = _pair(tmp_path)
        acked = set()
        acked_lock = threading.Lock()
        stop = threading.Event()

        def writer(t):
            eng = ReplicatedEngine(primary.engine, primary)
            i = 0
            while not stop.is_set():
                nid = f"w{t}_{i}"
                try:
                    eng.create_node(Node(id=nid, labels=[],
                                         properties={"t": t}))
                except (NotPrimaryError, ConnectionError):
                    return  # fenced mid-storm: expected
                with acked_lock:
                    acked.add(nid)
                i += 1

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        try:
            for t in threads:
                t.start()
            # let the storm actually land acks before pulling the rug
            # (fixed sleeps starve on a loaded single-core box)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                with acked_lock:
                    if len(acked) >= 20:
                        break
                time.sleep(0.01)
            standby.promote()  # fences the primary via transport
            stop.set()
            for t in threads:
                t.join()
            assert standby.role is Role.PRIMARY
            assert primary.role is Role.STANDBY
            with acked_lock:
                final_acked = set(acked)
            assert final_acked  # the storm actually wrote something
            for nid in final_acked:
                assert standby.engine.has_node(nid), (
                    f"quorum-acked {nid} missing on promoted standby")
            # deposed primary must reject further writes
            with pytest.raises(NotPrimaryError):
                primary.apply("create_node",
                              {"id": "late", "labels": [],
                               "properties": {}})
            # ...and the new primary must accept them
            standby.apply("create_node",
                          {"id": "late", "labels": [], "properties": {}})
            assert standby.engine.has_node("late")
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()

    def test_writers_migrate_after_failover(self, tmp_path):
        """End-to-end client story: writers retry against the standby
        after the fence; total committed count on the new primary equals
        acked-on-old + acked-on-new with no overlap loss."""
        primary, standby, tp, ts = _pair(tmp_path)
        acked_old, acked_new = set(), set()
        lock = threading.Lock()
        promoted = threading.Event()

        from nornicdb_tpu.errors import AlreadyExistsError

        def writer(t):
            i = 0
            while i < 200:
                nid = f"m{t}_{i}"
                try:
                    if not promoted.is_set():
                        primary.apply(
                            "create_node",
                            {"id": nid, "labels": [], "properties": {}})
                        with lock:
                            acked_old.add(nid)
                    else:
                        standby.apply(
                            "create_node",
                            {"id": nid, "labels": [], "properties": {}})
                        with lock:
                            acked_new.add(nid)
                    i += 1
                except AlreadyExistsError:
                    # ambiguous-failure retry: the fence raced the ack,
                    # but the quorum write DID land — count it and move
                    # on (the standard idempotent-client story)
                    with lock:
                        acked_new.add(nid)
                    i += 1
                except (NotPrimaryError, ConnectionError):
                    promoted.wait(timeout=5.0)  # failover in progress

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(4)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.1)
            standby.promote()
            promoted.set()
            for t in threads:
                t.join()
            for nid in acked_old | acked_new:
                assert standby.engine.has_node(nid)
            assert len(acked_old) + len(acked_new) == 4 * 200
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()


class TestFencingRaces:
    def test_stale_epoch_batches_rejected_after_fence(self, tmp_path):
        """Batches carrying the old epoch that arrive AFTER the fence
        must be rejected — late in-flight replication from a deposed
        primary can't scribble on the new primary's state."""
        primary, standby, tp, ts = _pair(tmp_path)
        try:
            primary.apply("create_node",
                          {"id": "pre", "labels": [], "properties": {}})
            old_epoch = primary.epoch
            standby.promote()
            reply = standby.handle_wal_batch({
                "type": "wal_batch", "epoch": old_epoch,
                "records": [{"op": "create_node",
                             "data": {"id": "ghost", "labels": [],
                                      "properties": {}},
                             "seq": 999}],
                "primary": "primary",
            })
            assert reply["ok"] is False
            assert not standby.engine.has_node("ghost")
            assert standby.engine.has_node("pre")
        finally:
            primary.close(); standby.close(); tp.close(); ts.close()


class TestOutOfOrderDelivery:
    def test_shuffled_concurrent_batches_converge_in_order(self, tmp_path):
        """Direct handler invocation (the reference tests its handlers
        the same way, ha_standby.go:736-779): 4 threads deliver disjoint
        seq ranges shuffled; the reorder buffer must apply them in seq
        order so create-then-update inversions cannot lose updates."""
        ts = ClusterTransport("s-ooo")
        ts.start()
        cfg = ReplicationConfig(mode="ha_standby", node_id="s-ooo")
        standby = HAStandby(_wal_engine(tmp_path, "s"), ts, cfg)
        try:
            # seq i: create node b<i>; seq i+100: bump its version
            recs = []
            for i in range(1, 101):
                recs.append({"op": "create_node", "seq": i,
                             "data": {"id": f"b{i}", "labels": [],
                                      "properties": {"v": 0}}})
            for i in range(1, 101):
                recs.append({"op": "update_node", "seq": 100 + i,
                             "data": {"id": f"b{i}", "labels": [],
                                      "properties": {"v": 1}}})
            import random as _random
            rng = _random.Random(13)
            rng.shuffle(recs)
            chunks = [recs[i::4] for i in range(4)]

            def deliver(chunk):
                for rec in chunk:
                    standby.handle_wal_batch({
                        "type": "wal_batch", "epoch": 1,
                        "records": [rec], "primary": "p",
                    })

            threads = [threading.Thread(target=deliver, args=(c,))
                       for c in chunks]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert standby.applied_seq == 200
            for i in range(1, 101):
                node = standby.engine.get_node(f"b{i}")
                assert node.properties.get("v") == 1, (
                    f"b{i}: update lost to reordering")
        finally:
            standby.close()
            ts.close()


class TestRaftUnderWrites:
    def test_committed_writes_survive_forced_leader_change(self):
        from nornicdb_tpu.replication import RaftNode
        from nornicdb_tpu.replication.ha_standby import _op_args

        transports = [ClusterTransport(f"rr{i}") for i in range(3)]
        for t in transports:
            t.start()
        addrs = [t.addr for t in transports]
        engines = [MemoryEngine() for _ in range(3)]
        nodes = []
        for i, t in enumerate(transports):
            cfg = ReplicationConfig(
                mode="raft", node_id=f"rr{i}",
                peers=[a for j, a in enumerate(addrs) if j != i],
                # generous timing: a loaded CI box must not livelock
                # the election into a spurious failure
                heartbeat_interval=0.08, election_timeout=(0.3, 0.7),
            )
            eng = engines[i]

            def apply_fn(op, data, _eng=eng):
                getattr(_eng, op)(*_op_args(op, data))

            nodes.append(RaftNode(t, cfg, apply_fn))
        try:
            for n in nodes:
                n.start()
            deadline = time.monotonic() + 5.0
            leader = None
            while time.monotonic() < deadline and leader is None:
                leaders = [n for n in nodes if n.role is Role.PRIMARY]
                leader = leaders[0] if len(leaders) == 1 else None
                time.sleep(0.02)
            assert leader is not None
            acked = []
            for i in range(30):
                leader.apply("create_node",
                             {"id": f"r{i}", "labels": [],
                              "properties": {}})
                acked.append(f"r{i}")
            # forced leader change: silence the old leader's transport
            old = leader
            old_i = nodes.index(old)
            old.close()
            deadline = time.monotonic() + 20.0
            new_leader = None
            while time.monotonic() < deadline and new_leader is None:
                cands = [n for n in nodes
                         if n is not old and n.role is Role.PRIMARY]
                new_leader = cands[0] if cands else None
                time.sleep(0.02)
            assert new_leader is not None, "no new leader elected"
            new_i = nodes.index(new_leader)
            assert new_i != old_i
            for nid in acked:
                assert engines[new_i].has_node(nid), (
                    f"committed {nid} lost across leader change")
        finally:
            for n in nodes:
                try:
                    n.close()
                except Exception:
                    pass
            for t in transports:
                t.close()
