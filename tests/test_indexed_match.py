"""Hash-index candidate narrowing in the row interpreter
(executor._indexed_candidates): the UNWIND bulk-ingest hot path, plus
the staleness guards that force fallback to label scans."""

import time

import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


@pytest.fixture()
def ex():
    return CypherExecutor(NamespacedEngine(MemoryEngine(), "idx"))


def test_unwind_relationship_ingest_uses_index(ex):
    """10k per-row MATCHes must resolve via the hash index: label scans
    would be O(rows x nodes) and take minutes."""
    rows = [{"id": i} for i in range(5_000)]
    ex.execute("UNWIND $rows AS r CREATE (:I {id: r.id})", {"rows": rows})
    pairs = [{"a": i, "b": (i + 1) % 5_000} for i in range(5_000)]
    t0 = time.perf_counter()
    r = ex.execute(
        "UNWIND $pairs AS p MATCH (a:I {id: p.a}), (b:I {id: p.b}) "
        "CREATE (a)-[:NEXT]->(b)", {"pairs": pairs})
    dt = time.perf_counter() - t0
    assert r.stats.relationships_created == 5_000
    assert dt < 10.0, f"{dt:.1f}s — index probe not engaged"
    assert ex.execute(
        "MATCH (:I {id: 0})-[:NEXT]->(b:I) RETURN b.id").rows == [[1]]


def test_same_statement_creates_visible(ex):
    """MATCH after CREATE in one statement sees the created nodes, and
    the indexed path agrees exactly with the scan path."""
    q = ("UNWIND [1, 2] AS i CREATE (:C {cid: i}) WITH i "
         "MATCH (c:C {cid: i}) RETURN count(c)")
    r = ex.execute(q)
    scan_ex = CypherExecutor(NamespacedEngine(MemoryEngine(), "scan"))
    scan_ex.enable_fastpaths = False
    rs = scan_ex.execute(q)
    assert r.rows == rs.rows
    assert r.rows[0][0] >= 2  # creations were matchable
    assert ex.execute("MATCH (c:C) RETURN count(c)").rows == [[2]]


def test_create_then_match_no_duplicates(ex):
    """Regression: a lazy snapshot built mid-statement (after CREATE)
    already contains the created node; the created-nodes union must not
    double it."""
    r = ex.execute("CREATE (:P {id: 1}) WITH 1 AS one "
                   "MATCH (p:P {id: 1}) RETURN p.id")
    assert r.rows == [[1]]
    # and through UNWIND ingest: exactly one edge per pair
    ex.execute("UNWIND [10, 11] AS i CREATE (:P {id: i})")
    r2 = ex.execute(
        "UNWIND [[10, 11]] AS pr MATCH (a:P {id: pr[0]}), "
        "(b:P {id: pr[1]}) CREATE (a)-[:E]->(b)")
    assert r2.stats.relationships_created == 1


def test_scan_baseline_really_scans(ex):
    """enable_fastpaths=False must disable the index probe too (test
    baselines depend on it)."""
    from unittest import mock

    scan_ex = CypherExecutor(NamespacedEngine(MemoryEngine(), "scan2"))
    scan_ex.enable_fastpaths = False
    scan_ex.execute("CREATE (:S {id: 1})")
    with mock.patch.object(scan_ex, "_indexed_candidates",
                           side_effect=AssertionError("probe used")):
        assert scan_ex.execute(
            "UNWIND [1] AS i MATCH (s:S {id: i}) RETURN count(s)"
        ).rows == [[1]]


def test_updates_in_statement_force_fallback(ex):
    """SET before a MATCH in the same statement must not serve stale
    index values."""
    ex.execute("CREATE (:U {k: 'old', id: 1})")
    r = ex.execute(
        "MATCH (u:U {id: 1}) SET u.k = 'new' "
        "WITH u MATCH (v:U {k: 'new'}) RETURN count(v)")
    assert r.rows == [[1]]
    # and the inverse: the old value no longer matches
    r2 = ex.execute("MATCH (v:U {k: 'old'}) RETURN count(v)")
    assert r2.rows == [[0]]


def test_bool_int_distinction_survives_index(ex):
    ex.execute("CREATE (:B {flag: true}), (:B {flag: 1})")
    assert ex.execute(
        "UNWIND [true] AS f MATCH (b:B {flag: f}) "
        "RETURN count(b)").rows == [[1]]
    assert ex.execute(
        "UNWIND [1] AS f MATCH (b:B {flag: f}) "
        "RETURN count(b)").rows == [[1]]


def test_unhashable_and_null_probe_values(ex):
    ex.execute("CREATE (:V {k: 1})")
    assert ex.execute(
        "UNWIND [[1, 2]] AS x MATCH (v:V {k: x}) "
        "RETURN count(v)").rows == [[0]]
    assert ex.execute(
        "UNWIND [null] AS x MATCH (v:V {k: x}) "
        "RETURN count(v)").rows == [[0]]


def test_multi_label_and_second_prop_still_verified(ex):
    ex.execute("CREATE (:A:B {k: 1, j: 'x'}), (:A {k: 1, j: 'y'})")
    assert ex.execute(
        "UNWIND [1] AS v MATCH (n:A:B {k: v}) RETURN count(n)"
    ).rows == [[1]]
    assert ex.execute(
        "UNWIND [1] AS v MATCH (n:A {k: v, j: 'y'}) RETURN count(n)"
    ).rows == [[1]]


def test_merge_bulk_ingest_linear(ex):
    """UNWIND MERGE must stay O(rows): the create-side probe consults an
    incrementally-built map over same-statement creates."""
    rows = [{"id": i} for i in range(5_000)]
    t0 = time.perf_counter()
    r = ex.execute("UNWIND $rows AS r MERGE (:Mi {id: r.id})",
                   {"rows": rows})
    dt = time.perf_counter() - t0
    assert r.stats.nodes_created == 5_000
    assert dt < 10.0, f"{dt:.1f}s — quadratic created-list scan"
    # idempotent second pass
    r2 = ex.execute("UNWIND $rows AS r MERGE (:Mi {id: r.id})",
                    {"rows": rows})
    assert r2.stats.nodes_created == 0


def test_merge_dedups_within_statement(ex):
    r = ex.execute("UNWIND [1, 1, 2, 2, 2] AS i MERGE (:Md {id: i})")
    assert r.stats.nodes_created == 2
    assert ex.execute("MATCH (d:Md) RETURN count(d)").rows == [[2]]
    scan_ex = CypherExecutor(NamespacedEngine(MemoryEngine(), "mscan"))
    scan_ex.enable_fastpaths = False
    rs = scan_ex.execute("UNWIND [1, 1, 2, 2, 2] AS i MERGE (:Md {id: i})")
    assert rs.stats.nodes_created == 2


class TestPointLookupWriteRows:
    """The r5 point-lookup short-circuit in try_fast_match_rows: bare
    `(v:L {p: $x})` comma paths resolve via two hash-index gets instead
    of the full binding machinery. Parity vs the general interpreter on
    every edge the shortcut declines (bools, 0/1, multi-candidate,
    missing, WHERE)."""

    def _seed(self, ex, n=50):
        for i in range(n):
            ex.execute("CREATE (:P {id: $i, name: $n, flag: $f})",
                       {"i": i + 2, "n": f"p{i}", "f": i % 2 == 0})
        # duplicate name -> multi-candidate lookups
        ex.execute("CREATE (:P {id: 1000, name: 'p1'})")

    def test_create_rel_between_point_matches(self, ex):
        self._seed(ex)
        r = ex.execute(
            "MATCH (a:P {id: $a}), (b:P {id: $b}) "
            "CREATE (a)-[:R]->(b)", {"a": 5, "b": 9})
        assert r.stats.relationships_created == 1
        got = ex.execute(
            "MATCH (a:P {id: 5})-[:R]->(b:P) RETURN b.id").rows
        assert got == [[9]]

    def test_multi_candidate_cross_product(self, ex):
        self._seed(ex)
        # name 'p1' matches two nodes: cross product = 2 rows, 2 edges
        r = ex.execute(
            "MATCH (a:P {name: 'p1'}), (b:P {id: 7}) "
            "CREATE (a)-[:R2]->(b)")
        assert r.stats.relationships_created == 2

    def test_no_match_creates_nothing(self, ex):
        self._seed(ex)
        r = ex.execute(
            "MATCH (a:P {id: 999999}), (b:P {id: 7}) "
            "CREATE (a)-[:R3]->(b)")
        assert r.stats.relationships_created == 0

    def test_bool_and_int_identity_stay_exact(self, ex):
        self._seed(ex)
        # flag=true must not match flag=1-typed values and vice versa —
        # the shortcut declines these; semantics must still hold
        ex.execute("CREATE (:P {id: 2000, flag: 1})")
        rows = ex.execute(
            "MATCH (a:P {flag: $f}) RETURN count(a)", {"f": 1}).rows
        assert rows == [[1]]
        rows_t = ex.execute(
            "MATCH (a:P {flag: true}) RETURN count(a)").rows
        assert rows_t == [[25]]

    def test_set_through_point_match(self, ex):
        self._seed(ex)
        ex.execute("MATCH (a:P {id: 5}), (b:P {id: 9}) "
                   "SET a.touched = true, b.touched = true")
        assert ex.execute(
            "MATCH (p:P) WHERE p.touched = true RETURN count(p)"
        ).rows == [[2]]
