"""APOC value-level long tail (apoc_bulk.py) — representative coverage
per category (reference: apoc/apoc.go registerAllFunctions)."""

import pytest

from nornicdb_tpu.query.executor import CypherExecutor
from nornicdb_tpu.storage import MemoryEngine, NamespacedEngine


@pytest.fixture()
def ex():
    return CypherExecutor(NamespacedEngine(MemoryEngine(), "bulk"))


def q1(ex, s, p=None):
    return ex.execute(s, p or {}).rows[0][0]


CASES = [
    # bitwise — 64-bit two's complement semantics
    ("RETURN apoc.bitwise.and(12, 10)", 8),
    ("RETURN apoc.bitwise.or(12, 10)", 14),
    ("RETURN apoc.bitwise.xor(12, 10)", 6),
    ("RETURN apoc.bitwise.not(0)", -1),
    ("RETURN apoc.bitwise.leftShift(1, 63)", -9223372036854775808),
    ("RETURN apoc.bitwise.rightShift(-8, 1)", -4),
    ("RETURN apoc.bitwise.rotateLeft(1, 1)", 2),
    ("RETURN apoc.bitwise.rotateRight(1, 1)", -9223372036854775808),
    ("RETURN apoc.bitwise.setBit(0, 3)", 8),
    ("RETURN apoc.bitwise.clearBit(15, 0)", 14),
    ("RETURN apoc.bitwise.toggleBit(8, 3)", 0),
    ("RETURN apoc.bitwise.testBit(8, 3)", True),
    ("RETURN apoc.bitwise.countBits(255)", 8),
    ("RETURN apoc.bitwise.op(6, '&', 3)", 2),
    # number
    ("RETURN apoc.number.romanize(1987)", "MCMLXXXVII"),
    ("RETURN apoc.number.arabize('XIV')", 14),
    ("RETURN apoc.number.isPrime(97)", True),
    ("RETURN apoc.number.isPrime(1)", False),
    ("RETURN apoc.number.nextPrime(14)", 17),
    ("RETURN apoc.number.fibonacci(10)", 55),
    ("RETURN apoc.number.factorial(5)", 120),
    ("RETURN apoc.number.gcd(12, 18)", 6),
    ("RETURN apoc.number.lcm(4, 6)", 12),
    ("RETURN apoc.number.isEven(4)", True),
    ("RETURN apoc.number.toHex(255)", "ff"),
    ("RETURN apoc.number.fromHex('ff')", 255),
    ("RETURN apoc.number.toBase(255, 36)", "73"),
    ("RETURN apoc.number.fromBase('73', 36)", 255),
    ("RETURN apoc.number.clamp(15, 0, 10)", 10.0),
    ("RETURN apoc.number.lerp(0, 10, 0.5)", 5.0),
    ("RETURN apoc.number.parse('1,234')", 1234),
    # math / stats
    ("RETURN apoc.math.median([1,2,3,4])", 2.5),
    ("RETURN apoc.math.mode([1,2,2,3])", 2),
    ("RETURN apoc.math.product([2,3,4])", 24.0),
    ("RETURN apoc.stats.count([1,2,3])", 3),
    ("RETURN apoc.stats.range([1,9,4])", 8.0),
    ("RETURN apoc.stats.iqr([1,2,3,4,5])", 2.0),
    # scoring
    ("RETURN apoc.scoring.jaccard([1,2,3],[2,3,4])", 0.5),
    ("RETURN apoc.scoring.dice([1,2],[2,3])", 0.5),
    ("RETURN apoc.scoring.sigmoid(0)", 0.5),
    ("RETURN apoc.scoring.tf(2, 10)", 0.2),
    ("RETURN apoc.scoring.rank([30, 10, 20])", [1, 3, 2]),
    ("RETURN apoc.scoring.topK([5,1,9,3], 2)", [9.0, 5.0]),
    # coll extras
    ("RETURN apoc.coll.containsDuplicates([1,2,2])", True),
    ("RETURN apoc.coll.containsSorted([1,3,5,7], 5)", True),
    ("RETURN apoc.coll.disjunction([1,2,3],[2,3,4])", [1, 4]),
    ("RETURN apoc.coll.isEmpty([])", True),
    ("RETURN apoc.coll.insertAll([1,4], 1, [2,3])", [1, 2, 3, 4]),
    ("RETURN apoc.coll.pairsMin([1,2,3])", [[1, 2], [2, 3]]),
    ("RETURN apoc.coll.slice([1,2,3,4], 1, 2)", [2, 3]),
    # text
    ("RETURN apoc.text.base64Encode('hi')", "aGk="),
    ("RETURN apoc.text.base64Decode('aGk=')", "hi"),
    ("RETURN apoc.text.capitalizeAll('ab cd')", "Ab Cd"),
    ("RETURN apoc.text.indexesOf('banana', 'a')", [1, 3, 5]),
    ("RETURN apoc.text.urlencode('a b&c')", "a%20b%26c"),
    ("RETURN apoc.text.urldecode('a%20b%26c')", "a b&c"),
    ("RETURN apoc.text.phonetic('Robert')", "R163"),
    ("RETURN apoc.text.fromCodePoint(72, 105)", "Hi"),
    ("RETURN apoc.text.compareCleaned('Hello!', 'hello')", True),
    # util
    ("RETURN apoc.util.coalesce(null, null, 3)", 3),
    ("RETURN apoc.util.when(true, 'a', 'b')", "a"),
    ("RETURN apoc.util.case([false, 'x', true, 'y'], 'z')", "y"),
    ("RETURN apoc.util.md5Hex('abc')", "900150983cd24fb0d6963f7d28e17f72"),
    ("RETURN apoc.util.sha1Hex('abc')",
     "a9993e364706816aba3e25717850c26c9cd0d89d"),
    ("RETURN apoc.util.partition([1,2,3,4,5], 2)", [[1, 2], [3, 4], [5]]),
    ("RETURN apoc.util.repeat('ab', 3)", "ababab"),
    ("RETURN apoc.util.isNode(1)", False),
    ("RETURN apoc.util.typeof('x')", "STRING"),
    # json
    ("RETURN apoc.json.get({a: {b: [1,2,3]}}, '$.a.b[1]')", 2),
    ("RETURN apoc.json.flatten({a: {b: 1}})", {"a.b": 1}),
    ("RETURN apoc.json.unflatten({`a.b`: 1})", {"a": {"b": 1}}),
    ("RETURN apoc.json.size({a:1, b:2})", 2),
    ("RETURN apoc.json.validate('{\"a\": 1}')", True),
    ("RETURN apoc.json.validate('nope{')", False),
    ("RETURN apoc.json.type([1,2])", "LIST"),
    # temporal
    ("RETURN apoc.temporal.dayOfWeek(datetime('2026-07-30T00:00:00Z'))", 4),
    ("RETURN apoc.temporal.quarter(datetime('2026-07-30T00:00:00Z'))", 3),
    ("RETURN apoc.temporal.isLeapYear(2024)", True),
    ("RETURN apoc.temporal.isWeekend(datetime('2026-08-01T00:00:00Z'))",
     True),
    ("RETURN apoc.temporal.daysInMonth(datetime('2026-02-01T00:00:00Z'))",
     28),
    ("RETURN apoc.temporal.toEpochMillis(datetime('1970-01-01T00:00:01Z'))",
     1000),
    ("RETURN apoc.temporal.isBetween(datetime('2026-02-01T00:00:00Z'), "
     "datetime('2026-01-01T00:00:00Z'), datetime('2026-03-01T00:00:00Z'))",
     True),
    ("RETURN apoc.temporal.formatDuration(90061000)", "1d 1h 1m 1s"),
    # convert
    ("RETURN apoc.convert.toIntList(['1','2'])", [1, 2]),
    ("RETURN apoc.convert.toSet([1,2,2,3])", [1, 2, 3]),
    # hashing
    ("RETURN apoc.hashing.sha256('abc')",
     "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"),
    ("RETURN apoc.hashing.fnv1a('a')", 3826002220),
    ("RETURN apoc.hashing.murmurhash3('hello')", 613153351),
    # date
    ("RETURN apoc.date.fromUnixtime(0)", "1970-01-01 00:00:00"),
    ("RETURN apoc.date.toYears(0)", 0.0),
]


@pytest.mark.parametrize("query,expected", CASES)
def test_case(ex, query, expected):
    got = q1(ex, query)
    if isinstance(expected, float):
        assert got == pytest.approx(expected)
    else:
        assert got == expected


def test_temporal_month_arithmetic(ex):
    # Jan 31 + 1 month clamps to Feb 28 (non-leap)
    assert q1(
        ex, "RETURN toString(apoc.temporal.add("
            "datetime('2026-01-31T00:00:00Z'), 1, 'month'))"
    ).startswith("2026-02-28")
    assert q1(
        ex, "RETURN toString(apoc.temporal.subtract("
            "datetime('2026-03-31T00:00:00Z'), 1, 'month'))"
    ).startswith("2026-02-28")
    assert q1(
        ex, "RETURN toString(apoc.temporal.startOf("
            "datetime('2026-07-30T14:22:00Z'), 'month'))"
    ).startswith("2026-07-01T00:00")


def test_compress_roundtrip(ex):
    comp = q1(ex, "RETURN apoc.util.compress('hello world')")
    assert q1(ex, "RETURN apoc.util.decompress($c)", {"c": comp}) == \
        "hello world"
    gz = q1(ex, "RETURN apoc.util.compressWithAlgorithm('abc', 'gzip')")
    assert q1(ex, "RETURN apoc.util.decompressWithAlgorithm($c, 'gzip')",
              {"c": gz}) == "abc"


def test_util_validate(ex):
    from nornicdb_tpu.errors import CypherRuntimeError

    with pytest.raises(CypherRuntimeError, match="boom"):
        ex.execute("RETURN apoc.util.validate(true, 'boom')")
    assert q1(ex, "RETURN apoc.util.validate(false, 'boom')") is None
    with pytest.raises(CypherRuntimeError):
        ex.execute("RETURN apoc.util.validatePattern('abc', '[0-9]+')")


def test_xml_roundtrip(ex):
    m = q1(ex, "RETURN apoc.xml.parse('<a x=\"1\"><b>t</b></a>')")
    assert m["_type"] == "a" and m["x"] == "1"
    assert m["_children"][0]["_text"] == "t"
    assert q1(ex, "RETURN apoc.xml.getText('<a>hi <b>there</b></a>')") == \
        "hi there"
    assert q1(ex, "RETURN apoc.xml.minify('<a> <b>t</b> </a>')") == \
        "<a><b>t</b></a>"
    assert q1(ex, "RETURN apoc.xml.validate('<a/>')") is True
    assert q1(ex, "RETURN apoc.xml.validate('<a>')") is False
    out = q1(ex, "RETURN apoc.xml.setAttribute('<a/>', 'k', 'v')")
    assert 'k="v"' in out


def test_diff(ex):
    d = q1(ex, "RETURN apoc.diff.maps({a:1, b:2}, {b:3, c:4})")
    assert d["leftOnly"] == {"a": 1}
    assert d["rightOnly"] == {"c": 4}
    assert d["different"] == {"b": {"left": 2, "right": 3}}
    assert q1(ex, "RETURN apoc.diff.strings('kitten','sitting')")[
        "distance"] == 3
    deep = q1(ex, "RETURN apoc.diff.deep({a: {b: 1}}, {a: {b: 2}})")
    assert deep == [{"path": "a.b", "kind": "changed", "left": 1,
                     "right": 2}]


def test_agg_family(ex):
    ex.execute("UNWIND [3,1,2,2] AS x CREATE (:V {v: x})")
    assert q1(ex, "MATCH (n:V) RETURN apoc.agg.median(n.v)") == 2.0
    assert q1(ex, "MATCH (n:V) RETURN apoc.agg.mode(n.v)") == 2
    assert q1(ex, "MATCH (n:V) RETURN apoc.agg.product(n.v)") == 12
    st = q1(ex, "MATCH (n:V) RETURN apoc.agg.statistics(n.v)")
    assert st["count"] == 4 and st["min"] == 1.0 and st["max"] == 3.0
    mx = q1(ex, "MATCH (n:V) RETURN apoc.agg.maxItems(n.v, n.v)")
    assert mx["value"] == 3
    freq = q1(ex, "MATCH (n:V) RETURN apoc.agg.frequencies(n.v)")
    assert {"value": 2, "count": 2} in freq
    # grouped aggregation
    ex.execute("UNWIND [['a',1],['a',2],['b',5]] AS p "
               "CREATE (:G {g: p[0], v: p[1]})")
    r = ex.execute("MATCH (n:G) RETURN n.g AS g, apoc.agg.first(n.v) "
                   "ORDER BY g")
    assert [row[1] for row in r.rows] == [1, 5]


def test_agg_percentile_and_slice(ex):
    ex.execute("UNWIND range(1, 10) AS x CREATE (:P {v: x})")
    assert q1(ex, "MATCH (n:P) RETURN apoc.agg.percentile(n.v, 0.5)") == \
        pytest.approx(5.5)
    assert q1(ex, "MATCH (n:P) WITH n ORDER BY n.v "
                  "RETURN apoc.agg.slice(n.v, 2, 3)") == [3, 4, 5]
    assert q1(ex, "MATCH (n:P) WITH n ORDER BY n.v "
                  "RETURN apoc.agg.nth(n.v, 4)") == 5
