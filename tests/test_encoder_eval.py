"""Committed-checkpoint search quality gate.

The reference ships trained bge-m3 weights and gates quality with JSONL
eval suites (pkg/eval/harness.go:175-272, cmd/eval). Equivalent here:
the committed mini encoder (models/checkpoints/encoder_mini.npz, trained
by models/pretrain.py) must clear quality gates on the committed suite:

1. absolute thresholds with headroom over the measured band of the
   r4 training recipe (topic-grouped cross-document positives,
   asymmetric query/document windows, symmetric InfoNCE — best
   checkpoints measure MRR ~0.80-0.88, recall ~0.40-0.42; the r3 gate
   values of 0.5/0.5/0.75 were committed without a passing run and are
   replaced by these measured-with-margin floors);
2. trained must beat a RANDOM-INIT encoder of the same shape by a wide
   MRR margin — training carries signal, not just architecture (the r3
   failure mode: committed weights scored BELOW random);
3. trained must beat the purely LEXICAL HashEmbedder on recall —
   the semantic encoder must retrieve same-topic documents lexical
   overlap alone cannot."""

import json
import os

import numpy as np
import pytest

from nornicdb_tpu.eval import EvalHarness, Thresholds
from nornicdb_tpu.models.pretrain import (
    default_checkpoint_path,
    load_checkpoint,
    load_default_embedder,
)

DATA = os.path.join(os.path.dirname(__file__), "data")
DOCS = os.path.join(DATA, "encoder_eval_docs.jsonl")
SUITE = os.path.join(DATA, "encoder_eval.jsonl")


def _load_docs():
    docs = []
    with open(DOCS, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                docs.append(json.loads(line))
    return docs


def _harness_over(embedder, thresholds):
    docs = _load_docs()
    ids = [d["id"] for d in docs]
    mat = np.asarray(
        embedder.embed_batch([d["text"] for d in docs]), np.float32
    )
    mat /= np.maximum(np.linalg.norm(mat, axis=1, keepdims=True), 1e-12)

    def search_fn(query, limit):
        q = np.asarray(embedder.embed(query), np.float32)
        q /= max(float(np.linalg.norm(q)), 1e-12)
        order = np.argsort(-(mat @ q))[:limit]
        return [ids[i] for i in order]

    return EvalHarness(search_fn, thresholds)


@pytest.fixture(scope="module")
def trained():
    emb = load_default_embedder()
    if emb is None:
        pytest.fail("committed encoder checkpoint missing "
                    "(models/checkpoints/encoder_mini.npz)")
    return emb


def test_checkpoint_is_committed_and_small():
    path = default_checkpoint_path()
    assert path is not None
    assert os.path.getsize(path) < 8_000_000, "checkpoint too big for git"


def test_trained_encoder_clears_thresholds(trained):
    # floors sit ~15-30% under the measured band of the committed
    # checkpoint (see module docstring); a regression in pretraining
    # or the embedder path drops below them
    result = _harness_over(
        trained,
        Thresholds(precision=0.30, recall=0.30, mrr=0.70),
    ).run_file(SUITE)
    summary = result.to_dict()
    assert result.passed, summary


def test_trained_beats_lexical_hash_on_recall(trained):
    """Semantic value-add gate: the trained encoder must retrieve
    same-topic documents that pure lexical overlap cannot (the hash
    embedder measures ~0.34 recall on this suite)."""
    from nornicdb_tpu.embed.embedder import HashEmbedder

    loose = Thresholds(precision=0.0, recall=0.0, mrr=0.0)
    trained_res = _harness_over(trained, loose).run_file(SUITE)
    hash_res = _harness_over(HashEmbedder(), loose).run_file(SUITE)
    assert trained_res.recall > hash_res.recall, (
        trained_res.to_dict(), hash_res.to_dict(),
    )


def test_trained_beats_random_init(trained):
    """The committed weights must carry learned signal: same shape,
    random params, same tokenizer — quality should collapse."""
    from nornicdb_tpu.embed.embedder import JaxEncoderEmbedder
    from nornicdb_tpu.models.encoder import Encoder

    cfg, _ = load_checkpoint(default_checkpoint_path())
    random_emb = JaxEncoderEmbedder(model=Encoder(cfg), cfg=cfg, seed=123)
    loose = Thresholds(precision=0.0, recall=0.0, mrr=0.0)
    trained_res = _harness_over(trained, loose).run_file(SUITE)
    random_res = _harness_over(random_emb, loose).run_file(SUITE)
    assert trained_res.mrr > random_res.mrr + 0.1, (
        trained_res.to_dict(), random_res.to_dict(),
    )
    assert trained_res.recall > random_res.recall


def test_db_default_embedder_is_trained_encoder():
    """db.open() without an explicit embedder uses the committed
    checkpoint (reference default: local embeddings always on,
    embed.go; here the committed mini encoder plays bge-m3's role)."""
    import nornicdb_tpu
    from nornicdb_tpu.embed.embedder import CachedEmbedder, JaxEncoderEmbedder

    db = nornicdb_tpu.open(auto_embed=False)
    try:
        emb = db._embedder
        assert isinstance(emb, CachedEmbedder)
        assert isinstance(emb.inner, JaxEncoderEmbedder)
    finally:
        db.close()
