"""Multi-worker wire plane: parallel frontends over ONE device plane.

ISSUE 11 / ROADMAP item 3. BENCH_r07 showed the serving stack
collapsing at the wire, not the device: the qdrant gRPC surface knees
at 724 qps open-loop while the Go reference does ~29k ops/s on the
same contract, and PR 1's framework-floor calibration (vs_floor 1.31)
says one Python event loop is the ceiling. This module is the
architectural fix:

- ``NORNICDB_WIRE_WORKERS`` frontend workers — separate PROCESSES by
  default (``NORNICDB_WIRE_WORKER_MODE=thread`` keeps them in-process
  for tests/tiny benches) — each running its own grpc.aio server and a
  lean HTTP frontend bound to ONE shared port pair via SO_REUSEPORT,
  so the kernel load-balances connections and protobuf/JSON
  parse+serialize runs on N cores instead of one;
- every worker funnels into the single shared device plane through the
  lock-free :class:`~nornicdb_tpu.search.broker.DispatchBroker` ring:
  raw-embedding ops coalesce across workers into one batched device
  dispatch (the MicroBatcher's leader/rider protocol with the broker
  as standing leader — coalescing gets *better* with more frontends),
  and generic ops (full-fidelity ``search_points``, upsert convoys,
  scroll pages, any REST route) execute concurrently on the plane's
  pool where they coalesce in the existing MicroBatcher/BatchCoalescer
  machinery;
- responses assemble zero-copy in the worker: the qdrant Search reply
  is hand-encoded straight from the plane's point dicts
  (api/wire_codec.py — no protobuf object graph), validated response
  bytes ride each worker's own generation-checked WireCache against
  write generations MIRRORED into shared memory (cache.py
  ``set_generation_mirror``), so a cache hit never crosses the ring;
- per-rider tier attribution stays rider-accurate across the process
  boundary (the plane records serves; broker responses carry the tier
  and the leader-stamped stage intervals which the worker re-records
  under surface ``broker``), degrade-ledger records produced by a
  worker's query ride its response back into the worker's own ledger,
  and each worker's ``/metrics`` scrape merges the shared plane's
  series exactly once (obs/metrics.py ``render_merged``); ``/readyz``
  forwards the plane verdict and adds ``broker_unreachable``;
- a worker whose broker died times out (``NORNICDB_WIRE_TIMEOUT_S``)
  and errors — never hangs; a crashed worker's listening socket leaves
  the SO_REUSEPORT group, so surviving workers keep taking traffic.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nornicdb_tpu import admission as _adm
from nornicdb_tpu import obs
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu.search.broker import (
    BrokerClient,
    BrokerRemoteError,
    BrokerTimeout,
    DispatchBroker,
)


def resolve_vec_dispatch(db, key: str, queries, k: int):
    """The OP_VEC dispatch-key vocabulary resolved against one DB's
    device indexes — the ONE copy shared by the plane's local dispatch
    and each read replica's dispatch (replication/read_fleet.py), so a
    new key can never silently exist on one side only."""
    if key == "__service__":
        return db.search._ann_search_batch(queries, k)
    if key.startswith("qdrant:"):
        return db.qdrant_compat._ann_search_index(
            key[len("qdrant:"):]).search_batch(queries, k)
    raise KeyError(f"unknown vec-dispatch key {key!r}")


def wire_workers_from_env(default: int = 1) -> int:
    try:
        return int(os.environ.get("NORNICDB_WIRE_WORKERS", str(default)))
    except ValueError:
        return default


def wire_worker_mode() -> str:
    mode = os.environ.get("NORNICDB_WIRE_WORKER_MODE", "process").lower()
    return mode if mode in ("process", "thread") else "process"


# -- worker-side proxies ----------------------------------------------------


def _map_remote(exc: BrokerRemoteError):
    from nornicdb_tpu.api.qdrant import QdrantError

    if exc.type_name == "QdrantError":
        return QdrantError(str(exc), status=exc.status)
    if exc.type_name == "DeadlineExceeded":
        # the plane shed a budget-expired rider (ISSUE 15): surface it
        # as the same fail-fast the local batcher would have raised
        return _adm.DeadlineExceeded(str(exc))
    return exc


def _graft_vec_spans(doc: Dict[str, Any], k: int) -> None:
    """Graft an OP_VEC response's plane-side span records into the
    live trace (ring.claim -> plane.coalesce -> device.dispatch with
    original timing), falling back to the single leader-stamped
    interval when the rider posted without a trace context. Also
    stamps the fleet node the router chose (ISSUE 13)."""
    spans = doc.get("spans")
    if spans:
        for sd in spans:
            obs.attach_span_tree(sd)
    else:
        obs.attach_span("broker.dispatch", doc["t0"], doc["t1"],
                        surface="broker", batch=doc["batch"], k=k)
    if doc.get("node"):
        obs.annotate(fleet_node=doc["node"])


class BrokerCompat:
    """Worker-side stand-in for QdrantCompat: every method forwards as
    a generic broker op to the real compat on the device plane, where
    concurrent ops from all workers coalesce through the existing
    MicroBatcher (searches) and BatchCoalescer (upsert convoys).
    Degrade records produced by an op ride back into THIS process's
    ledger; stage intervals re-record under surface ``broker``."""

    def __init__(self, client: BrokerClient):
        self._client = client

    @property
    def cache_gen(self) -> int:
        # shared-memory mirror of the plane's search-cache generation:
        # worker wire caches validate without a ring round trip
        return self._client.qdrant_gen()

    def _call(self, method: str, *args, **kwargs):
        try:
            doc = self._client.call("compat", method, *args, **kwargs)
        except BrokerTimeout:
            from nornicdb_tpu.api.qdrant import QdrantError

            _audit.record_degrade("wire", "broker", "error",
                                  "broker_timeout", index=method)
            raise QdrantError(
                "device plane unavailable (broker timeout)", status=503)
        except BrokerRemoteError as exc:
            raise _map_remote(exc) from None
        meta = doc.get("meta") or {}
        if self._client.cross_process:
            for rec in meta.get("degrades", ()):
                _audit.replay_degrade(rec)
        # plane-side span tree (ISSUE 13): graft it so this worker's
        # /admin/traces shows the op's full plane story under the
        # ingress root — same trace id on both sides of the ring
        for sd in meta.get("spans", ()):
            obs.attach_span_tree(sd)
        obs.record_stage("broker", "coalesce_wait",
                         doc["t0"] - doc["t_post"])
        obs.record_stage("broker", "apply", doc["t1"] - doc["t0"])
        # ring post->dispatch interval = this worker's measured queue
        # wait (ISSUE 15): the shedding verdict's signal
        _adm.CONTROLLER.note_wait(_adm.lane(), doc["t0"] - doc["t_post"])
        return doc["result"]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        import functools

        return functools.partial(self._call, name)


class BrokerSearch:
    """Worker-side stand-in for the SearchService surface the gRPC
    servicers use. Raw vector search posts the embedding RAW onto the
    ring (OP_VEC) and rides a cross-worker batched device dispatch;
    hybrid/exact paths forward generically."""

    def __init__(self, client: BrokerClient):
        self._client = client

    @property
    def generation(self) -> int:
        return self._client.search_gen()

    def vector_search_candidates(self, query_vec, k: int = 10,
                                 exact: bool = False,
                                 lexical_doc_ids=None):
        if exact or lexical_doc_ids:
            doc = self._search_call("vector_search_candidates",
                                    np.asarray(query_vec, np.float32),
                                    k=k, exact=exact,
                                    lexical_doc_ids=lexical_doc_ids)
            return doc
        try:
            doc = self._client.vec_search(
                "__service__", np.asarray(query_vec, np.float32), k)
        except BrokerTimeout:
            _audit.record_degrade("vector", "broker", "error",
                                  "broker_timeout")
            raise RuntimeError(
                "device plane unavailable (broker timeout)")
        except BrokerRemoteError as exc:
            raise _map_remote(exc) from None
        now = time.time()
        obs.record_stage("broker", "coalesce_wait",
                         doc["t0"] - doc["t_post"])
        obs.record_stage("broker", "device_dispatch",
                         doc["t1"] - doc["t0"])
        obs.record_stage("broker", "merge", now - doc["t1"])
        _adm.CONTROLLER.note_wait(_adm.lane(), doc["t0"] - doc["t_post"])
        _graft_vec_spans(doc, k)
        _audit.set_last_served(doc.get("tier"))
        return doc["hits"]

    def _search_call(self, method: str, *args, **kwargs):
        try:
            doc = self._client.call("search", method, *args, **kwargs)
        except BrokerTimeout:
            _audit.record_degrade("vector", "broker", "error",
                                  "broker_timeout", index=method)
            raise RuntimeError(
                "device plane unavailable (broker timeout)")
        except BrokerRemoteError as exc:
            raise _map_remote(exc) from None
        meta = doc.get("meta") or {}
        if self._client.cross_process:
            for rec in meta.get("degrades", ()):
                _audit.replay_degrade(rec)
        for sd in meta.get("spans", ()):
            obs.attach_span_tree(sd)
        return doc["result"]

    def search(self, **kwargs):
        return self._search_call("search", **kwargs)


class _BrokerStorage:
    """Minimal storage facade for servicer fallbacks (point payload
    lookups); hot paths use the batched plane op instead."""

    def __init__(self, client: BrokerClient):
        self._client = client

    def get_node(self, node_id: str):
        try:
            return self._client.call("db", "storage.get_node",
                                     node_id)["result"]
        except BrokerRemoteError as exc:
            raise _map_remote(exc) from None


class _WorkerDB:
    """The db-shaped object a worker's GrpcServer is built over."""

    def __init__(self, client: BrokerClient):
        self._client = client
        self.qdrant_compat = BrokerCompat(client)
        self.search = BrokerSearch(client)
        self.storage = _BrokerStorage(client)
        self._data_dir = None

    def plane_call(self, method: str, *args, **kwargs):
        doc = self._client.call("plane", method, *args, **kwargs)
        return doc["result"]


# -- worker servicer overrides ----------------------------------------------


def _worker_servicers():
    """Built lazily so importing wire_plane never drags grpc in."""
    from nornicdb_tpu.api import wire_codec
    from nornicdb_tpu.api.grpc_server import SearchServicer
    from nornicdb_tpu.api.qdrant_official_grpc import (
        OfficialPointsServicer,
        _with_payload,
        _with_vectors,
        filter_to_dict,
    )
    from nornicdb_tpu.api.proto import nornic_pb2 as pb

    class WorkerSearchServicer(SearchServicer):
        """nornic.v1.SearchService in a frontend worker: raw vector
        rides the ring's coalesced OP_VEC; payloads come back in ONE
        batched plane op instead of a storage read per hit."""

        def Search(self, request):
            t0 = time.time()
            k = int(request.limit) or 10
            hits = self.db.search.vector_search_candidates(
                np.asarray(list(request.vector), dtype=np.float32), k=k)
            payloads = self.db.plane_call(
                "payload_json_many", [nid for nid, _ in hits])
            return pb.SearchResponse(
                hits=[pb.Hit(node_id=str(nid), score=float(score),
                             payload_json=payloads.get(nid, "{}"))
                      for nid, score in hits],
                took_ms=(time.time() - t0) * 1e3,
            )

    class WorkerPointsServicer(OfficialPointsServicer):
        """qdrant.Points in a frontend worker. Search assembles the
        reply ZERO-COPY: ranked point dicts from the plane splice
        straight into wire bytes (api/wire_codec.py) — no protobuf
        object graph in the worker, the only per-reply work after the
        encode is the 9-byte time splice.

        The HOT SHAPE — cosine collection, no filter, no vector echo —
        rides the ring's coalesced OP_VEC instead of a pickled
        full-fidelity OP_CALL (the PR 11 named headroom): the raw
        embedding posts straight onto the ring, coalesces across every
        worker into one batched device dispatch per collection, and one
        batched plane op hydrates payloads. Anything the fast path
        cannot prove sound — non-cosine distance, filters, a hydration
        under-fill from a racing delete — falls back to the
        full-fidelity ``search_points`` OP_CALL, never to a wrong or
        short answer."""

        def __init__(self, compat):
            super().__init__(compat)
            # collection eligibility briefs, validated against the
            # shared qdrant write generation (any write invalidates)
            self._fast_briefs: Dict[str, Tuple[int, Dict[str, Any]]] = {}

        def _fast_brief(self, name: str) -> Optional[Dict[str, Any]]:
            gen = self.compat._client.qdrant_gen()
            cached = self._fast_briefs.get(name)
            if cached is not None and cached[0] == gen:
                return cached[1]
            try:
                brief = self.compat._client.call(
                    "plane", "qdrant_fast_brief", name)["result"]
            except Exception:  # noqa: BLE001 — slow path decides
                return None
            if len(self._fast_briefs) > 256:
                self._fast_briefs.clear()
            self._fast_briefs[name] = (gen, brief)
            return brief

        def _fast_search(self, brief, request, limit: int, offset: int,
                         with_payload: bool, threshold, t0: float):
            """OP_VEC fast path; None = let the OP_CALL path serve."""
            vec = np.asarray(list(request.vector), dtype=np.float32)
            want = int(brief.get("size") or 0)
            if want and vec.shape[0] != want:
                from nornicdb_tpu.api.qdrant import QdrantError

                raise QdrantError(
                    f"search vector size {vec.shape[0]} != collection "
                    f"size {want}")
            try:
                doc = self.compat._client.vec_search(
                    "qdrant:" + brief["collection"], vec, limit + offset)
            except BrokerTimeout:
                from nornicdb_tpu.api.qdrant import QdrantError

                _audit.record_degrade("wire", "broker", "error",
                                      "broker_timeout",
                                      index=brief["collection"])
                raise QdrantError(
                    "device plane unavailable (broker timeout)",
                    status=503)
            except BrokerRemoteError as exc:
                raise _map_remote(exc) from None
            hits = doc.get("hits") or []
            obs.record_stage("broker", "coalesce_wait",
                             doc["t0"] - doc["t_post"])
            obs.record_stage("broker", "device_dispatch",
                             doc["t1"] - doc["t0"])
            _adm.CONTROLLER.note_wait(_adm.lane(),
                                      doc["t0"] - doc["t_post"])
            _graft_vec_spans(doc, limit + offset)
            _audit.set_last_served(doc.get("tier"))
            got = self.compat._client.call(
                "plane", "qdrant_points_brief", brief["collection"],
                [nid for nid, _ in hits],
                with_payload)["result"]
            by_id = got.get("points") or {}
            missing = sum(1 for nid, _ in hits if nid not in by_id)
            points = []
            for nid, score in hits:
                d = by_id.get(nid)
                if d is None:
                    continue  # deleted between dispatch and hydrate
                if threshold is not None and float(score) < threshold:
                    continue
                d = dict(d)
                d["score"] = float(score)
                points.append(d)
            if missing and len(points) < limit + offset \
                    and len(points) < int(got.get("total") or 0):
                # racing deletes displaced candidates the widening
                # rounds of the full path would have refilled
                return None
            return wire_codec.append_time(
                wire_codec.encode_search_response(points[offset:]),
                time.time() - t0)

        def Search(self, request):
            t0 = time.time()
            offset = (int(request.offset)
                      if request.HasField("offset") else 0)
            limit = int(request.limit) or 10
            query_filter = filter_to_dict(request.filter)
            with_payload = _with_payload(request.with_payload)
            with_vector = _with_vectors(request)
            threshold = (request.score_threshold
                         if request.HasField("score_threshold") else None)
            if query_filter is None and not with_vector:
                brief = self._fast_brief(request.collection_name)
                if brief and brief.get("ok"):
                    resp = self._fast_search(brief, request, limit,
                                             offset, with_payload,
                                             threshold, t0)
                    if resp is not None:
                        return resp
            hits = self.compat.search_points(
                request.collection_name,
                list(request.vector),
                limit=limit + offset,
                with_payload=with_payload,
                with_vector=with_vector,
                score_threshold=threshold,
                query_filter=query_filter,
            )
            return wire_codec.append_time(
                wire_codec.encode_search_response(hits[offset:]),
                time.time() - t0)

    return WorkerSearchServicer, WorkerPointsServicer


# -- worker HTTP frontend ---------------------------------------------------


class _WorkerHttpServer:
    """Lean HTTP frontend of one wire worker: the hot search route
    parses/serializes locally (device work via the broker), /metrics
    merges the shared plane's series exactly once, /readyz merges the
    plane verdict with broker reachability, and every other route
    forwards to the device plane's full router (rendered there)."""

    def __init__(self, worker_db: _WorkerDB, host: str, port: int,
                 worker_id: int):
        from nornicdb_tpu.cache import LRUCache

        self.db = worker_db
        self.host = host
        self.port = port
        self.worker_id = worker_id
        self._client = worker_db._client
        self._search_wire: LRUCache = LRUCache(max_size=512,
                                               ttl_seconds=300.0)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        if self._client.cross_process:
            # the device plane as a fleet-telemetry source (ISSUE 13):
            # this worker's /admin/fleet merges its own registry with
            # the plane's. Thread mode shares ONE registry — a source
            # there would double-count every shared counter.
            obs.register_fleet_source(
                "plane", lambda: self.db.plane_call("metrics_state"))

    # -- route bodies --------------------------------------------------

    def _nornicdb_search(self, body: bytes, headers) -> Tuple[int, bytes]:
        from nornicdb_tpu.api.http_server import _json_default

        gen = self._client.search_gen()
        key = (headers.get("Authorization", ""), body)
        hit = self._search_wire.get(key)
        if hit is not None and hit[0] == gen:
            _audit.record_served("hybrid", "cached")
            return 200, hit[1]
        # miss-only admission verdict (ISSUE 15): a byte-fresh hit is
        # never shed; only a miss pays the broker round trip
        _adm.check("http", _adm.lane())
        status, payload = self.db.plane_call(
            "search_payload", body,
            headers.get("Authorization", ""))
        t_ser = time.perf_counter()
        data = json.dumps(payload, default=_json_default).encode()
        obs.record_stage("http", "serialize",
                         time.perf_counter() - t_ser)
        if status == 200:
            self._search_wire.put(key, (gen, data))
        return status, data

    def _metrics(self, accept: str = "") -> Tuple[str, str]:
        """(content_type, body). Content-negotiated like the main
        server's /metrics: an OpenMetrics Accept gets the exemplar-
        carrying exposition — including the PLANE's bucket exemplars,
        which ride the merged dump_state (ISSUE 13 satellite: they
        were silently dropped from worker scrapes before)."""
        from nornicdb_tpu.api.http_server import _accepts_openmetrics
        from nornicdb_tpu.obs.metrics import REGISTRY, render_merged

        om = _accepts_openmetrics(accept)
        ctype = (REGISTRY.OPENMETRICS_CONTENT_TYPE if om
                 else "text/plain; version=0.0.4")
        if not self._client.cross_process:
            # thread-mode workers share the plane's process registry:
            # the shared series are already here exactly once
            return ctype, (REGISTRY.render_openmetrics() if om
                           else REGISTRY.render())
        try:
            remote = self.db.plane_call("metrics_state")
        except Exception:  # noqa: BLE001 — scrape must not fail
            remote = []
        return ctype, render_merged([remote] if remote else [],
                                    openmetrics=om)

    def _admin_check(self, headers) -> None:
        """Admin routes served WORKER-locally still authorize on the
        plane (the authenticator lives there); raises the plane's
        HTTPError-equivalent through the broker on denial."""
        self.db.plane_call("admin_check",
                           headers.get("Authorization", ""))

    def _admin_traces(self, path: str) -> Dict[str, Any]:
        """This worker's own trace ring — the ingress roots with the
        plane-side spans grafted (a forwarded /admin/traces would show
        the PLANE's ring, not this worker's wire->ring chains)."""
        if path.endswith("/slowest"):
            return {"slow_ms": obs.TRACES.slow_ms,
                    "recorded": obs.TRACES.recorded,
                    "worker": self.worker_id,
                    "traces": obs.TRACES.slowest(limit=10)}
        return {"slow_ms": obs.TRACES.slow_ms,
                "recorded": obs.TRACES.recorded,
                "worker": self.worker_id,
                "traces": obs.TRACES.snapshot(limit=50)}

    def _admin_events(self, path: str) -> Dict[str, Any]:
        """Unified incident timeline, merged across the process seam:
        this worker's journal (broker-replayed degrades) plus the
        plane's (drains, failovers, quarantines), ordered causally —
        by timestamp, seq tie-break — with per-record origin."""
        limit = 100
        tail = path.rsplit("/", 1)[-1]
        if tail.isdigit():
            limit = int(tail)
        local = [{**rec, "origin": f"worker-{self.worker_id}"}
                 for rec in obs.event_snapshot(limit=limit)]
        doc = dict(obs.event_summary())
        if self._client.cross_process:
            try:
                remote = self.db.plane_call("events_state", limit)
                local += [{**rec, "origin": "plane"}
                          for rec in remote.get("events", ())]
                doc["plane"] = {k: remote.get(k)
                                for k in ("recorded", "by_kind")}
            except Exception:  # noqa: BLE001 — local timeline still serves
                doc["plane"] = "unreachable"
        local.sort(key=lambda r: (r.get("ts", 0.0), r.get("seq", 0)))
        doc["worker"] = self.worker_id
        doc["events"] = local[-limit:]
        return doc

    def _admin_tenants(self, path: str) -> Dict[str, Any]:
        """Per-tenant rollup over the MERGED registry view (ISSUE 18):
        this worker's own series plus the shared device plane's,
        exactly once — the same merge discipline as /metrics. Thread
        mode shares one registry, so the local dump already holds the
        whole truth."""
        from nornicdb_tpu.obs.metrics import dump_state, merge_states

        top = None
        tail = path.rsplit("/", 1)[-1]
        if tail.isdigit():
            top = int(tail)
        remotes: List[Any] = []
        if self._client.cross_process:
            try:
                remotes = [self.db.plane_call("metrics_state")]
            except Exception:  # noqa: BLE001 — local view still serves
                remotes = []
        merged = merge_states(dump_state(), remotes)
        doc = _tenant.tenants_summary(state=merged, top=top)
        doc["worker"] = self.worker_id
        return doc

    def _readyz(self) -> Tuple[int, Dict[str, Any]]:
        try:
            status, payload = self.db.plane_call("readyz")
        except Exception:  # noqa: BLE001
            return 503, {"status": "degraded",
                         "reasons": ["broker_unreachable"],
                         "worker": self.worker_id}
        payload = dict(payload)
        payload["worker"] = self.worker_id
        return status, payload

    def _forward(self, method: str, path: str, body: bytes,
                 headers) -> Tuple[int, str, bytes]:
        return tuple(self.db.plane_call(
            "route_rendered", method, path, body,
            {"Authorization": headers.get("Authorization", ""),
             "Accept": headers.get("Accept", "")}))

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "_WorkerHttpServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True
            wbufsize = 64 * 1024

            def log_message(self, *args):
                pass

            def _reply_bytes(self, status: int, ctype: str,
                             data: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _handle(self, method: str) -> None:
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                path = self.path.split("?")[0]
                # ingress deadline + admission verdict (ISSUE 15): the
                # worker mints the budget like the main server; it
                # rides the broker ring to the plane in the slot
                # header. Shedding is worker-local (each frontend sees
                # its own in-flight pressure).
                dl, explicit = _adm.parse_deadline_header(
                    self.headers.get(_adm.DEADLINE_HEADER), "http")
                from nornicdb_tpu.api.http_server import _shed_lane_for

                lane = _shed_lane_for(method, path)
                # the wire-cached search route checks admission AFTER
                # its cache probe (a byte-fresh hit is never shed) —
                # inside _nornicdb_search; every other work route
                # checks here, before the broker round trip
                cached_route = (method == "POST"
                                and path == "/nornicdb/search")
                # tenant identity resolved at THIS ingress (ISSUE 18):
                # header first, multidb path namespace as fallback —
                # shed verdicts and cached serves attribute here, and
                # the identity rides the broker ring in the slot
                # header's packed trace context for plane-side work
                segs = [s for s in path.split("/") if s]
                namespace = (segs[1]
                             if len(segs) > 1 and segs[0] == "db"
                             else None)
                ten, ten_explicit = _tenant.resolve(
                    self.headers.get(_tenant.TENANT_HEADER), None,
                    namespace)
                with _tenant.tenant_scope(ten, explicit=ten_explicit), \
                        _adm.request_scope("http", dl, lane_name=lane,
                                           explicit=explicit):
                    if lane is not None and not cached_route:
                        try:
                            _adm.check("http", lane)
                        except _adm.ShedError as e:
                            self._reply_shed(e)
                            return
                    self._handle_admitted(method, path, body)

            def _reply_shed(self, e) -> None:
                data = json.dumps({"errors": [{
                    "code": "Neo.TransientError.Request."
                            "ResourceExhausted",
                    "message": str(e)}]}).encode()
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.send_header("Retry-After", str(
                    max(1, int(round(e.retry_after_s)))))
                self.end_headers()
                self.wfile.write(data)

            def _handle_admitted(self, method: str, path: str,
                                 body: bytes) -> None:
                try:
                    if method == "POST" and path == "/nornicdb/search":
                        status, data = outer._nornicdb_search(
                            body, self.headers)
                        self._reply_bytes(status, "application/json",
                                          data)
                        return
                    if method == "GET" and path == "/metrics":
                        ctype, body = outer._metrics(
                            self.headers.get("Accept", ""))
                        self._reply_bytes(200, ctype, body.encode())
                        return
                    if method == "GET" and (
                            path == "/admin/traces"
                            or path == "/admin/traces/slowest"):
                        # worker-LOCAL: the ingress traces live here
                        outer._admin_check(self.headers)
                        self._reply_bytes(
                            200, "application/json",
                            json.dumps(outer._admin_traces(path),
                                       default=str).encode())
                        return
                    if method == "GET" and (
                            path == "/admin/events"
                            or path.startswith("/admin/events/")):
                        outer._admin_check(self.headers)
                        self._reply_bytes(
                            200, "application/json",
                            json.dumps(outer._admin_events(path),
                                       default=str).encode())
                        return
                    if method == "GET" and path == "/admin/fleet":
                        # merged local+plane view via the aggregator
                        # (the plane source registered at worker boot)
                        outer._admin_check(self.headers)
                        self._reply_bytes(
                            200, "application/json",
                            json.dumps(obs.fleet_summary(),
                                       default=str).encode())
                        return
                    if method == "GET" and (
                            path == "/admin/tenants"
                            or path.startswith("/admin/tenants/")):
                        # merged local+plane per-tenant rollup
                        outer._admin_check(self.headers)
                        self._reply_bytes(
                            200, "application/json",
                            json.dumps(outer._admin_tenants(path),
                                       default=str).encode())
                        return
                    if method == "GET" and path == "/readyz":
                        status, payload = outer._readyz()
                        self._reply_bytes(status, "application/json",
                                          json.dumps(payload).encode())
                        return
                    if method == "GET" and path == "/health":
                        self._reply_bytes(200, "application/json",
                                          b'{"status": "ok"}')
                        return
                    status, ctype, data = outer._forward(
                        method, self.path, body, self.headers)
                    self._reply_bytes(status, ctype, data)
                except _adm.ShedError as e:
                    # miss-path shed from the cached search route:
                    # honest 429 with the Retry-After header
                    self._reply_shed(e)
                    return
                except Exception as e:  # noqa: BLE001 — boundary
                    # a plane-side auth denial keeps its 401/403
                    # through the ring (BrokerRemoteError carries the
                    # remote HTTPError status), a shed keeps its 429
                    # and a deadline fail-fast its 504 (ISSUE 15);
                    # everything else stays the transient 503 it
                    # always was
                    status = getattr(e, "status", None)
                    if status not in (401, 403, 429, 504):
                        status = 503
                    self._reply_bytes(
                        status, "application/json",
                        json.dumps({"errors": [{
                            "code": "Neo.TransientError.General."
                                    "WirePlane",
                            "message": str(e)[:300]}]}).encode())

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

        from nornicdb_tpu.api.http_server import (
            ReuseportThreadingHTTPServer,
        )

        self._server = ReuseportThreadingHTTPServer(
            (self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"wire-http-{self.worker_id}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._client.cross_process:
            obs.unregister_fleet_source("plane")
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


# -- one worker (grpc + http frontends over one BrokerClient) ---------------


class WireWorker:
    """One frontend worker: its own grpc.aio server + lean HTTP server,
    both SO_REUSEPORT-bound to the plane's shared ports, all device
    work funneled through its BrokerClient."""

    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.worker_id = int(spec["worker_id"])
        self.client = BrokerClient(spec["broker"])
        # fleet admission posture (ISSUE 16): this worker's controller
        # publishes into / reads back the ring posture word — one
        # overloaded worker tightens every frontend's verdict
        self.client.bind_admission()
        self.worker_db = _WorkerDB(self.client)
        self.grpc = None
        self.http = None

    def start(self) -> "WireWorker":
        from nornicdb_tpu.api.grpc_server import GrpcServer

        search_cls, points_cls = _worker_servicers()
        want_port = int(self.spec["grpc_port"])
        self.grpc = GrpcServer(
            self.worker_db, host=self.spec["host"], port=want_port,
            search_servicer_cls=search_cls,
            points_servicer_cls=points_cls)
        if want_port and self.grpc.port != want_port:
            raise RuntimeError(
                f"worker {self.worker_id} failed SO_REUSEPORT bind on "
                f"{want_port} (got {self.grpc.port})")
        self.grpc.start()
        self.http = _WorkerHttpServer(
            self.worker_db, self.spec["host"],
            int(self.spec["http_port"]), self.worker_id).start()
        # readiness flag the plane polls: servers are bound and serving
        with open(self._ready_path(), "w") as f:
            f.write(str(os.getpid()))
        return self

    def _ready_path(self) -> str:
        return os.path.join(self.spec["broker"]["sock_dir"],
                            f"ready-{self.worker_id}")

    def _stop_path(self) -> str:
        return os.path.join(self.spec["broker"]["sock_dir"], "stop")

    def serve_forever(self) -> None:
        """Process-mode main loop: exit when the plane signals stop,
        the parent process died, or the broker went away for good."""
        ppid = os.getppid()
        stale_since = None
        while True:
            time.sleep(0.25)
            if os.path.exists(self._stop_path()):
                break
            if os.getppid() != ppid:
                break
            try:
                alive = self.client.broker_alive()
            except Exception:  # noqa: BLE001 — shm unlinked
                break
            if not alive:
                stale_since = stale_since or time.time()
                if time.time() - stale_since > 10.0:
                    break
            else:
                stale_since = None
        self.stop()

    def stop(self) -> None:
        try:
            if self.grpc is not None:
                self.grpc.stop()
        finally:
            if self.http is not None:
                self.http.stop()
            self.client.close()


def _worker_main(spec: Dict[str, Any]) -> None:
    """Process-mode entry (``python -m nornicdb_tpu.api.wire_plane
    --worker <json>``): build the worker, serve until the plane
    stops."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    worker = WireWorker(spec)
    try:
        worker.start()
    except Exception:  # noqa: BLE001 — plane's ready-poll times out
        import traceback

        traceback.print_exc()
        try:
            worker.stop()
        finally:
            os._exit(1)
    worker.serve_forever()
    os._exit(0)


# -- plane-side ops exposed to workers --------------------------------------


class _PlaneOps:
    """The generic-op surface workers call on the device plane (target
    ``plane``): batched payload fetches, rendered route forwarding,
    readiness, and the metrics snapshot the worker scrape merges."""

    def __init__(self, plane: "WirePlane"):
        self._plane = plane

    def payload_json_many(self, ids: List[str]) -> Dict[str, str]:
        out: Dict[str, str] = {}
        storage = self._plane.db.storage
        for nid in ids:
            try:
                node = storage.get_node(nid)
                out[nid] = json.dumps(node.properties, default=str)
            except Exception:  # noqa: BLE001
                out[nid] = "{}"
        return out

    def search_payload(self, body: bytes, auth: str = ""):
        from nornicdb_tpu.api.http_server import HTTPError

        try:
            return self._plane.parent_http.route(
                "POST", "/nornicdb/search", body,
                {"Authorization": auth} if auth else {})
        except HTTPError as e:
            # client errors keep their status through the ring instead
            # of surfacing as a broker-side 503
            return (e.status, {"errors": [{"code": e.code,
                                           "message": e.message}]})

    def route_rendered(self, method: str, path: str, body: bytes,
                       headers: Dict[str, str]):
        from nornicdb_tpu.api.http_server import (
            HTTPError,
            _json_default,
            _NegotiatedText,
        )

        try:
            status, payload = self._plane.parent_http.route(
                method, path, body, headers or {})
        except HTTPError as e:
            return (e.status, "application/json", json.dumps(
                {"errors": [{"code": e.code,
                             "message": e.message}]}).encode())
        if isinstance(payload, _NegotiatedText):
            return (status, payload.content_type, payload.encode())
        if isinstance(payload, str):
            ctype = ("text/html; charset=utf-8"
                     if payload.lstrip().startswith("<")
                     else "text/plain; version=0.0.4")
            return (status, ctype, payload.encode())
        return (status, "application/json",
                json.dumps(payload, default=_json_default).encode())

    def readyz(self):
        return self._plane.parent_http._readyz()

    def metrics_state(self):
        from nornicdb_tpu.obs.metrics import dump_state

        return dump_state()

    def events_state(self, limit: int = 100):
        """The plane's incident-timeline slice for a worker's merged
        ``/admin/events`` view (ISSUE 13)."""
        doc = dict(obs.event_summary())
        doc["events"] = obs.event_snapshot(limit=int(limit))
        return doc

    def admin_check(self, auth: str = "") -> bool:
        """Authorize a worker-local admin route on the plane (the
        authenticator lives here); raises the HTTPError — carrying its
        401/403 status — back through the ring on denial."""
        http = self._plane.parent_http
        username = http.authenticate(
            {"Authorization": auth} if auth else {})
        from nornicdb_tpu.auth import ADMIN

        http.authorize(username, "system", ADMIN)
        return True

    # -- qdrant OP_VEC fast path (ISSUE 12 satellite) ------------------

    def qdrant_fast_brief(self, name: str) -> Dict[str, Any]:
        """Eligibility brief for the worker's OP_VEC qdrant Search fast
        path: alias-resolved collection name, distance and vector size.
        Only Cosine collections are eligible (the coalesced device
        index serves cosine; Dot/Euclid ride the raw-matrix path)."""
        compat = self._plane.db.qdrant_compat
        try:
            resolved = compat.resolve(name)
            meta = compat._meta(resolved)
        except Exception:  # noqa: BLE001 — missing collections 404 on
            # the slow path with the full error mapping
            return {"ok": False}
        cfg = meta.properties.get("config", {}) or {}
        return {
            "ok": cfg.get("distance", "Cosine") == "Cosine",
            "collection": resolved,
            "size": int(cfg.get("size", 0) or 0),
            "distance": cfg.get("distance", "Cosine"),
        }

    def qdrant_points_brief(self, name: str, ids: List[str],
                            with_payload: bool = True) -> Dict[str, Any]:
        """Batched hydration for OP_VEC-ranked collection hits: point
        dicts (scoreless — the worker splices its own scores) keyed by
        node id, plus the live point count so the worker can detect a
        racing-delete under-fill and fall back."""
        compat = self._plane.db.qdrant_compat
        storage = self._plane.db.storage
        points: Dict[str, Any] = {}
        for nid in ids:
            try:
                node = storage.get_node(nid)
            except Exception:  # noqa: BLE001 — deleted mid-flight
                continue
            points[nid] = compat._point_dict(node, with_payload, False)
        try:
            total = len(compat._index(compat.resolve(name)))
        except Exception:  # noqa: BLE001
            total = len(points)
        return {"points": points, "total": total}


# -- the plane --------------------------------------------------------------


def _reserve_port(host: str, port: int) -> Tuple[socket.socket, int]:
    """Bind (not listen) a placeholder SO_REUSEPORT socket so the port
    number is fixed before any worker boots; workers join the reuseport
    group, the placeholder never accepts."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.bind((host, port))
    return s, s.getsockname()[1]


class WirePlane:
    """N frontend workers + one broker over one device plane (one DB).

    ``workers <= 1`` is not served here — callers keep today's
    single-process GrpcServer/HttpServer path; the plane exists to add
    frontends, so it requires ``workers >= 2``."""

    def __init__(self, db, workers: Optional[int] = None,
                 host: str = "127.0.0.1", grpc_port: int = 0,
                 http_port: int = 0, mode: Optional[str] = None,
                 slot_bytes: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 authenticator=None, fleet=None):
        from nornicdb_tpu.api.http_server import HttpServer

        self.db = db
        # replica-aware read routing (ISSUE 12): with a FleetRouter the
        # plane's coalesced vector dispatches and the workers' generic
        # search/qdrant READ ops fan across admitted+ready replicas
        # (writes keep funneling to this db, the primary); None keeps
        # the single-node plane exactly as before
        self.fleet = fleet
        self.workers = workers if workers is not None \
            else wire_workers_from_env(2)
        if self.workers < 2:
            raise ValueError(
                "WirePlane needs >= 2 workers; use GrpcServer/"
                "HttpServer directly for single-process serving")
        self.mode = (mode or wire_worker_mode())
        self.host = host
        # full router instance for forwarded REST routes + /readyz —
        # never started: route() is a plain method over the db
        self.parent_http = HttpServer(db, port=0,
                                      authenticator=authenticator)
        self._plane_ops = _PlaneOps(self)
        compat = db.qdrant_compat
        target_compat = fleet.routed_compat() if fleet is not None \
            else compat
        target_search = fleet.routed_search() if fleet is not None \
            else db.search
        self.broker = DispatchBroker(
            self._vec_dispatch,
            targets={"compat": target_compat, "search": target_search,
                     "db": db, "plane": self._plane_ops},
            n_workers=self.workers, slot_bytes=slot_bytes)
        self._timeout_s = timeout_s
        # the device plane's controller shares the same posture word
        # as the wire workers (ISSUE 16)
        self.broker.bind_admission()
        obs.register_resource("queue", "broker", self.broker)
        # write-generation mirrors: worker wire caches validate against
        # shared memory instead of a broker round trip
        compat._search_cache.set_generation_mirror(
            self.broker.set_qdrant_gen)
        db.search._result_cache.set_generation_mirror(
            self.broker.set_search_gen)
        self._grpc_sock, self.grpc_port = _reserve_port(host, grpc_port)
        self._http_sock, self.http_port = _reserve_port(host, http_port)
        self._procs: List[Any] = []
        self._thread_workers: List[WireWorker] = []
        self._started = False

    # -- device-plane dispatch targets ---------------------------------

    def _local_vec_dispatch(self, key: str, queries: np.ndarray, k: int):
        return resolve_vec_dispatch(self.db, key, queries, k)

    def _vec_dispatch(self, key: str, queries: np.ndarray, k: int):
        if self.fleet is not None:
            return self.fleet.vec_dispatch(key, queries, k,
                                           self._local_vec_dispatch)
        return self._local_vec_dispatch(key, queries, k)

    # -- lifecycle -----------------------------------------------------

    def _spec(self, wid: int) -> Dict[str, Any]:
        spec = {
            "worker_id": wid,
            "host": self.host,
            "grpc_port": self.grpc_port,
            "http_port": self.http_port,
            "broker": self.broker.client_spec(
                wid, cross_process=(self.mode == "process")),
        }
        if self._timeout_s is not None:
            spec["broker"]["timeout_s"] = self._timeout_s
        return spec

    def start(self, ready_timeout_s: Optional[float] = None
              ) -> "WirePlane":
        self.broker.start()
        if self.mode == "thread":
            for wid in range(self.workers):
                self._thread_workers.append(
                    WireWorker(self._spec(wid)).start())
        else:
            # subprocess + module entry, not multiprocessing spawn:
            # spawn re-imports the parent's __main__ (breaks under
            # embedded/driver mains), while `-m ...wire_plane --worker`
            # gives each frontend a clean interpreter whose only job
            # is this JSON spec
            import subprocess
            import sys

            import nornicdb_tpu as _pkg

            # the worker interpreter must resolve this package no
            # matter the caller's cwd: prepend the package parent
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(_pkg.__file__)))
            env = dict(os.environ)
            env["PYTHONPATH"] = pkg_root + (
                os.pathsep + env["PYTHONPATH"]
                if env.get("PYTHONPATH") else "")
            for wid in range(self.workers):
                # stderr to a file, not a pipe: nobody drains a pipe
                # during serving, and a full pipe buffer would block
                # the worker mid-write
                err_path = os.path.join(self.broker.sock_dir,
                                        f"worker{wid}.err")
                with open(err_path, "wb") as err_f:
                    p = subprocess.Popen(
                        [sys.executable, "-m",
                         "nornicdb_tpu.api.wire_plane", "--worker",
                         json.dumps(self._spec(wid))],
                        stdout=subprocess.DEVNULL,
                        stderr=err_f, env=env)
                p._nornic_err_path = err_path
                self._procs.append(p)
            timeout = ready_timeout_s or 90.0
            deadline = time.time() + timeout
            missing = set(range(self.workers))
            while missing and time.time() < deadline:
                for wid in list(missing):
                    if os.path.exists(os.path.join(
                            self.broker.sock_dir, f"ready-{wid}")):
                        missing.discard(wid)
                dead = [p for p in self._procs if p.poll() is not None]
                if dead:
                    err = ""
                    try:
                        with open(dead[0]._nornic_err_path, "rb") as f:
                            err = f.read().decode(
                                errors="replace")[-800:]
                    except OSError:
                        pass
                    self.stop()
                    raise RuntimeError(
                        f"wire worker died during startup: {err}")
                if missing:
                    time.sleep(0.05)
            if missing:
                self.stop()
                raise RuntimeError(
                    f"wire workers {sorted(missing)} not ready within "
                    f"{timeout:.0f}s")
        self._started = True
        return self

    def stop(self) -> None:
        try:
            with open(os.path.join(self.broker.sock_dir, "stop"),
                      "w") as f:
                f.write("1")
        except OSError:
            pass
        for w in self._thread_workers:
            try:
                w.stop()
            except Exception:  # noqa: BLE001
                pass
        self._thread_workers = []
        for p in self._procs:
            try:
                p.wait(timeout=3)
            except Exception:  # noqa: BLE001
                p.terminate()
                try:
                    p.wait(timeout=3)
                except Exception:  # noqa: BLE001
                    p.kill()
        self._procs = []
        try:
            self.db.qdrant_compat._search_cache.set_generation_mirror(
                None)
            self.db.search._result_cache.set_generation_mirror(None)
        except Exception:  # noqa: BLE001
            pass
        obs.resources.unregister("queue", "broker")
        sock_dir = self.broker.sock_dir
        self.broker.stop()
        import shutil

        shutil.rmtree(sock_dir, ignore_errors=True)
        for s in (self._grpc_sock, self._http_sock):
            try:
                s.close()
            except OSError:
                pass

    @property
    def grpc_address(self) -> str:
        return f"{self.host}:{self.grpc_port}"


if __name__ == "__main__":  # worker process entry
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", required=True,
                    help="JSON worker spec from WirePlane._spec")
    _args = ap.parse_args()
    _worker_main(json.loads(_args.worker))
