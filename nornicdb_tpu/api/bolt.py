"""Bolt protocol server (4.0–4.4) — works with official Neo4j drivers.

Reference: pkg/bolt/server.go — handshake magic 0x6060B017 + version
negotiation (server.go:141-145), message types (server.go:150-158),
dispatch (handleMessage, server.go:1016-1100), chunked transport,
HELLO auth, RUN/PULL/DISCARD streaming with has_more, explicit
BEGIN/COMMIT/ROLLBACK transactions, bookmarks.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from nornicdb_tpu import obs
from nornicdb_tpu.api.packstream import Packer, Structure, Unpacker, to_packable
from nornicdb_tpu.storage.txn import TransactionOverlay

_BOLT_H = obs.REGISTRY.histogram(
    "nornicdb_bolt_request_seconds",
    "Bolt message handling latency by message type", labels=("msg",))

BOLT_MAGIC = 0x6060B017
SUPPORTED_VERSIONS = [(4, 4), (4, 3), (4, 2), (4, 1), (4, 0)]

# request signatures (reference: server.go:150-158)
MSG_HELLO = 0x01
MSG_GOODBYE = 0x02
MSG_RESET = 0x0F
MSG_RUN = 0x10
MSG_BEGIN = 0x11
MSG_COMMIT = 0x12
MSG_ROLLBACK = 0x13
MSG_DISCARD = 0x2F
MSG_PULL = 0x3F
# response signatures
MSG_SUCCESS = 0x70
MSG_RECORD = 0x71
MSG_IGNORED = 0x7E
MSG_FAILURE = 0x7F

SERVER_AGENT = "NornicTPU/1.0"


class _Stream:
    """One materialized result awaiting PULL/DISCARD."""

    def __init__(self, columns: List[str], rows: List[List[Any]],
                 stats: Optional[Dict[str, Any]] = None):
        self.columns = columns
        self.rows = rows
        self.pos = 0
        self.stats = stats or {}


class BoltSession:
    """Per-connection protocol state machine.

    States: CONNECTED -> READY -> STREAMING (autocommit) or
    TX_READY/TX_STREAMING (explicit tx) -> DEFUNCT on failure until RESET.
    """

    def __init__(self, server: "BoltServer"):
        self.server = server
        self.authed = False
        self.username: Optional[str] = None
        self.failed = False
        self.database = server.default_database
        self.tx: Optional[TransactionOverlay] = None
        self.tx_executor = None
        self.stream: Optional[_Stream] = None
        self.last_bookmark = ""

    # -- message handlers ------------------------------------------------

    _MSG_NAMES = {
        MSG_HELLO: "hello", MSG_GOODBYE: "goodbye", MSG_RESET: "reset",
        MSG_RUN: "run", MSG_BEGIN: "begin", MSG_COMMIT: "commit",
        MSG_ROLLBACK: "rollback", MSG_DISCARD: "discard",
        MSG_PULL: "pull",
    }

    def handle(self, sig: int, fields: List[Any]) -> List[Tuple[int, List[Any]]]:
        """Returns a list of (signature, fields) response messages."""
        t0 = time.perf_counter()
        try:
            if sig == MSG_RUN:
                # RUN carries the query execution — the latency that
                # matters; a root span makes bolt queries show up in
                # the slow-request ring like every other surface
                with obs.trace("wire", method="RUN", transport="bolt"):
                    return self._handle_inner(sig, fields)
            return self._handle_inner(sig, fields)
        finally:
            _BOLT_H.labels(
                self._MSG_NAMES.get(sig, "other")).observe(
                time.perf_counter() - t0)

    def _handle_inner(
        self, sig: int, fields: List[Any]
    ) -> List[Tuple[int, List[Any]]]:
        if self.failed and sig not in (MSG_RESET, MSG_GOODBYE):
            return [(MSG_IGNORED, [{}])]
        try:
            if sig == MSG_HELLO:
                return self._hello(fields[0] if fields else {})
            if sig == MSG_GOODBYE:
                raise _Goodbye()
            if sig == MSG_RESET:
                return self._reset()
            if not self.authed:
                return self._failure("Neo.ClientError.Security.Unauthorized",
                                     "HELLO required before other messages")
            if sig == MSG_RUN:
                return self._run(*(fields + [{}] * (3 - len(fields)))[:3])
            if sig == MSG_PULL:
                return self._pull(fields[0] if fields else {})
            if sig == MSG_DISCARD:
                return self._discard(fields[0] if fields else {})
            if sig == MSG_BEGIN:
                return self._begin(fields[0] if fields else {})
            if sig == MSG_COMMIT:
                return self._commit()
            if sig == MSG_ROLLBACK:
                return self._rollback()
            return self._failure("Neo.ClientError.Request.Invalid",
                                 f"unknown message 0x{sig:02X}")
        except _Goodbye:
            raise
        except Exception as e:  # noqa: BLE001 — protocol boundary
            return self._failure(_error_code(e), str(e))

    def _hello(self, extra: Dict[str, Any]) -> List[Tuple[int, List[Any]]]:
        auth = self.server.authenticator
        if auth is not None:
            scheme = extra.get("scheme", "none")
            principal = extra.get("principal", "")
            credentials = extra.get("credentials", "")
            try:
                if scheme == "basic":
                    auth.login(principal, credentials)
                    self.username = principal
                elif scheme == "bearer":
                    claims = auth.verify_token(credentials)
                    self.username = claims.get("sub")
                else:
                    raise ValueError(f"unsupported auth scheme {scheme!r}")
            except Exception as e:
                self.failed = True
                return self._failure("Neo.ClientError.Security.Unauthorized", str(e))
        self.authed = True
        return [(MSG_SUCCESS, [{
            "server": SERVER_AGENT,
            "connection_id": f"bolt-{uuid.uuid4().hex[:8]}",
        }])]

    def _reset(self) -> List[Tuple[int, List[Any]]]:
        self.failed = False
        self.stream = None
        if self.tx is not None and self.tx.is_open:
            self.tx.rollback()
        self.tx = None
        self.tx_executor = None
        return [(MSG_SUCCESS, [{}])]

    def _executor_for(self, extra: Dict[str, Any]):
        db = extra.get("db") or self.database
        return self.server.executor_for(db)

    def _run(self, query: str, params: Dict[str, Any],
             extra: Dict[str, Any]) -> List[Tuple[int, List[Any]]]:
        if self.stream is not None:
            return self._failure("Neo.ClientError.Request.Invalid",
                                 "previous result not consumed")
        if self.tx is not None and self.tx.is_open:
            executor = self.tx_executor
        else:
            executor = self._executor_for(extra)
        try:
            result = executor.execute(query, params or {})
        except Exception as e:
            self.failed = True
            return self._failure(_error_code(e), str(e))
        self.stream = _Stream(result.columns, result.rows,
                              getattr(result.stats, "to_dict", dict)())
        return [(MSG_SUCCESS, [{"fields": self.stream.columns, "t_first": 0}])]

    def _pull(self, extra: Dict[str, Any]) -> List[Tuple[int, List[Any]]]:
        if self.stream is None:
            return self._failure("Neo.ClientError.Request.Invalid", "no result to pull")
        n = extra.get("n", -1)
        out: List[Tuple[int, List[Any]]] = []
        s = self.stream
        end = len(s.rows) if n < 0 else min(s.pos + n, len(s.rows))
        while s.pos < end:
            out.append((MSG_RECORD, [[to_packable(v) for v in s.rows[s.pos]]]))
            s.pos += 1
        if s.pos >= len(s.rows):
            meta: Dict[str, Any] = {"t_last": 0}
            if s.stats:
                meta["stats"] = s.stats
            if self.tx is None:
                self.last_bookmark = f"bm-{uuid.uuid4().hex[:12]}"
                meta["bookmark"] = self.last_bookmark
            self.stream = None
            out.append((MSG_SUCCESS, [meta]))
        else:
            out.append((MSG_SUCCESS, [{"has_more": True}]))
        return out

    def _discard(self, extra: Dict[str, Any]) -> List[Tuple[int, List[Any]]]:
        if self.stream is None:
            return self._failure("Neo.ClientError.Request.Invalid", "no result to discard")
        n = extra.get("n", -1)
        s = self.stream
        if n < 0 or s.pos + n >= len(s.rows):
            self.stream = None
            return [(MSG_SUCCESS, [{"t_last": 0}])]
        s.pos += n
        return [(MSG_SUCCESS, [{"has_more": True}])]

    def _begin(self, extra: Dict[str, Any]) -> List[Tuple[int, List[Any]]]:
        if self.tx is not None and self.tx.is_open:
            return self._failure("Neo.ClientError.Request.Invalid",
                                 "transaction already open")
        db = extra.get("db") or self.database
        storage = self.server.storage_for(db)
        self.tx = TransactionOverlay(storage)
        from nornicdb_tpu.query.executor import CypherExecutor

        self.tx_executor = CypherExecutor(self.tx)
        base = self.server.executor_for(db)
        if getattr(base, "_search", None) is not None:
            self.tx_executor.set_search_service(base._search)
        return [(MSG_SUCCESS, [{}])]

    def _commit(self) -> List[Tuple[int, List[Any]]]:
        if self.tx is None or not self.tx.is_open:
            return self._failure("Neo.ClientError.Request.Invalid", "no open transaction")
        self.tx.commit()
        self.tx = None
        self.tx_executor = None
        self.last_bookmark = f"bm-{uuid.uuid4().hex[:12]}"
        return [(MSG_SUCCESS, [{"bookmark": self.last_bookmark}])]

    def _rollback(self) -> List[Tuple[int, List[Any]]]:
        if self.tx is None or not self.tx.is_open:
            return self._failure("Neo.ClientError.Request.Invalid", "no open transaction")
        self.tx.rollback()
        self.tx = None
        self.tx_executor = None
        return [(MSG_SUCCESS, [{}])]

    def _failure(self, code: str, message: str) -> List[Tuple[int, List[Any]]]:
        self.failed = True
        return [(MSG_FAILURE, [{"code": code, "message": message}])]


class _Goodbye(Exception):
    pass


def _error_code(e: Exception) -> str:
    from nornicdb_tpu.errors import CypherSyntaxError, NotFoundError

    if isinstance(e, CypherSyntaxError):
        return "Neo.ClientError.Statement.SyntaxError"
    if isinstance(e, NotFoundError):
        return "Neo.ClientError.Statement.EntityNotFound"
    return "Neo.DatabaseError.General.UnknownError"


# ---------------------------------------------------------------------------
# Transport: handshake + chunked messages over TCP
# ---------------------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def read_message(sock: socket.socket) -> bytes:
    """Read one chunked message (2-byte BE size chunks, 0x0000 ends)."""
    out = b""
    while True:
        size = struct.unpack(">H", _recv_exact(sock, 2))[0]
        if size == 0:
            if out:
                return out
            continue  # NOOP keepalive chunk
        out += _recv_exact(sock, size)


def write_message(sock: socket.socket, payload: bytes) -> None:
    buf = bytearray()
    for i in range(0, len(payload), 65535):
        chunk = payload[i:i + 65535]
        buf += struct.pack(">H", len(chunk)) + chunk
    buf += b"\x00\x00"
    sock.sendall(bytes(buf))


class BoltServer:
    """TCP server hosting BoltSessions over a DB (or DatabaseManager)."""

    def __init__(self, db, host: str = "127.0.0.1", port: int = 7687,
                 authenticator=None, database_manager=None):
        self.db = db
        self.host = host
        self.port = port
        self.authenticator = authenticator
        self.database_manager = database_manager
        self.default_database = getattr(db, "database", "neo4j")
        self._executors: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- routing ---------------------------------------------------------

    def storage_for(self, database: str):
        if self.database_manager is not None and database != self.default_database:
            return self.database_manager.get_storage(database)
        return self.db.storage

    def executor_for(self, database: str):
        if database == self.default_database:
            return self.db.executor
        with self._lock:
            ex = self._executors.get(database)
            if ex is None:
                from nornicdb_tpu.query.executor import CypherExecutor

                ex = CypherExecutor(self.storage_for(database))
                self._executors[database] = ex
            return ex

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "BoltServer":
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):  # noqa: D102
                try:
                    # Bolt is a small-message request/response protocol:
                    # without TCP_NODELAY, Nagle + delayed ACK stalls
                    # every exchange ~40ms (observed 22 ops/s vs 2k+)
                    self.request.setsockopt(socket.IPPROTO_TCP,
                                            socket.TCP_NODELAY, 1)
                    outer._serve_connection(self.request)
                except (ConnectionError, OSError, _Goodbye):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="bolt-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # -- per-connection protocol loop -----------------------------------

    def _serve_connection(self, sock: socket.socket) -> None:
        magic = struct.unpack(">I", _recv_exact(sock, 4))[0]
        if magic != BOLT_MAGIC:
            sock.close()
            return
        proposals = [struct.unpack(">I", _recv_exact(sock, 4))[0] for _ in range(4)]
        chosen = 0
        for p in proposals:
            major, minor = p & 0xFF, (p >> 8) & 0xFF
            if (major, minor) in SUPPORTED_VERSIONS:
                chosen = p & 0xFFFF
                break
            # range notation: minor..minor-range supported in 4.3+
            rng = (p >> 16) & 0xFF
            for delta in range(rng + 1):
                if (major, minor - delta) in SUPPORTED_VERSIONS:
                    chosen = ((minor - delta) << 8) | major
                    break
            if chosen:
                break
        sock.sendall(struct.pack(">I", chosen))
        if chosen == 0:
            sock.close()
            return

        session = BoltSession(self)
        while True:
            payload = read_message(sock)
            msg = Unpacker(payload).unpack()
            if not isinstance(msg, Structure):
                raise ConnectionError("malformed message")
            try:
                responses = session.handle(msg.tag, msg.fields)
            except _Goodbye:
                sock.close()
                return
            for sig, fields in responses:
                p = Packer()
                p.pack(Structure(sig, fields))
                write_message(sock, p.data())
