"""Standalone OAuth 2.0 authorization-code provider.

Reference: cmd/oauth-provider/main.go (a separate process serving the
authorization-code flow with a consent form, discovery document, token
exchange, and userinfo). Same surface here:

- ``GET  /.well-known/oauth-authorization-server`` — discovery
- ``GET  /oauth2/v1/authorize``  — consent form (HTML)
- ``POST /oauth2/v1/consent``    — approve -> redirect with code
- ``POST /oauth2/v1/token``      — authorization_code -> access token
- ``GET  /oauth2/v1/userinfo``   — bearer token -> profile
- ``GET  /health``

Tokens and codes are in-memory with expiry, like the reference; start
via ``python -m nornicdb_tpu.cli oauth-provider --port 8888``.
"""

from __future__ import annotations

import html
import json
import secrets
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

_CODE_TTL_S = 600.0
_TOKEN_TTL_S = 3600.0


class OAuthProvider:
    def __init__(self, port: int = 8888, client_id: str = "nornicdb",
                 client_secret: str = "nornicdb-secret",
                 issuer: Optional[str] = None, host: str = "127.0.0.1",
                 allowed_redirects: Optional[list] = None):
        self.port = port
        self.host = host
        self.client_id = client_id
        self.client_secret = client_secret
        self.issuer = issuer or f"http://{host}:{port}"
        # redirect_uri allowlist (prefix match). Codes must never be
        # delivered to unregistered URIs (OAuth code-exfiltration via
        # open redirect); default covers local development only.
        self.allowed_redirects = list(allowed_redirects) if \
            allowed_redirects is not None else \
            ["http://localhost", "http://127.0.0.1", "http://app/cb"]
        self.users: Dict[str, Dict[str, Any]] = {
            "demo": {"sub": "demo", "preferred_username": "demo",
                     "roles": ["reader"]},
        }
        self._codes: Dict[str, Dict[str, Any]] = {}
        self._tokens: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- core flows ------------------------------------------------------

    def discovery(self) -> Dict[str, Any]:
        return {
            "issuer": self.issuer,
            "authorization_endpoint": f"{self.issuer}/oauth2/v1/authorize",
            "token_endpoint": f"{self.issuer}/oauth2/v1/token",
            "userinfo_endpoint": f"{self.issuer}/oauth2/v1/userinfo",
            "response_types_supported": ["code"],
            "grant_types_supported": ["authorization_code"],
            "token_endpoint_auth_methods_supported": ["client_secret_post"],
        }

    def redirect_allowed(self, redirect_uri: str) -> bool:
        """Exact scheme+host+port match against a registered entry, with
        path prefix match. A raw string prefix is NOT enough: a host like
        ``localhost.evil.example`` starts with an allowed prefix but must
        be rejected."""
        try:
            target = urllib.parse.urlsplit(str(redirect_uri))
            target_port = target.port  # .port parses lazily: may raise
        except ValueError:
            return False
        if not target.scheme or not target.hostname:
            return False
        for entry in self.allowed_redirects:
            allowed = urllib.parse.urlsplit(entry)
            if target.scheme != allowed.scheme:
                continue
            if target.hostname != allowed.hostname:
                continue
            # an entry without an explicit port accepts any port on that
            # exact host (dev servers move ports); an explicit port pins
            if allowed.port is not None and target_port != allowed.port:
                continue
            if target.path.startswith(allowed.path):
                return True
        return False

    def issue_code(self, client_id: str, redirect_uri: str,
                   user_id: str) -> str:
        if client_id != self.client_id:
            raise ValueError("unknown client_id")
        if not self.redirect_allowed(redirect_uri):
            raise ValueError("redirect_uri not registered")
        if user_id not in self.users:
            raise ValueError("unknown user")
        code = secrets.token_urlsafe(32)
        with self._lock:
            self._gc_locked()
            self._codes[code] = {
                "client_id": client_id, "redirect_uri": redirect_uri,
                "user_id": user_id,
                "expires_at": time.time() + _CODE_TTL_S,
            }
        return code

    def exchange(self, grant_type: str, code: str, client_id: str,
                 client_secret: str,
                 redirect_uri: str) -> Dict[str, Any]:
        if grant_type != "authorization_code":
            return {"error": "unsupported_grant_type"}
        if client_id != self.client_id or \
                client_secret != self.client_secret:
            return {"error": "invalid_client"}
        with self._lock:
            self._gc_locked()
            entry = self._codes.pop(code, None)  # single use
            if entry is None or entry["expires_at"] < time.time():
                return {"error": "invalid_grant"}
            if entry["redirect_uri"] != redirect_uri or \
                    entry["client_id"] != client_id:
                return {"error": "invalid_grant"}
            token = secrets.token_urlsafe(32)
            self._tokens[token] = {
                "user_id": entry["user_id"],
                "expires_at": time.time() + _TOKEN_TTL_S,
            }
        return {"access_token": token, "token_type": "Bearer",
                "expires_in": int(_TOKEN_TTL_S)}

    def userinfo(self, token: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            entry = self._tokens.get(token)
            if entry is None or entry["expires_at"] < time.time():
                return None
            user = self.users.get(entry["user_id"])
        return dict(user) if user else None

    def _gc_locked(self) -> None:
        now = time.time()
        for table in (self._codes, self._tokens):
            for key in [k for k, v in table.items()
                        if v["expires_at"] < now]:
                table.pop(key, None)

    # -- HTTP ------------------------------------------------------------

    def start(self) -> "OAuthProvider":
        provider = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _json(self, obj: Dict[str, Any], status: int = 200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _html(self, text: str, status: int = 200):
                body = text.encode()
                self.send_response(status)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _form(self) -> Dict[str, str]:
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length).decode()
                ctype = self.headers.get("Content-Type", "")
                if "application/json" in ctype:
                    try:
                        return {str(k): str(v) for k, v in
                                json.loads(raw or "{}").items()}
                    except ValueError:
                        return {}
                return {k: v[0] for k, v in
                        urllib.parse.parse_qs(raw).items()}

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                qs = {k: v[0] for k, v in
                      urllib.parse.parse_qs(parsed.query).items()}
                if parsed.path == "/.well-known/oauth-authorization-server":
                    return self._json(provider.discovery())
                if parsed.path == "/health":
                    return self._json({"status": "ok",
                                       "users": len(provider.users)})
                if parsed.path == "/oauth2/v1/authorize":
                    if qs.get("response_type") != "code":
                        return self._json(
                            {"error": "unsupported_response_type"}, 400)
                    if qs.get("client_id") != provider.client_id:
                        return self._json({"error": "invalid_client"}, 400)
                    if not provider.redirect_allowed(
                            qs.get("redirect_uri", "")):
                        return self._json(
                            {"error": "invalid_redirect_uri"}, 400)
                    return self._html(_consent_form(
                        qs.get("client_id", ""),
                        qs.get("redirect_uri", ""),
                        qs.get("state", ""), qs.get("scope", "")))
                if parsed.path == "/oauth2/v1/userinfo":
                    auth = self.headers.get("Authorization", "")
                    token = auth.removeprefix("Bearer ").strip()
                    info = provider.userinfo(token)
                    if info is None:
                        return self._json({"error": "invalid_token"}, 401)
                    return self._json(info)
                return self._json({"error": "not_found"}, 404)

            def do_POST(self):
                parsed = urllib.parse.urlparse(self.path)
                form = self._form()
                if parsed.path == "/oauth2/v1/consent":
                    try:
                        code = provider.issue_code(
                            form.get("client_id", ""),
                            form.get("redirect_uri", ""),
                            form.get("user_id", "demo"))
                    except ValueError as exc:
                        return self._json(
                            {"error": "invalid_request",
                             "error_description": str(exc)}, 400)
                    target = form.get("redirect_uri", "")
                    sep = "&" if "?" in target else "?"
                    location = (f"{target}{sep}code={code}"
                                f"&state={urllib.parse.quote(form.get('state', ''))}")
                    self.send_response(302)
                    self.send_header("Location", location)
                    self.end_headers()
                    return None
                if parsed.path == "/oauth2/v1/token":
                    out = provider.exchange(
                        form.get("grant_type", ""), form.get("code", ""),
                        form.get("client_id", ""),
                        form.get("client_secret", ""),
                        form.get("redirect_uri", ""))
                    status = 200 if "access_token" in out else 400
                    return self._json(out, status)
                return self._json({"error": "not_found"}, 404)

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        if "://" not in (self.issuer or "") or self.issuer.endswith(":0"):
            self.issuer = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def _consent_form(client_id: str, redirect_uri: str, state: str,
                  scope: str) -> str:
    esc = html.escape
    return f"""<!doctype html><html><head><title>Authorize</title></head>
<body><h1>Authorize {esc(client_id)}</h1>
<p>The application requests access{' to ' + esc(scope) if scope else ''}.</p>
<form method="POST" action="/oauth2/v1/consent">
<input type="hidden" name="client_id" value="{esc(client_id)}">
<input type="hidden" name="redirect_uri" value="{esc(redirect_uri)}">
<input type="hidden" name="state" value="{esc(state)}">
<input type="hidden" name="scope" value="{esc(scope)}">
<label>User: <input name="user_id" value="demo"></label>
<button type="submit">Approve</button>
</form></body></html>"""
