"""HTTP server: Neo4j HTTP API, REST search, admin, metrics, health.

Reference: pkg/server — router (server_router.go:59-314), server.New
(server.go:921), Neo4j transactional HTTP API (`/db/{name}/tx/commit`),
REST search/similar/decay/embed endpoints (server_nornicdb.go), auth
(JWT bearer + basic), Prometheus /metrics (server_public.go:195-216),
/health + /status, GDPR export/delete, rate limiting, multi-database
admin. Built on stdlib ThreadingHTTPServer (no flask in this image).
"""

from __future__ import annotations

import base64
import functools
import json
import os
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from nornicdb_tpu import admission as _adm
from nornicdb_tpu import obs
from nornicdb_tpu.obs import tenant as _tenant

# tier-mix truth for search wire-cache hits (ISSUE 10): cached child —
# the response-bytes hit path must not pay a labels() probe per request
_SEARCH_CACHED_SERVED = obs.audit.served_counter("hybrid", "cached")
from nornicdb_tpu.audit import ADMIN_ACTION, AUTH, DATA_WRITE, GDPR, AuditLog
from nornicdb_tpu.auth import ADMIN, READ, WRITE, AuthError, PermissionDenied
from nornicdb_tpu.storage.txn import TransactionManager

SERVER_NAME = "nornicdb-tpu"
API_VERSION = "1.0"


class ReuseportThreadingHTTPServer(ThreadingHTTPServer):
    """SO_REUSEPORT-bound ThreadingHTTPServer: the wire plane's
    parallel frontend workers (ISSUE 11) share one listening port and
    let the kernel balance accepted connections. Shared by HttpServer
    (``reuse_port=True``) and the worker frontends (wire_plane.py)."""

    daemon_threads = True

    def server_bind(self):
        import socket as _socket

        self.socket.setsockopt(_socket.SOL_SOCKET,
                               _socket.SO_REUSEPORT, 1)
        ThreadingHTTPServer.server_bind(self)

_HTTP_H = obs.REGISTRY.histogram(
    "nornicdb_http_request_seconds",
    "HTTP request latency by route family", labels=("route",))


# routes admission control never sheds: probes, observability and
# admin surfaces must stay reachable on an overloaded node — shedding
# /readyz or /admin/scheduler would blind the operator exactly when
# the scheduler is acting (ISSUE 15)
_SHED_EXEMPT = ("health", "readyz", "metrics", "admin", "auth",
                "status", "openapi.json", "swagger", "docs", "browser",
                "bifrost", "")


# qdrant point READ sub-routes: POST /collections/<c>/points/<tail> is
# a read for these tails (mirrors the gRPC _shed_lane_of split: only
# point WRITES ride the background lane)
_POINT_READ_TAILS = ("search", "query", "scroll", "count", "recommend",
                     "retrieve")


def _shed_lane_for(method: str, path: str) -> Optional[str]:
    """Admission lane of one HTTP request, or None when the route is
    exempt from shedding. Writes (PUT/DELETE, bulk point upserts and
    point delete/payload ops) ride the background lane — under
    pressure they shed before reads; the qdrant point READ endpoints
    (search/query/scroll/count/recommend) stay interactive."""
    seg = path.split("/", 2)[1] if path.startswith("/") else path
    if seg in _SHED_EXEMPT:
        return None
    if method in ("PUT", "DELETE"):
        return _adm.LANE_BACKGROUND
    if method == "POST" and "/points" in path \
            and path.rsplit("/", 1)[-1] not in _POINT_READ_TAILS:
        return _adm.LANE_BACKGROUND
    return _adm.LANE_INTERACTIVE


def _route_family(path: str) -> str:
    """Coarse route label — first path segment, special-casing the tx
    API — so metric cardinality stays bounded no matter what clients
    request (raw paths carry ids/collection names)."""
    segments = [s for s in path.split("/") if s]
    if not segments:
        return "root"
    head = segments[0]
    if head == "db":
        return "tx"
    if head in ("nornicdb", "collections", "graphql", "admin", "heimdall",
                "mcp", "metrics", "health", "status", "auth", "browser",
                "v1", "debug"):
        return head
    return "other"


def _accepts_openmetrics(accept: str) -> bool:
    """True when the Accept header prefers the OpenMetrics exposition.

    Honors q-values (RFC 9110 §12.4.2): ``q=0`` means "not acceptable",
    and OpenMetrics is only served when its q is at least that of any
    classic-text range (``text/plain``, ``text/*``, ``*/*``) — a
    scraper sending ``text/plain;q=1.0, application/openmetrics-text;
    q=0.1`` prefers (and gets) classic Prometheus text."""
    om_q = 0.0
    classic_q = 0.0
    saw_om = False
    for part in accept.split(","):
        fields = part.strip().split(";")
        mtype = fields[0].strip().lower()
        if not mtype:
            continue
        q = 1.0
        for param in fields[1:]:
            key, _, value = param.strip().partition("=")
            if key.strip().lower() == "q":
                try:
                    q = float(value)
                except ValueError:
                    pass
        if mtype == "application/openmetrics-text":
            saw_om = True
            om_q = max(om_q, q)
        elif mtype in ("text/plain", "text/*", "*/*"):
            classic_q = max(classic_q, q)
    return saw_om and om_q > 0.0 and om_q >= classic_q


class _NegotiatedText(str):
    """A pre-rendered text body carrying its own content type (used by
    the OpenMetrics exposition, whose media type the default
    str-payload sniffing in ``_reply`` cannot infer)."""

    content_type: str = "text/plain; charset=utf-8"


class _Metrics:
    """Server counters, now backed by the process-wide telemetry
    registry (nornicdb_tpu/obs) so /metrics serves REAL Prometheus
    types — ``counter`` lines for these, ``histogram`` exposition with
    _bucket/_sum/_count for the latency families — instead of the old
    everything-is-a-gauge text. The inc(name) call-site contract is
    unchanged."""

    def __init__(self) -> None:
        from nornicdb_tpu.obs import REGISTRY

        self._registry = REGISTRY
        self._fams: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.started_at = time.time()

    def inc(self, name: str, value: float = 1.0) -> None:
        fam = self._fams.get(name)
        if fam is None:
            with self._lock:
                fam = self._fams.get(name)
                if fam is None:
                    fam = self._registry.counter(
                        f"nornicdb_{name}", f"server counter {name}")
                    self._fams[name] = fam
        fam.inc(value)

    def _extra_gauges(self, extra: Dict[str, float]) -> Dict[str, float]:
        gauges = {f"nornicdb_{k}": v for k, v in extra.items()}
        gauges["nornicdb_uptime_seconds"] = time.time() - self.started_at
        return gauges

    def render(self, extra: Dict[str, float]) -> str:
        return self._registry.render(self._extra_gauges(extra))

    def render_openmetrics(self, extra: Dict[str, float]) -> _NegotiatedText:
        body = _NegotiatedText(
            self._registry.render_openmetrics(self._extra_gauges(extra)))
        body.content_type = self._registry.OPENMETRICS_CONTENT_TYPE
        return body


class _RateLimiter:
    """Fixed-window per-client limiter (reference: rate limiting in
    pkg/server). One dict per CURRENT window: when the minute rolls
    over, every recorded count belongs to a dead window, so the whole
    map is dropped — a long-lived server no longer leaks one entry per
    client ever seen (the old map kept stale (window, count) tuples
    forever)."""

    def __init__(self, per_minute: int):
        self.per_minute = per_minute
        self._window = -1
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def allow(self, client: str) -> bool:
        if not self.per_minute:
            return True
        window = int(time.time() // 60)
        with self._lock:
            if window != self._window:
                self._window = window
                self._counts.clear()
            n = self._counts.get(client, 0)
            if n >= self.per_minute:
                return False
            self._counts[client] = n + 1
            return True

    def tracked_clients(self) -> int:
        with self._lock:
            return len(self._counts)


class HTTPError(Exception):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(message)
        self.status = status
        self.code = code
        self.message = message


class _GraphGeneration:
    """MutationListener that versions the whole graph: every mutation
    event (including bulk clears) bumps one counter, giving response-
    bytes caches a safe validity token. The bump is an itertools.count
    next() — atomic under the GIL, unlike `gen += 1`, whose lost
    updates could leave the generation unmoved across a racing pair of
    writes and let a stale entry validate."""

    __slots__ = ("gen", "_c")

    def __init__(self):
        import itertools

        self._c = itertools.count(1)
        self.gen = 0

    def _bump(self) -> None:
        self.gen = next(self._c)

    def on_node_upsert(self, node) -> None:
        self._bump()

    def on_node_delete(self, node_id) -> None:
        self._bump()

    def on_edge_upsert(self, edge) -> None:
        self._bump()

    def on_edge_delete(self, edge_id) -> None:
        self._bump()

    def on_bulk_change(self) -> None:
        self._bump()


class HttpServer:
    """One HTTP surface over a DB (+ optional multidb manager, auth,
    audit)."""

    def __init__(self, db, host: str = "127.0.0.1", port: int = 7474,
                 authenticator=None, database_manager=None,
                 audit: Optional[AuditLog] = None,
                 rate_limit_per_minute: int = 0,
                 reuse_port: bool = False):
        self.db = db
        self.host = host
        self.port = port
        # SO_REUSEPORT bind (ISSUE 11): parallel wire-plane frontend
        # workers share one listening port; the kernel load-balances
        # accepted connections across their listeners
        self._reuse_port = reuse_port
        self.authenticator = authenticator
        self.database_manager = database_manager
        self.audit = audit or AuditLog(enabled=False)
        self.metrics = _Metrics()
        self.rate_limiter = _RateLimiter(rate_limit_per_minute)
        self.tx_manager = TransactionManager(timeout_seconds=60.0)
        self.default_database = getattr(db, "database", "neo4j")
        self._executors: Dict[str, Any] = {}
        self._tx_executors: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._mcp = None  # lazily-mounted MCP endpoint (/mcp)
        # /nornicdb/search response-bytes cache: (auth, body) ->
        # (search generation, serialized 200 response)
        from nornicdb_tpu.cache import LRUCache

        self._search_wire: LRUCache = LRUCache(max_size=512,
                                               ttl_seconds=300.0)
        # /graphql response-bytes cache for query-kind documents, keyed
        # the same way and validated against a graph-mutation
        # generation fed by a storage listener (any write through any
        # surface — bolt, tx API, qdrant, bulk clears — invalidates)
        self._graphql_wire: LRUCache = LRUCache(max_size=512,
                                                ttl_seconds=300.0)
        self._graph_gen = _GraphGeneration()
        if hasattr(db, "storage") and hasattr(db.storage, "add_listener"):
            db.storage.add_listener(self._graph_gen)

    @property
    def mcp(self):
        if self._mcp is None:
            from nornicdb_tpu.api.mcp import McpServer

            self._mcp = McpServer(self.db)
        return self._mcp

    # -- routing helpers -------------------------------------------------

    def storage_for(self, database: str):
        if self.database_manager is not None and database != self.default_database:
            return self.database_manager.get_storage(database)
        if database != self.default_database:
            raise HTTPError(404, "Neo.ClientError.Database.DatabaseNotFound",
                            f"database {database!r} not found")
        return self.db.storage

    def executor_for(self, database: str):
        if database == self.default_database:
            return self.db.executor
        with self._lock:
            ex = self._executors.get(database)
            if ex is None:
                from nornicdb_tpu.query.executor import CypherExecutor

                ex = CypherExecutor(self.storage_for(database))
                self._executors[database] = ex
            return ex

    # -- auth ------------------------------------------------------------

    def authenticate(self, headers) -> Optional[str]:
        """Returns username or None (anonymous). Raises HTTPError(401)."""
        if self.authenticator is None:
            return None
        header = headers.get("Authorization", "")
        try:
            if header.startswith("Bearer "):
                claims = self.authenticator.verify_token(header[7:])
                return claims.get("sub")
            if header.startswith("Basic "):
                raw = base64.b64decode(header[6:]).decode()
                username, _, password = raw.partition(":")
                self.authenticator.login(username, password)
                return username
        except AuthError as e:
            self.audit.record(AUTH, "reject", success=False, reason=str(e))
            raise HTTPError(401, "Neo.ClientError.Security.Unauthorized", str(e))
        if self.authenticator.allow_anonymous_reads:
            return None
        raise HTTPError(401, "Neo.ClientError.Security.Unauthorized",
                        "authentication required")

    def authorize(self, username: Optional[str], database: str, privilege: str) -> None:
        if self.authenticator is None:
            return
        try:
            self.authenticator.check(username, database, privilege)
        except PermissionDenied as e:
            raise HTTPError(403, "Neo.ClientError.Security.Forbidden", str(e))

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "HttpServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # keep-alive throughput: without TCP_NODELAY the two-write
            # response (headers, then body) stalls ~40ms per request on
            # the Nagle + delayed-ACK interaction — measured 23 ops/s vs
            # 3,300 with it on the same handler. The buffered wfile
            # (flushed once per request by handle_one_request) makes the
            # response a single segment.
            disable_nagle_algorithm = True
            wbufsize = 64 * 1024

            def log_message(self, *args):  # silence stdlib logging
                pass

            def _dispatch(self, method: str) -> None:
                outer.metrics.inc("http_requests_total")
                client = self.client_address[0]
                if not outer.rate_limiter.allow(client):
                    self._reply(429, {"error": "rate limit exceeded"})
                    return
                path = self.path.split("?")[0]
                if method == "GET" and path == "/bifrost/events":
                    # SSE push channel (reference: heimdall Bifrost,
                    # bifrost.go:15,42) — streamed, bypasses JSON reply
                    # AND the latency histogram (stream lifetime is not
                    # request latency)
                    outer._stream_bifrost(self)
                    return
                t0 = time.perf_counter()
                # cross-node trace propagation (ISSUE 13): a request
                # forwarded by the fleet router (RemoteReplica) carries
                # its originating trace context in X-Nornic-Trace — the
                # root opened here joins that trace instead of minting
                # a new id, so one fleet-routed read is ONE trace
                tctx = obs.unpack_context(
                    self.headers.get(obs.TRACE_HEADER, ""))
                # deadline budget minted at ingress (ISSUE 15): the
                # client's X-Nornic-Deadline-Ms when present, else the
                # surface default derived from the SLO objective; the
                # route's admission lane binds the scope so per-lane
                # accounting matches the shed verdict
                dl, explicit = _adm.parse_deadline_header(
                    self.headers.get(_adm.DEADLINE_HEADER), "http")
                lane = _shed_lane_for(method, path)
                # tenant resolution (ISSUE 18): explicit X-Nornic-Tenant
                # header > tenant propagated in the trace context > the
                # multidb namespace (/db/{name}/... routes name their
                # DB; everything else is the server's default database)
                segs = [s for s in path.split("/") if s]
                if len(segs) > 1 and segs[0] == "db":
                    namespace = segs[1]
                elif len(segs) > 1 and segs[0] == "collections":
                    # qdrant routes derive the tenant from the
                    # collection BEFORE admission, so a shed verdict
                    # is attributed to the right tenant (the deeper
                    # alias-resolving refine still runs on admitted
                    # requests)
                    namespace = (_tenant.tenant_for_collection(segs[1])
                                 or outer.default_database)
                else:
                    namespace = outer.default_database
                ten, ten_explicit = _tenant.resolve(
                    self.headers.get(_tenant.TENANT_HEADER), tctx,
                    namespace)
                try:
                    # propagated_trace opens a plain root when no
                    # context came across — one call site, both cases
                    with _tenant.tenant_scope(ten,
                                              explicit=ten_explicit), \
                            obs.propagated_trace(
                                "wire", tctx,
                                method=f"{method} {path}",
                                transport="http"):
                        obs.annotate(
                            deadline_ms=round(
                                (dl - time.time()) * 1e3, 1),
                            tenant=_tenant.current_tenant())
                        with _adm.request_scope("http", dl,
                                                lane_name=lane,
                                                explicit=explicit):
                            self._handle(method, lane)
                finally:
                    # finally: a handler that raises (client hung up
                    # mid-write) is exactly the request p99 wants
                    _HTTP_H.labels(_route_family(path)).observe(
                        time.perf_counter() - t0)

            def _reply_shed(self, e) -> None:
                outer.metrics.inc("http_errors_total")
                self._reply(
                    429,
                    {"errors": [{
                        "code": "Neo.TransientError.Request."
                                "ResourceExhausted",
                        "message": str(e)}]},
                    extra_headers={"Retry-After": str(
                        max(1, int(round(e.retry_after_s))))})

            def _handle(self, method: str,
                        lane: Optional[str]) -> None:
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                # admission verdict (ISSUE 15): work routes pass the
                # controller before any storage/device work; a shed is
                # an honest 429 with Retry-After from the lane's drain
                # rate — never a silent queue entry. The wire-cached
                # byte routes below check INSIDE their helpers, after
                # the cache probe: a byte-fresh hit is never shed.
                cached_route = (method == "POST" and self.path in
                                ("/nornicdb/search", "/graphql"))
                if lane is not None and not cached_route:
                    try:
                        _adm.check("http", lane)
                    except _adm.ShedError as e:
                        self._reply_shed(e)
                        return
                if cached_route:
                    # response-bytes wire cache (same pattern as the
                    # qdrant gRPC Search): identical request bytes
                    # against unchanged state skip execution, hit
                    # copies AND json serialization entirely
                    try:
                        data = (outer._search_response_bytes(
                                    body, self.headers)
                                if self.path == "/nornicdb/search" else
                                outer._graphql_response_bytes(
                                    body, self.headers))
                    except HTTPError as e:
                        outer.metrics.inc("http_errors_total")
                        self._reply(e.status, {"errors": [
                            {"code": e.code, "message": e.message}]})
                        return
                    except _adm.ShedError as e:
                        # miss-path shed from inside the cached-byte
                        # helper (hits never reach the controller)
                        self._reply_shed(e)
                        return
                    except _adm.DeadlineExceeded as e:
                        outer.metrics.inc("http_errors_total")
                        self._reply(504, {"errors": [
                            {"code": "Neo.TransientError.Request."
                                     "DeadlineExceeded",
                             "message": str(e)}]})
                        return
                    except Exception as e:  # noqa: BLE001
                        outer.metrics.inc("http_errors_total")
                        self._reply(500, {"errors": [
                            {"code": "Neo.DatabaseError.General."
                                     "UnknownError",
                             "message": str(e)}]})
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                try:
                    status, payload = outer.route(
                        method, self.path, body, self.headers)
                except HTTPError as e:
                    outer.metrics.inc("http_errors_total")
                    self._reply(e.status, {"errors": [
                        {"code": e.code, "message": e.message}]})
                    return
                except _adm.DeadlineExceeded as e:
                    # budget expired in queue: honest 504 fail-fast
                    # (the ledger/journal record is the batcher's)
                    outer.metrics.inc("http_errors_total")
                    self._reply(504, {"errors": [
                        {"code": "Neo.TransientError.Request."
                                 "DeadlineExceeded",
                         "message": str(e)}]})
                    return
                except Exception as e:  # noqa: BLE001 — surface boundary
                    outer.metrics.inc("http_errors_total")
                    self._reply(500, {"errors": [
                        {"code": "Neo.DatabaseError.General.UnknownError",
                         "message": str(e)}]})
                    return
                self._reply(status, payload)

            def _reply(self, status: int, payload: Dict[str, Any],
                       extra_headers: Optional[Dict[str, str]] = None
                       ) -> None:
                if isinstance(payload, _NegotiatedText):
                    ctype = payload.content_type
                    data = payload.encode()
                elif isinstance(payload, str):
                    # pre-rendered text bodies: playground HTML, or the
                    # Prometheus exposition format (/metrics)
                    ctype = ("text/html; charset=utf-8"
                             if payload.lstrip().startswith("<") else
                             "text/plain; version=0.0.4")
                    data = payload.encode()
                else:
                    ctype = "application/json"
                    # _json_default converts Node/Edge/numpy lazily — an
                    # eager _jsonable() walk over every response value
                    # cost ~0.1ms/request on the search surface
                    t_ser = time.perf_counter()
                    data = json.dumps(payload,
                                      default=_json_default).encode()
                    obs.record_stage("http", "serialize",
                                     time.perf_counter() - t_ser)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        server_cls = (ReuseportThreadingHTTPServer if self._reuse_port
                      else ThreadingHTTPServer)
        self._server = server_cls((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="http-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # -- router (reference: server_router.go:59-314) ---------------------

    def route(self, method: str, path: str, body: bytes,
              headers) -> Tuple[int, Any]:
        parsed = urlparse(path)
        segments = [s for s in parsed.path.split("/") if s]
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        payload: Dict[str, Any] = {}
        if body:
            t_parse = time.perf_counter()
            try:
                payload = json.loads(body)
            except json.JSONDecodeError:
                raise HTTPError(400, "Neo.ClientError.Request.InvalidFormat",
                                "request body must be JSON")
            obs.record_stage("http", "parse",
                             time.perf_counter() - t_parse)

        # public endpoints (no auth)
        if parsed.path == "/health":
            return 200, {"status": "ok"}
        if parsed.path == "/readyz":
            # readiness (distinct from liveness): a live node that is
            # mid-rebuild, near changelog overrun or queue-saturated
            # should be rotated out of traffic, not restarted
            return self._readyz()
        if parsed.path == "/metrics":
            # content negotiation: OpenMetrics (exemplars, # EOF) when
            # asked for, classic Prometheus text — byte-compatible with
            # what every prior round served — otherwise
            accept = str(headers.get("Accept", "") or "") if headers else ""
            if _accepts_openmetrics(accept):
                return 200, self.metrics.render_openmetrics(
                    self._metric_snapshot())
            return 200, self.metrics.render(self._metric_snapshot())
        if parsed.path == "/" and method == "GET":
            return 200, {"server": SERVER_NAME, "version": API_VERSION,
                         "bolt": "bolt://", "transaction": "/db/{name}/tx",
                         "browser": "/browser"}
        if parsed.path in ("/browser", "/browser/") and method == "GET":
            # embedded admin browser (reference: ui/ React app served by
            # the binary via embed.go)
            return 200, _browser_html()
        if parsed.path == "/openapi.json" and method == "GET":
            from nornicdb_tpu.api.openapi import openapi_spec

            return 200, openapi_spec()
        if parsed.path in ("/swagger", "/swagger/", "/docs") and \
                method == "GET":
            # interactive API docs (reference: cmd/swagger-ui); single
            # self-contained page, no CDN assets
            from nornicdb_tpu.api.openapi import docs_page

            return 200, docs_page()
        if parsed.path == "/auth/login" and method == "POST":
            return self._login(payload)

        username = self.authenticate(headers)

        # MCP JSON-RPC endpoint (reference: pkg/mcp streamable HTTP)
        if parsed.path == "/mcp" and method == "POST":
            self.authorize(username, self.default_database, WRITE)
            response = self.mcp.handle_jsonrpc(payload)
            return (200, response) if response is not None else (202, {})

        # GraphQL endpoint + playground (reference: pkg/graphql mount)
        if parsed.path == "/graphql":
            if method == "GET":
                from nornicdb_tpu.api.graphql import PLAYGROUND_HTML

                return 200, PLAYGROUND_HTML
            if method == "POST":
                from nornicdb_tpu.api.graphql import GraphQLAPI, GraphQLError

                q = payload.get("query", "")
                op_name = payload.get("operationName")
                try:
                    kind = GraphQLAPI.operation_kind(q, op_name)
                except GraphQLError as e:
                    return 200, {"data": None,
                                 "errors": [{"message": str(e)}]}
                self.authorize(
                    username, self.default_database,
                    WRITE if kind == "mutation" else READ,
                )
                return 200, self.graphql.execute(
                    q, payload.get("variables"), op_name)

        if parsed.path == "/status":
            return 200, self._status()
        if parsed.path == "/debug/profile" and method == "POST":
            # pprof analog (reference keeps pprof routes behind a build
            # flag, server_router.go:302-314): profile one statement and
            # return the hottest frames. Admin-only.
            self.authorize(username, self.default_database, ADMIN)
            return self._debug_profile(payload)

        # Neo4j transactional HTTP API: /db/{name}/tx[/commit|/{txid}...]
        if segments[:1] == ["db"] and len(segments) >= 3:
            return self._db_routes(method, segments, payload, username)

        # REST convenience API (reference: server_nornicdb.go)
        if segments[:1] == ["nornicdb"]:
            return self._nornicdb_routes(method, segments, payload, query, username)

        # Heimdall: OpenAI-compatible chat + management
        # (reference: pkg/heimdall OpenAI-compatible chat, scheduler.go:311)
        if parsed.path == "/v1/chat/completions" and method == "POST":
            self.authorize(username, self.default_database, READ)
            return self._chat_completions(payload, username)
        if segments[:1] == ["heimdall"]:
            self.authorize(username, self.default_database,
                           WRITE if method == "POST" else READ)
            return self._heimdall_routes(method, segments, payload, username)

        # Qdrant-compatible REST surface (reference: pkg/qdrantgrpc
        # translated onto storage+search; REST here speaks the Qdrant
        # HTTP wire format)
        if segments[:1] == ["collections"]:
            self.authorize(
                username, self.default_database,
                WRITE if method in ("PUT", "DELETE") or
                (len(segments) >= 3 and segments[2] == "points" and
                 segments[-1] in ("delete",)) else READ,
            )
            return self._qdrant_routes(method, segments, payload, query)

        # admin
        if segments[:1] == ["admin"]:
            return self._admin_routes(method, segments, payload, username)

        raise HTTPError(404, "Neo.ClientError.Request.Invalid",
                        f"no route for {method} {parsed.path}")

    def _metric_snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        try:
            out["nodes_total"] = float(self.db.storage.count_nodes())
            out["edges_total"] = float(self.db.storage.count_edges())
        except Exception:
            pass
        return out

    def _readyz(self) -> Tuple[int, Any]:
        """Readiness verdict from the resource-accounting snapshot:
        degraded (503) while any registered index has a background
        rebuild in flight, any changelog is near overrun (the device
        paths are about to fall back to host-exact serving), or any
        MicroBatcher queue is saturated past its drain rate. Thresholds:
        ``NORNICDB_READY_CHANGELOG_FRAC`` (default 0.9) and
        ``NORNICDB_READY_QUEUE_FACTOR`` (default 1.0 x max_batch)."""
        from nornicdb_tpu.config import env_float

        changelog_frac = env_float("READY_CHANGELOG_FRAC", 0.9)
        queue_factor = env_float("READY_QUEUE_FACTOR", 1.0)
        reasons: List[str] = []
        checks = {"indexes": 0, "queues": 0, "rebuilds_pending": 0,
                  "changelogs_near_overrun": 0, "queues_saturated": 0,
                  "parity_breaches": 0}
        for entry in obs.resource_snapshot():
            name = f"{entry['family']}/{entry['index']}"
            if "queue_depth" in entry and "rows" not in entry:
                checks["queues"] += 1
                limit = max(1, int((entry.get("max_batch") or 64)
                                   * queue_factor))
                if entry["queue_depth"] >= limit:
                    checks["queues_saturated"] += 1
                    reasons.append(
                        f"queue_saturated:{entry['index']}"
                        f"({entry['queue_depth']}/{limit})")
                continue
            checks["indexes"] += 1
            if entry.get("rebuild_in_flight"):
                checks["rebuilds_pending"] += 1
                reasons.append(f"index_rebuild:{name}")
            depth = entry.get("changelog_depth")
            cap = entry.get("changelog_cap")
            if depth is not None and cap and depth >= changelog_frac * cap:
                checks["changelogs_near_overrun"] += 1
                reasons.append(
                    f"changelog_near_overrun:{name}({depth}/{cap})")
        # device-memory ledger (ISSUE 20): shape-derived gauges vs the
        # backend's own live-buffer accounting — sustained drift past
        # the bound means bytes the accounting cannot name (a leak, or
        # an unregistered resident slab); either way this node's
        # capacity story is wrong and an operator must look
        try:
            mem = obs.device.reconcile()
            checks["device_mem_leak"] = int(bool(mem["leak_suspected"]))
            if mem["leak_suspected"]:
                reasons.append(
                    f"device_mem_drift:{mem['drift_bytes']}"
                    f">{mem['bound_bytes']}")
        except Exception:
            pass
        # shadow-parity breaches (ISSUE 10): a tier whose device/host
        # parity sits below its documented floor must rotate this node
        # out of traffic — serving fast wrong answers is not ready
        try:
            for b in obs.parity_breaches():
                checks["parity_breaches"] += 1
                reasons.append(
                    f"parity_breach:{b['surface']}:{b['tier']}"
                    f"({b['ratio']}<{b['floor']})")
        except Exception:
            pass
        # read-replica freshness (ISSUE 12): a fleet node behind the
        # NORNICDB_READY_MAX_LAG_OPS threshold or mid catch-up must
        # drain — the router (and any load balancer probing this
        # endpoint) stops sending it reads instead of letting it serve
        # answers staler than the documented bound
        fleet = getattr(self.db, "fleet_node", None)
        replica_doc: Optional[Dict[str, Any]] = None
        if fleet is not None:
            checks["replica"] = 1
            checks["replica_not_ready"] = 0
            try:
                for r in fleet.ready_reasons():
                    checks["replica_not_ready"] += 1
                    reasons.append(r)
            except Exception:
                # fail CLOSED: a replica whose freshness verdict cannot
                # be computed (teardown race, bad env) must drain, not
                # keep taking reads it can no longer prove fresh
                checks["replica_not_ready"] += 1
                reasons.append("replica_state_unknown")
            # watermark truth for remote probers (ISSUE 16): the fleet
            # router's lease grants and lag checks read this node's
            # applied seq/epoch off the same probe that carries the
            # ready verdict — no second round-trip
            st = getattr(fleet, "standby", None)
            if st is not None:
                try:
                    replica_doc = {
                        "node": fleet.name,
                        "applied_seq": int(st.applied_seq),
                        "lag_ops": int(st.lag_ops()),
                        "epoch": int(st.epoch),
                        "catching_up": bool(st.catching_up),
                    }
                except Exception:  # noqa: BLE001 — probe stays best-effort
                    replica_doc = None
        # keep the SLO sample ring warm from the probe cadence (the
        # engine is scrape-driven; kubelet-style periodic readiness
        # probes give it a steady clock even with /metrics unscraped)
        try:
            obs.get_slo_engine().tick()
        except Exception:
            pass
        if reasons:
            doc = {"status": "degraded", "reasons": sorted(reasons),
                   "checks": checks}
            if replica_doc is not None:
                doc["replica"] = replica_doc
            return 503, doc
        doc = {"status": "ready", "checks": checks}
        if replica_doc is not None:
            doc["replica"] = replica_doc
        return 200, doc

    def _debug_profile(self, payload: Dict[str, Any]) -> Tuple[int, Any]:
        """Run one Cypher statement under cProfile; return wall time and
        the top frames by cumulative time."""
        import cProfile
        import pstats

        if not isinstance(payload, dict):
            return 400, {"error": "JSON object body required"}
        statement = str(payload.get("statement") or "")
        if not statement:
            return 400, {"error": "statement required"}
        params = payload.get("parameters") or {}
        try:
            repeat = int(payload.get("repeat", 1))
        except (TypeError, ValueError):
            return 400, {"error": "repeat must be an integer"}
        repeat = max(1, min(repeat, 1000))
        executor = self.db.executor
        prof = cProfile.Profile()
        t0 = time.perf_counter()
        prof.enable()
        try:
            for _ in range(repeat):
                result = executor.execute(statement, params)
        except Exception as exc:
            # caller's statement failed: client error, not a server
            # fault (the finally disables the profiler)
            return 400, {"error": f"{type(exc).__name__}: {exc}"[:400]}
        finally:
            prof.disable()
        wall_ms = (time.perf_counter() - t0) * 1e3
        stats = pstats.Stats(prof)
        frames = []
        for func, (cc, nc, tt, ct, _callers) in sorted(
                stats.stats.items(), key=lambda kv: -kv[1][3])[:25]:
            filename, line, name = func
            frames.append({
                "function": f"{filename.rsplit('/', 1)[-1]}:{line}({name})",
                "calls": nc,
                "tottime_ms": round(tt * 1e3, 3),
                "cumtime_ms": round(ct * 1e3, 3),
            })
        return 200, {
            "statement": statement,
            "repeat": repeat,
            "wall_ms": round(wall_ms, 3),
            "rows": result.n_rows,
            "top_frames": frames,
        }

    def _status(self) -> Dict[str, Any]:
        dbs: List[str] = [self.default_database]
        if self.database_manager is not None:
            dbs = [d.name for d in self.database_manager.list_databases()]
        doc = {
            "server": SERVER_NAME, "version": API_VERSION,
            "databases": dbs,
            "counts": {"nodes": self.db.storage.count_nodes(),
                       "edges": self.db.storage.count_edges()},
        }
        svc = self.db._search  # don't force an index build from /status
        if svc is not None:
            doc["search"] = {
                "indexed_docs": svc.stats.indexed_docs,
                "indexed_vectors": svc.stats.indexed_vectors,
                "strategy": svc.stats.strategy,
            }
            if svc.stats.last_timings:  # NORNICDB_TPU_SEARCH_DIAG set
                doc["search"]["last_timings_ms"] = {
                    k: round(v, 3) for k, v in svc.stats.last_timings.items()
                }
        return doc

    def _login(self, payload: Dict[str, Any]) -> Tuple[int, Any]:
        if self.authenticator is None:
            raise HTTPError(400, "Neo.ClientError.Request.Invalid",
                            "auth disabled")
        try:
            token = self.authenticator.login(
                payload.get("username", ""), payload.get("password", ""))
        except AuthError as e:
            self.audit.record(AUTH, "login", actor=payload.get("username", ""),
                              success=False)
            raise HTTPError(401, "Neo.ClientError.Security.Unauthorized", str(e))
        self.audit.record(AUTH, "login", actor=payload.get("username", ""))
        return 200, {"token": token}

    # -- Neo4j transactional HTTP API ------------------------------------

    def _db_routes(self, method: str, segments: List[str],
                   payload: Dict[str, Any],
                   username: Optional[str]) -> Tuple[int, Any]:
        database = segments[1]
        if segments[2] != "tx":
            raise HTTPError(404, "Neo.ClientError.Request.Invalid", "unknown route")
        statements = payload.get("statements", [])
        writes = any(_is_write(s.get("statement", "")) for s in statements)
        self.authorize(username, database, WRITE if writes else READ)

        # POST /db/{name}/tx/commit — one-shot
        if len(segments) == 4 and segments[3] == "commit":
            executor = self.executor_for(database)
            if writes:
                self.metrics.inc("cypher_writes_total")
                self.audit.record(DATA_WRITE, "cypher", actor=username or "",
                                  database=database)
            return 200, self._run_statements(executor, statements,
                                               database=database)

        # POST /db/{name}/tx — open explicit tx
        if len(segments) == 3 and method == "POST":
            tx_id = uuid.uuid4().hex[:16]
            storage = self.storage_for(database)
            tx = self.tx_manager.begin(tx_id, storage)
            from nornicdb_tpu.query.executor import CypherExecutor

            ex = CypherExecutor(tx)
            with self._lock:
                self._tx_executors[tx_id] = ex
            result = self._run_statements(ex, statements,
                                          database=database)
            result["commit"] = f"/db/{database}/tx/{tx_id}/commit"
            result["transaction"] = {"id": tx_id}
            return 201, result

        # /db/{name}/tx/{txid}[/commit]
        tx_id = segments[3]
        tx = self.tx_manager.get(tx_id)
        with self._lock:
            ex = self._tx_executors.get(tx_id)
        if tx is None or ex is None:
            raise HTTPError(404, "Neo.ClientError.Transaction.TransactionNotFound",
                            f"transaction {tx_id} not found")
        if len(segments) == 5 and segments[4] == "commit":
            result = self._run_statements(ex, statements,
                                          database=database)
            self.tx_manager.commit(tx_id)
            with self._lock:
                self._tx_executors.pop(tx_id, None)
            return 200, result
        if method == "DELETE":
            self.tx_manager.rollback(tx_id)
            with self._lock:
                self._tx_executors.pop(tx_id, None)
            return 200, {"results": [], "errors": []}
        if method == "POST":
            return 200, self._run_statements(ex, statements,
                                           database=database)
        raise HTTPError(405, "Neo.ClientError.Request.Invalid", "bad method")

    def _run_statements(self, executor, statements,
                        database: Optional[str] = None) -> Dict[str, Any]:
        results, errors = [], []
        for stmt in statements:
            q = stmt.get("statement", "")
            params = stmt.get("parameters", {}) or {}
            try:
                if database is not None and self.database_manager is not None:
                    # per-db rate limits + result caps (reference:
                    # pkg/multidb limits.go + enforcement.go)
                    self.database_manager.enforce_query(database, _is_write(q))
                r = executor.execute(q, params)
                if database is not None and self.database_manager is not None:
                    self.database_manager.truncate_result(database, r)
            except Exception as e:  # noqa: BLE001 — per-statement errors
                errors.append({"code": _http_error_code(e), "message": str(e)})
                break  # Neo4j stops at first error
            results.append({
                "columns": r.columns,
                "data": [{"row": [_jsonable(v) for v in row], "meta": []}
                         for row in r.rows],
                "stats": r.stats.to_dict() if hasattr(r.stats, "to_dict") else {},
            })
        # the cypher tx path has no audit serve chokepoint — the
        # per-tenant request still counts, once per tx (ISSUE 18)
        _tenant.record_served("http", "host")
        return {"results": results, "errors": errors}

    def _search_response_bytes(self, body: bytes, headers) -> bytes:
        """Serve POST /nornicdb/search from the response-bytes cache,
        computing + storing on miss. Keyed on (Authorization, body) so a
        differently-privileged caller can never ride another's entry;
        generation-validated against the search result cache so any
        index mutation invalidates (reference: searchResultCache
        semantics, search.go:88-92)."""
        svc = self.db.search
        gen = svc._result_cache.generation
        key = (headers.get("Authorization", ""), body)
        hit = self._search_wire.get(key)
        if hit is not None and hit[0] == gen:
            self.metrics.inc("search_requests_total")
            _SEARCH_CACHED_SERVED.inc()
            # the pre-bound child skips record_served; per-tenant
            # attribution still counts the hit (ISSUE 18)
            _tenant.record_served("hybrid", "cached")
            return hit[1]
        # admission verdict AFTER the cache probe (ISSUE 15): a
        # byte-fresh hit is pure goodput and is never shed — only a
        # MISS (real device/storage work) passes the controller
        _adm.check("http", _adm.lane())
        status, payload = self.route("POST", "/nornicdb/search", body,
                                     headers)
        if status != 200:
            raise HTTPError(status, "Neo.ClientError.Request.Invalid",
                            str(payload)[:200])
        data = json.dumps(payload, default=_json_default).encode()
        self._search_wire.put(key, (gen, data))
        return data

    def _graphql_response_bytes(self, body: bytes, headers) -> bytes:
        """Serve POST /graphql from the response-bytes cache. Only
        query-kind documents are stored (mutations always execute), and
        entries are validated against the graph-mutation generation, so
        a write through ANY surface invalidates."""
        gen = self._graph_gen.gen
        key = (headers.get("Authorization", ""), body)
        hit = self._graphql_wire.get(key)
        if hit is not None and hit[0] == gen:
            return hit[1]
        # miss-only admission verdict: cache hits are never shed
        _adm.check("http", _adm.lane())
        status, payload = self.route("POST", "/graphql", body, headers)
        if status != 200:
            raise HTTPError(status, "Neo.ClientError.Request.Invalid",
                            str(payload)[:200])
        data = json.dumps(payload, default=_json_default).encode()
        try:
            from nornicdb_tpu.api.graphql import GraphQLAPI

            doc = json.loads(body)
            kind = GraphQLAPI.operation_kind(
                doc.get("query", ""), doc.get("operationName"))
        except Exception:
            kind = "mutation"  # unparseable: never cache
        if (kind == "query" and isinstance(payload, dict)
                and not payload.get("errors")):
            # gen was read BEFORE execution: a write racing the compute
            # leaves a stale-gen entry the next get rejects
            self._graphql_wire.put(key, (gen, data))
        return data

    # -- REST convenience API --------------------------------------------

    def _nornicdb_routes(self, method: str, segments: List[str],
                         payload: Dict[str, Any], query: Dict[str, str],
                         username: Optional[str]) -> Tuple[int, Any]:
        database = query.get("db", self.default_database)
        action = segments[1] if len(segments) > 1 else ""

        if action == "search" and method == "POST":
            self.authorize(username, database, READ)
            self.metrics.inc("search_requests_total")
            q = payload.get("query", "")
            limit = int(payload.get("limit", 10))
            kw: Dict[str, Any] = {}
            if payload.get("mode"):
                mode = str(payload["mode"])
                if mode not in ("hybrid", "text", "vector"):
                    # the openapi enum is the contract: a typo'd mode
                    # must be a 400, not a silently empty result set
                    raise HTTPError(
                        400, "Neo.ClientError.Request.InvalidFormat",
                        "mode must be one of hybrid, text, vector")
                kw["mode"] = mode
            # weighted RRF (reference: Service.Search weighted fusion):
            # [lexical, vector] source weights, validated here so a bad
            # body is a 400, not a device-path error
            w = payload.get("weights")
            if w is not None:
                if (not isinstance(w, (list, tuple)) or len(w) != 2
                        or not all(isinstance(x, (int, float)) for x in w)):
                    raise HTTPError(
                        400, "Neo.ClientError.Request.InvalidFormat",
                        "weights must be [lexical_weight, vector_weight]")
                kw["weights"] = (float(w[0]), float(w[1]))
            results = self.db.search.search(q, limit=limit, **kw)
            # raw results: _reply's json default converts lazily
            return 200, {"results": results}

        if action == "similar" and method == "POST":
            self.authorize(username, database, READ)
            node_id = payload.get("node_id", "")
            limit = int(payload.get("limit", 10))
            results = self.db.search.similar(node_id, limit=limit)
            return 200, {"results": results}

        if action == "graph_search" and method == "POST":
            # fused traverse-then-rank (query/device_graph.py): expand
            # 1-2 hops from the anchor, rank the distinct frontier by
            # cosine similarity — one device dispatch when gated on
            self.authorize(username, database, READ)
            anchor = payload.get("anchor_id", "")
            vec = payload.get("vector")
            hops = payload.get("hops")
            if not anchor or not isinstance(vec, list) or not vec \
                    or not isinstance(hops, list) or not hops:
                raise HTTPError(
                    400, "Neo.ClientError.Request.InvalidFormat",
                    "graph_search needs anchor_id, hops and vector")
            limit = int(payload.get("limit", 10))
            try:
                hits = self.db.graph_vector_search(
                    anchor, hops, vec, k=limit)
            except ValueError as exc:
                raise HTTPError(
                    400, "Neo.ClientError.Request.InvalidFormat",
                    str(exc))
            return 200, {"results": [
                {"node_id": nid, "score": score} for nid, score in hits]}

        if action == "store" and method == "POST":
            self.authorize(username, database, WRITE)
            node = self.db.store(
                payload.get("content", ""),
                labels=payload.get("labels"),
                properties=payload.get("properties"),
                node_id=payload.get("id"),
                embedding=payload.get("embedding"),
            )
            self.audit.record(DATA_WRITE, "store", actor=username or "",
                              database=database, target=node.id)
            return 201, {"id": node.id}

        if action == "decay" and method == "GET":
            self.authorize(username, database, READ)
            scores = self.db.decay.scores()
            return 200, {"scores": [
                {"node_id": s.node_id, "score": s.score, "tier": s.tier}
                for s in scores]}

        if action == "embed" and method == "POST":
            self.authorize(username, database, WRITE)
            if self.db._embedder is None:
                raise HTTPError(400, "Neo.ClientError.Request.Invalid",
                                "no embedder configured")
            vectors = self.db._embedder.embed_batch(payload.get("texts", []))
            return 200, {"embeddings": [list(map(float, v)) for v in vectors]}

        if action == "gdpr" and len(segments) > 2:
            from nornicdb_tpu.retention import gdpr_delete, gdpr_export

            prop = payload.get("property", "")
            value = payload.get("value")
            if segments[2] == "export" and method == "POST":
                self.authorize(username, database, READ)
                self.audit.record(GDPR, "export", actor=username or "")
                return 200, gdpr_export(self.db.storage, prop, value)
            if segments[2] == "delete" and method == "POST":
                self.authorize(username, database, ADMIN)
                n = gdpr_delete(self.db.storage, prop, value)
                self.audit.record(GDPR, "delete", actor=username or "",
                                  details={"deleted": n})
                return 200, {"deleted": n}

        raise HTTPError(404, "Neo.ClientError.Request.Invalid",
                        f"no route /nornicdb/{action}")

    # -- qdrant-compatible REST ------------------------------------------

    @property
    def qdrant(self):
        return self.db.qdrant_compat

    @property
    def graphql(self):
        if getattr(self, "_graphql", None) is None:
            from nornicdb_tpu.api.graphql import GraphQLAPI

            self._graphql = GraphQLAPI(self.db)
        return self._graphql

    @property
    def heimdall(self):
        """Heimdall manager + Bifrost, lazily stood up with the default
        in-process JAX SLM registered (reference: heimdall wiring in
        server.New, server.go:921)."""
        with self._lock:
            if getattr(self, "_heimdall", None) is None:
                from nornicdb_tpu.heimdall import (
                    Bifrost, Manager, ModelSpec,
                )
                from nornicdb_tpu.heimdall.model import DecoderConfig

                mgr = Manager()
                mgr.register(ModelSpec(
                    name="heimdall-slm", backend="jax",
                    options={"cfg": DecoderConfig.tiny()}))
                mgr.bifrost = Bifrost()
                self._heimdall = mgr
            return self._heimdall

    def _qdrant_snapshot_dir(self) -> str:
        import tempfile

        data_dir = getattr(self.db, "_data_dir", None)
        return (os.path.join(data_dir, "qdrant-snapshots") if data_dir
                else os.path.join(tempfile.gettempdir(),
                                  "nornicdb-qdrant-snapshots"))

    def _qdrant_routes(self, method: str, segments: List[str],
                       payload: Dict[str, Any],
                       query: Dict[str, str]) -> Tuple[int, Any]:
        """Qdrant REST wire format: every response is
        {"result": ..., "status": "ok", "time": seconds}."""
        from nornicdb_tpu.api.qdrant import QdrantError

        t0 = time.time()

        def ok(result: Any, status: int = 200) -> Tuple[int, Any]:
            return status, {"result": result, "status": "ok",
                            "time": time.time() - t0}

        try:
            q = self.qdrant
            if len(segments) == 1 and method == "GET":
                return ok({"collections": [
                    {"name": n} for n in q.list_collections()
                ]})
            if segments[1:] == ["aliases"] and method == "GET":
                return ok({"aliases": q.list_aliases()})
            name = segments[1] if len(segments) > 1 else ""
            if len(segments) == 2:
                if method == "PUT":
                    return ok(q.create_collection(
                        name, payload.get("vectors")))
                if method == "DELETE":
                    return ok(q.delete_collection(name))
                if method == "GET":
                    return ok(q.get_collection(name))
            if segments[1:] == ["aliases"] and method == "POST":
                # upstream POST /collections/aliases ChangeAliases body
                actions = []
                for act in payload.get("actions", []):
                    if "create_alias" in act:
                        a = act["create_alias"]
                        actions.append({"create": {
                            "alias": a.get("alias_name", ""),
                            "collection": a.get("collection_name", "")}})
                    elif "rename_alias" in act:
                        a = act["rename_alias"]
                        actions.append({"rename": {
                            "old": a.get("old_alias_name", ""),
                            "new": a.get("new_alias_name", "")}})
                    elif "delete_alias" in act:
                        actions.append({"delete": {
                            "alias": act["delete_alias"].get(
                                "alias_name", "")}})
                return ok(q.update_aliases(actions))
            if len(segments) == 3 and segments[2] == "aliases" \
                    and method == "GET":
                return ok({"aliases": q.list_aliases(name)})
            if len(segments) >= 3 and segments[2] == "snapshots":
                snap_dir = self._qdrant_snapshot_dir()
                if method == "POST" and len(segments) == 3:
                    return ok(q.create_snapshot(name, snap_dir))
                if method == "GET" and len(segments) == 3:
                    return ok(q.list_snapshots(name, snap_dir))
                if method == "DELETE" and len(segments) == 4:
                    return ok(q.delete_snapshot(name, segments[3],
                                                snap_dir))
                if method == "PUT" and len(segments) == 5 \
                        and segments[4] == "recover":
                    return ok({"restored": q.recover_snapshot(
                        name, segments[3], snap_dir)})
            if len(segments) >= 3 and segments[2] == "points":
                action = segments[3] if len(segments) > 3 else ""
                if method == "PUT" and not action:
                    n = q.upsert_points(name, payload.get("points", []))
                    # write path has no audit serve chokepoint — the
                    # per-tenant request (and its rate window) still
                    # counts the bulk upsert (ISSUE 18)
                    _tenant.record_served("qdrant", "host")
                    return ok({"operation_id": n, "status": "completed"})
                if method == "POST" and not action:
                    return ok(q.retrieve_points(
                        name, payload.get("ids", []),
                        with_payload=payload.get("with_payload", True),
                        with_vector=payload.get("with_vector", False)))
                if method == "POST" and action == "search":
                    return ok(q.search_points(
                        name, payload.get("vector", []),
                        limit=int(payload.get("limit", 10)),
                        with_payload=payload.get("with_payload", True),
                        with_vector=payload.get("with_vector", False),
                        score_threshold=payload.get("score_threshold"),
                        query_filter=payload.get("filter")))
                if method == "POST" and action == "query":
                    # universal query API subset: nearest by raw vector
                    qv = payload.get("query")
                    if isinstance(qv, dict):
                        qv = qv.get("nearest")
                    pts = q.search_points(
                        name, qv or [],
                        limit=int(payload.get("limit", 10)),
                        with_payload=payload.get("with_payload", True),
                        with_vector=payload.get("with_vector", False),
                        query_filter=payload.get("filter"))
                    return ok({"points": pts})
                if method == "POST" and action == "delete":
                    n = q.delete_points(
                        name,
                        payload.get("points", payload.get("ids", [])))
                    return ok({"operation_id": n, "status": "completed"})
                if method == "POST" and action == "count":
                    return ok({"count": q.count_points(name)})
                if method == "POST" and action == "scroll":
                    return ok(q.scroll_points(
                        name,
                        offset=payload.get("offset"),
                        limit=int(payload.get("limit", 10)),
                        with_payload=payload.get("with_payload", True),
                        with_vector=payload.get("with_vector", False)))
        except QdrantError as e:
            return e.status, {"status": {"error": str(e)},
                              "time": time.time() - t0}
        raise HTTPError(404, "Neo.ClientError.Request.Invalid",
                        f"no qdrant route {method} /{'/'.join(segments)}")

    # -- heimdall --------------------------------------------------------

    def _stream_bifrost(self, handler, idle_timeout: float = 10.0) -> None:
        """Stream Bifrost events as SSE until the client disconnects or
        the stream is idle past idle_timeout. Auth runs first — the feed
        carries tool-call args and must not be weaker than other routes."""
        from urllib.parse import parse_qs as _pq, urlparse as _up

        try:
            username = self.authenticate(handler.headers)
            self.authorize(username, self.default_database, READ)
        except (AuthError, PermissionDenied, HTTPError) as e:
            status = getattr(e, "status", 401)
            handler._reply(status if isinstance(status, int) else 401,
                           {"errors": [{"message": str(e)}]})
            return
        q = {k: v[0] for k, v in _pq(_up(handler.path).query).items()}
        try:
            idle = min(max(float(q.get("idle_timeout", idle_timeout)),
                           0.1), 120.0)
        except (TypeError, ValueError):
            handler._reply(400, {"errors": [
                {"message": "idle_timeout must be a number"}]})
            return
        bifrost = self.heimdall.bifrost
        sid = bifrost.subscribe()
        try:
            handler.close_connection = True  # streamed body has no length
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.send_header("Cache-Control", "no-cache")
            handler.send_header("Connection", "close")
            handler.end_headers()
            handler.wfile.write(b": connected\n\n")
            handler.wfile.flush()
            for msg in bifrost.events(sid, timeout=idle):
                handler.wfile.write(bifrost.sse(msg).encode())
                handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            bifrost.unsubscribe(sid)

    def _chat_completions(self, payload: Dict[str, Any],
                          username: Optional[str]) -> Tuple[int, Any]:
        """OpenAI-compatible /v1/chat/completions."""
        messages = payload.get("messages") or []
        result = self.heimdall.chat(
            messages,
            model=payload.get("model"),
            max_tokens=int(payload.get("max_tokens", 256)),
            temperature=float(payload.get("temperature", 0.0)),
            user=username,
        )
        now = int(time.time())
        return 200, {
            "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion",
            "created": now,
            "model": result.model,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": result.text},
                "finish_reason": "stop",
            }],
            "usage": _usage(messages, result.text),
        }

    def _heimdall_routes(self, method: str, segments: List[str],
                         payload: Dict[str, Any],
                         username: Optional[str]) -> Tuple[int, Any]:
        action = segments[1] if len(segments) > 1 else ""
        mgr = self.heimdall
        if action == "models" and method == "GET":
            return 200, {"models": [
                {"name": s.name, "backend": s.backend, "loaded": s.loaded,
                 "memory_bytes": s.memory_bytes}
                for s in mgr.models()
            ]}
        if action == "generate" and method == "POST":
            r = mgr.generate(
                payload.get("prompt", ""),
                model=payload.get("model"),
                max_tokens=int(payload.get("max_tokens", 256)),
                temperature=float(payload.get("temperature", 0.0)),
                user=username,
            )
            return 200, {"text": r.text, "model": r.model,
                         "took_ms": r.took_ms}
        if action == "tools" and method == "POST":
            r = mgr.generate_with_tools(
                payload.get("prompt", ""), self.mcp,
                model=payload.get("model"),
                max_rounds=int(payload.get("max_rounds", 4)),
                max_tokens=int(payload.get("max_tokens", 256)),
                user=username,
            )
            return 200, {"text": r.text, "model": r.model,
                         "tool_calls": r.tool_calls, "took_ms": r.took_ms}
        raise HTTPError(404, "Neo.ClientError.Request.Invalid",
                        f"no heimdall route {method} /{'/'.join(segments)}")

    # -- admin -----------------------------------------------------------

    def _admin_routes(self, method: str, segments: List[str],
                      payload: Dict[str, Any],
                      username: Optional[str]) -> Tuple[int, Any]:
        self.authorize(username, "system", ADMIN)
        action = segments[1] if len(segments) > 1 else ""

        if action == "traces" and method == "GET":
            # slow-request ring buffer: full span trees of the most
            # recent requests over NORNICDB_OBS_SLOW_MS (default 0 =
            # every request, ring-bounded). /admin/traces/slowest ranks
            # by duration instead of recency.
            if len(segments) > 2 and segments[2] == "slowest":
                return 200, {"slow_ms": obs.TRACES.slow_ms,
                             "recorded": obs.TRACES.recorded,
                             "traces": obs.TRACES.slowest(limit=10)}
            return 200, {"slow_ms": obs.TRACES.slow_ms,
                         "recorded": obs.TRACES.recorded,
                         "traces": obs.TRACES.snapshot(limit=50)}

        if action == "telemetry" and method == "GET":
            # include_empty: brand-new/idle histogram series report
            # count 0 with null percentiles — never a raise, never a
            # silent hole in the series list
            doc: Dict[str, Any] = {
                "latency": obs.latency_summary(include_empty=True),
                "compile_universe": obs.compile_universe(),
                "resources": obs.resource_snapshot(),
                # stage decomposition + queueing fraction per surface:
                # "slow because queued" vs "slow because compute" is
                # one query here, not a histogram-math exercise
                "stages": obs.stage_summary(),
                # per-query device cost: flops/bytes per (kind, index),
                # the pricing admission control / routing will consume
                "cost": obs.cost_summary(),
                # serving-tier truth (ISSUE 10): which ladder rung
                # answered (tier mix) and the shadow-parity state
                "tiers": obs.tier_mix(),
                "parity": obs.audit_summary(),
                # the admission actuator's verdict + lane state
                # (ISSUE 15): same block /admin/scheduler serves
                "scheduler": _adm.scheduler_summary(),
                "rate_limiter_clients":
                    self.rate_limiter.tracked_clients(),
                # per-tenant truth (ISSUE 18): top-K by cost with the
                # attribution-completeness and noisy-neighbor state
                "tenants": obs.tenants_summary(),
                # device truth (ISSUE 20): measured per-kind roofline
                # (effective FLOPs/s, bytes/s, padding efficiency), the
                # calibrated compile split and the memory ledger
                "device": obs.device_summary(),
            }
            svc = self.db._search  # no index build from a telemetry read
            if svc is not None:
                doc["microbatch"] = svc.microbatch_stats()
            return 200, doc

        if action == "tenants" and method == "GET":
            # per-tenant rollup (ISSUE 18): requests/qps/p99/tier mix/
            # sheds/degrades + the cumulative cost meter, top-K by
            # cost, with attribution completeness and the
            # noisy-neighbor detector's window state
            top = None
            if len(segments) > 2 and segments[2].isdigit():
                top = int(segments[2])  # /admin/tenants/<top>
            return 200, obs.tenants_summary(top=top)

        if action == "scheduler" and method == "GET":
            # the admission-control actuator (ISSUE 15): per-lane
            # queue/in-flight depth + drain rates, deadline-miss
            # counters, shed totals and the current admission verdict
            return 200, _adm.scheduler_summary()

        if action == "device" and method == "GET":
            # device truth (ISSUE 20): the calibration roofline per
            # dispatch kind (measured seconds joined against analytic
            # FLOPs/bytes), per-bucket service-time models with the
            # compile/execute split, unexpected-recompile count, and
            # the device-memory ledger reconciliation
            return 200, obs.device_summary()

        if action == "degrades" and method == "GET":
            # the unified degrade ledger (ISSUE 10): structured
            # (from_tier, to_tier, reason, versions) records of every
            # ladder step-down, newest first, plus a reason rollup
            limit = 100
            if len(segments) > 2 and segments[2].isdigit():
                limit = int(segments[2])  # /admin/degrades/<limit>
            doc = dict(obs.degrade_summary())
            doc["degrades"] = obs.degrade_snapshot(limit=limit)
            return 200, doc

        if action == "events" and method == "GET":
            # the unified incident timeline (ISSUE 13): degrades,
            # drains/admits, failovers, quarantines and SLO breaches
            # in one causally-ordered, trace-id-linked stream
            limit = 100
            if len(segments) > 2 and segments[2].isdigit():
                limit = int(segments[2])  # /admin/events/<limit>
            doc = dict(obs.event_summary())
            doc["events"] = obs.event_snapshot(limit=limit)
            return 200, doc

        if action == "fleet" and method == "GET":
            if len(segments) > 2 and segments[2] == "state":
                # this node's registry snapshot in the JSON-safe wire
                # shape — the scrape endpoint remote fleet aggregators
                # pull (obs.fleet.http_state_source)
                from nornicdb_tpu.obs import fleet as _fleet
                from nornicdb_tpu.obs.metrics import dump_state

                return 200, {"state": _fleet.state_to_jsonable(
                    dump_state())}
            # the fleet telemetry aggregator (ISSUE 13): merged
            # worker/plane/replica truth — lag in ops AND seconds,
            # tier mix, failovers, source health, incident rollup
            return 200, obs.fleet_summary()

        if action == "slo":
            engine = obs.get_slo_engine()
            if method == "GET":
                engine.tick()
                return 200, engine.status()
            if method == "POST" and len(segments) > 2 \
                    and segments[2] == "dump":
                # manual flight-recorder capture (same artifact a
                # breach writes automatically)
                path = engine.dump(reason="manual")
                self.audit.record(ADMIN_ACTION, "slo_dump",
                                  actor=username or "", target=path)
                return 200, {"path": path}

        if action == "databases":
            if self.database_manager is None:
                raise HTTPError(400, "Neo.ClientError.Request.Invalid",
                                "multi-database not enabled")
            if method == "GET":
                return 200, {"databases": [
                    {"name": d.name, "status": d.status, "default": d.default}
                    for d in self.database_manager.list_databases()]}
            if method == "POST":
                name = payload.get("name", "")
                self.database_manager.create_database(name)
                self.audit.record(ADMIN_ACTION, "create_database",
                                  actor=username or "", target=name)
                return 201, {"name": name}
            if method == "DELETE" and len(segments) > 2:
                self.database_manager.drop_database(segments[2])
                self.audit.record(ADMIN_ACTION, "drop_database",
                                  actor=username or "", target=segments[2])
                return 200, {"dropped": segments[2]}

        if action == "users":
            # reference: AdminUsers.tsx over the users admin API
            if self.authenticator is None:
                raise HTTPError(400, "Neo.ClientError.Request.Invalid",
                                "auth not enabled")
            a = self.authenticator
            if method == "GET":
                return 200, {"users": [
                    {"username": u,
                     "roles": list(a._users[u].roles),
                     "suspended": a._users[u].suspended}
                    for u in a.list_users()]}
            if method == "POST":
                name = payload.get("username", "")
                pw = payload.get("password", "")
                if not name or not pw:
                    raise HTTPError(400, "Neo.ClientError.Request.Invalid",
                                    "username and password required")
                a.create_user(name, pw, roles=payload.get("roles"))
                self.audit.record(ADMIN_ACTION, "create_user",
                                  actor=username or "", target=name)
                return 201, {"username": name}
            if method == "DELETE" and len(segments) > 2:
                a.delete_user(segments[2])
                self.audit.record(ADMIN_ACTION, "delete_user",
                                  actor=username or "", target=segments[2])
                return 200, {"deleted": segments[2]}
            if method == "PUT" and len(segments) > 2:
                target = segments[2]
                if "suspended" in payload:
                    a.suspend_user(target, bool(payload["suspended"]))
                if "password" in payload:
                    a.set_password(target, payload["password"])
                for role in payload.get("grant_roles", []):
                    a.grant_role(target, role)
                for role in payload.get("revoke_roles", []):
                    a.revoke_role(target, role)
                self.audit.record(ADMIN_ACTION, "update_user",
                                  actor=username or "", target=target)
                return 200, {"username": target}

        if action == "backup" and method == "POST":
            target = payload.get("path", "")
            if not target:
                raise HTTPError(400, "Neo.ClientError.Request.Invalid",
                                "path required")
            n = _backup(self.db.storage, target)
            self.audit.record(ADMIN_ACTION, "backup", actor=username or "",
                              details={"records": n})
            return 200, {"records": n, "path": target}

        if action == "flags":
            from nornicdb_tpu.config import flags

            if method == "GET":
                return 200, flags.all()
            if method == "PUT":
                for k, v in payload.items():
                    flags.set(k, v)
                return 200, flags.all()

        raise HTTPError(404, "Neo.ClientError.Request.Invalid",
                        f"no route /admin/{action}")


_WRITE_RE = re.compile(
    r"\b(CREATE|MERGE|DELETE|DETACH|SET|REMOVE|DROP|LOAD\s+CSV)\b", re.I)


def _usage(messages, completion: str) -> Dict[str, int]:
    """OpenAI-wire usage block (~4 chars/token heuristic). content may
    be explicitly null for assistant tool-call turns."""
    prompt_tokens = sum(
        len(m.get("content") or "") for m in messages) // 4
    completion_tokens = len(completion) // 4
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def _is_write(query: str) -> bool:
    return bool(_WRITE_RE.search(query))


def _http_error_code(e: Exception) -> str:
    from nornicdb_tpu.errors import CypherSyntaxError
    from nornicdb_tpu.multidb import DatabaseLimitExceeded

    if isinstance(e, CypherSyntaxError):
        return "Neo.ClientError.Statement.SyntaxError"
    if isinstance(e, DatabaseLimitExceeded):
        # distinct, retryable class: clients must be able to tell a
        # throttle from a genuine execution failure
        return "Neo.ClientError.Request.RateLimited"
    return "Neo.DatabaseError.Statement.ExecutionFailed"


def _json_default(value: Any) -> Any:
    """json.dumps default hook: called only for values the C encoder
    can't serialize, so the common all-plain-types response pays zero
    conversion cost."""
    from nornicdb_tpu.storage.types import Edge, Node

    if isinstance(value, Node):
        return {"id": value.id, "labels": value.labels,
                "properties": _jsonable(value.properties)}
    if isinstance(value, Edge):
        return {"id": value.id, "type": value.type,
                "start": value.start_node, "end": value.end_node,
                "properties": _jsonable(value.properties)}
    import numpy as np

    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.floating):
        return float(value)
    if isinstance(value, np.ndarray):
        return value.tolist()
    return str(value)


def _jsonable(value: Any) -> Any:
    from nornicdb_tpu.storage.types import Edge, Node

    if isinstance(value, Node):
        return {"id": value.id, "labels": value.labels,
                "properties": _jsonable(value.properties)}
    if isinstance(value, Edge):
        return {"id": value.id, "type": value.type,
                "start": value.start_node, "end": value.end_node,
                "properties": _jsonable(value.properties)}
    if isinstance(value, dict):
        return {k: _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    try:
        import numpy as np

        if isinstance(value, np.integer):
            return int(value)
        if isinstance(value, np.floating):
            return float(value)
        if isinstance(value, np.ndarray):
            return value.tolist()
    except ImportError:  # pragma: no cover
        pass
    return value


@functools.lru_cache(maxsize=1)
def _browser_html() -> str:
    """The embedded single-page admin browser (nornicdb_tpu/ui/),
    loaded once per process (matches PLAYGROUND_HTML in graphql.py)."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ui", "browser.html")
    with open(path, encoding="utf-8") as f:
        return f.read()


def _backup(storage, target_path: str) -> int:
    """Write a JSONL backup of all nodes+edges (reference:
    badger_backup.go + /admin/backup route)."""
    import os

    os.makedirs(os.path.dirname(os.path.abspath(target_path)), exist_ok=True)

    def _default(v):
        # typed property values (temporal/duration/point) keep their tag
        # so a restore revives them; anything else degrades to str
        from nornicdb_tpu.query.temporal_types import encode_value

        try:
            return encode_value(v)
        except TypeError:
            return str(v)

    n = 0
    tmp = target_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        for node in storage.all_nodes():
            f.write(json.dumps({"kind": "node", **node.to_dict()},
                               default=_default) + "\n")
            n += 1
        for edge in storage.all_edges():
            f.write(json.dumps({"kind": "edge", **edge.to_dict()},
                               default=_default) + "\n")
            n += 1
    os.replace(tmp, target_path)
    return n
