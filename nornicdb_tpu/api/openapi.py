"""OpenAPI 3 description of the HTTP surface + self-contained docs page.

Reference: cmd/swagger-ui (serves interactive API docs for the server).
Zero-egress environment: instead of the CDN-loaded swagger bundle, the
docs page is a single self-contained HTML explorer rendered from
``/openapi.json`` with inline JavaScript — same capability (browse
endpoints, schemas, try-it-out via fetch), no external assets.
"""

from __future__ import annotations

import json
from typing import Any, Dict

from nornicdb_tpu.api.http_server import API_VERSION


def openapi_spec() -> Dict[str, Any]:
    def op(summary, tag, request=None, response=None, params=None):
        out: Dict[str, Any] = {"summary": summary, "tags": [tag],
                               "responses": {"200": {
                                   "description": "OK",
                                   **({"content": {"application/json": {
                                       "schema": response}}}
                                      if response else {})}}}
        if request:
            out["requestBody"] = {"content": {"application/json": {
                "schema": request}}}
        if params:
            out["parameters"] = [
                {"name": n, "in": where, "schema": {"type": t},
                 "required": where == "path"}
                for n, where, t in params]
        return out

    obj = {"type": "object"}
    stmt_req = {"type": "object", "properties": {
        "statements": {"type": "array", "items": {
            "type": "object", "properties": {
                "statement": {"type": "string"},
                "parameters": obj}}}}}

    return {
        "openapi": "3.0.3",
        "info": {"title": "nornicdb-tpu HTTP API",
                 "version": API_VERSION,
                 "description": "Neo4j-compatible transaction API, REST "
                                "search, Qdrant-compatible REST, GraphQL, "
                                "MCP, and ops endpoints."},
        "paths": {
            "/health": {"get": op("Liveness probe", "ops")},
            "/readyz": {"get": op(
                "Readiness probe: 503 while index rebuilds are pending, "
                "changelogs near overrun, or batching queues saturated",
                "ops", response={"type": "object", "properties": {
                    "status": {"type": "string",
                               "enum": ["ready", "degraded"]},
                    "reasons": {"type": "array",
                                "items": {"type": "string"}},
                    "checks": {"type": "object"}}})},
            "/status": {"get": op("Server status + search stats", "ops")},
            "/metrics": {"get": op("Prometheus metrics", "ops")},
            "/admin/slo": {"get": op(
                "SLO budgets + multi-window burn rates per surface "
                "(admin)", "ops", response={"type": "object"})},
            "/admin/degrades": {"get": op(
                "Unified degrade ledger: structured (from_tier, "
                "to_tier, reason, versions) records of every serving "
                "ladder step-down, newest first (admin)", "ops",
                response={"type": "object", "properties": {
                    "recorded": {"type": "integer"},
                    "capacity": {"type": "integer"},
                    "by_reason": {"type": "object"},
                    "degrades": {"type": "array",
                                 "items": {"type": "object"}}}})},
            "/admin/events": {"get": op(
                "Unified incident timeline: causally-ordered, "
                "trace-id-linked degrade/drain/admit/failover/"
                "quarantine/SLO-breach events (admin)", "ops",
                response={"type": "object", "properties": {
                    "recorded": {"type": "integer"},
                    "capacity": {"type": "integer"},
                    "by_kind": {"type": "object"},
                    "events": {"type": "array",
                               "items": {"type": "object"}}}})},
            "/admin/scheduler": {"get": op(
                "Admission-control actuator state: per-lane in-flight "
                "depth + drain rates, deadline-miss counters, shed "
                "totals and the current admission posture (admin)",
                "ops",
                response={"type": "object", "properties": {
                    "posture": {"type": "string",
                                "enum": ["admit", "degrade", "shed",
                                         "shed_hard"]},
                    "lanes": {"type": "object"},
                    "deadline": {"type": "object"},
                    "shed": {"type": "object"},
                    "limits": {"type": "object"}}})},
            "/admin/fleet": {"get": op(
                "Fleet telemetry aggregator: merged worker/plane/"
                "replica registries — per-node lag (ops AND "
                "apply-delay seconds), tier mix, failovers, source "
                "health (admin)", "ops",
                response={"type": "object", "properties": {
                    "sources": {"type": "object"},
                    "workers": {"type": "number"},
                    "replicas": {"type": "object"},
                    "failovers": {"type": "object"},
                    "tiers": {"type": "object"},
                    "events": {"type": "object"}}})},
            "/openapi.json": {"get": op("This document", "ops")},
            "/debug/profile": {"post": op(
                "Profile one Cypher statement (admin)", "ops",
                request={"type": "object", "properties": {
                    "statement": {"type": "string"},
                    "parameters": {"type": "object"},
                    "repeat": {"type": "integer"}}},
                response={"type": "object"})},
            "/auth/login": {"post": op(
                "Exchange credentials for a JWT", "auth",
                request={"type": "object", "properties": {
                    "username": {"type": "string"},
                    "password": {"type": "string"}}},
                response={"type": "object", "properties": {
                    "token": {"type": "string"}}})},
            "/db/{database}/tx/commit": {"post": op(
                "Run Cypher statements in an auto-commit transaction",
                "cypher", request=stmt_req, response=obj,
                params=[("database", "path", "string")])},
            "/db/{database}/tx": {"post": op(
                "Open an explicit transaction", "cypher",
                request=stmt_req, params=[("database", "path",
                                           "string")])},
            "/nornicdb/search": {"post": op(
                "Hybrid search (BM25 + vector + weighted RRF; "
                "device-fused pipeline on large corpora)", "search",
                request={"type": "object", "properties": {
                    "query": {"type": "string"},
                    "limit": {"type": "integer"},
                    "mode": {"type": "string",
                             "enum": ["hybrid", "text", "vector"]},
                    "weights": {
                        "type": "array", "minItems": 2, "maxItems": 2,
                        "items": {"type": "number"},
                        "description": "[lexical, vector] RRF weights"}}},
                response=obj)},
            "/nornicdb/store": {"post": op(
                "Store content (auto-embeds via the queue)", "search",
                request={"type": "object", "properties": {
                    "content": {"type": "string"},
                    "labels": {"type": "array",
                               "items": {"type": "string"}},
                    "properties": obj}},
                response=obj)},
            "/nornicdb/similar": {"post": op(
                "Find nodes similar to an existing node", "search",
                request={"type": "object", "properties": {
                    "node_id": {"type": "string"},
                    "limit": {"type": "integer"}}},
                response=obj)},
            "/nornicdb/graph_search": {"post": op(
                "Fused graph+vector query: expand 1-2 relationship "
                "hops from the anchor, rank the distinct frontier by "
                "cosine similarity (one device dispatch when the graph "
                "plane is enabled)", "search",
                request={"type": "object", "properties": {
                    "anchor_id": {"type": "string"},
                    "hops": {"type": "array", "minItems": 1,
                             "maxItems": 2, "items": {},
                             "description": "relationship types; a "
                             "string means outgoing, [type, 'in'|'out'] "
                             "sets direction"},
                    "vector": {"type": "array",
                               "items": {"type": "number"}},
                    "limit": {"type": "integer"}}},
                response=obj)},
            "/graphql": {"post": op("GraphQL endpoint", "graphql",
                                    request=obj, response=obj)},
            "/mcp": {"post": op("Model Context Protocol endpoint", "mcp",
                                request=obj, response=obj)},
            "/v1/chat/completions": {"post": op(
                "Heimdall chat completions (OpenAI-compatible)",
                "heimdall", request=obj, response=obj)},
            "/collections/{name}/points": {"put": op(
                "Qdrant-compatible point upsert", "qdrant", request=obj,
                params=[("name", "path", "string")])},
            "/collections/{name}/points/search": {"post": op(
                "Qdrant-compatible vector search", "qdrant",
                request=obj, params=[("name", "path", "string")])},
            "/nornicdb/gdpr/export": {"post": op(
                "GDPR subject data export by property match", "gdpr",
                request={"type": "object", "properties": {
                    "property": {"type": "string"},
                    "value": {}}},
                response=obj)},
        },
    }


def docs_page() -> str:
    """Single-file API explorer (no external assets)."""
    spec = json.dumps(openapi_spec())
    return """<!doctype html><html><head><meta charset="utf-8">
<title>nornicdb-tpu API</title><style>
body{font-family:system-ui,sans-serif;margin:0;background:#f7f7f9;color:#1b1b20}
header{background:#20222b;color:#fff;padding:14px 24px;font-size:18px}
main{max-width:960px;margin:24px auto;padding:0 16px}
.ep{background:#fff;border:1px solid #e2e2ea;border-radius:8px;margin:10px 0;overflow:hidden}
.ep>summary{padding:10px 14px;cursor:pointer;display:flex;gap:12px;align-items:center}
.m{font-weight:700;border-radius:4px;padding:2px 10px;color:#fff;font-size:12px;min-width:44px;text-align:center}
.get{background:#2f7d4f}.post{background:#2456a8}.put{background:#9a6b1f}.delete{background:#a83232}
.body{padding:0 14px 14px}.tag{color:#666;font-size:12px;margin-left:auto}
pre{background:#f1f1f6;padding:10px;border-radius:6px;overflow:auto;font-size:12px}
button{background:#20222b;color:#fff;border:0;border-radius:5px;padding:6px 14px;cursor:pointer}
textarea{width:100%;min-height:70px;font-family:monospace;font-size:12px}
</style></head><body><header>nornicdb-tpu HTTP API</header><main id="eps"></main>
<script>
const SPEC = SPEC_JSON;
const root = document.getElementById('eps');
for (const [path, methods] of Object.entries(SPEC.paths)) {
  for (const [method, op] of Object.entries(methods)) {
    const d = document.createElement('details'); d.className = 'ep';
    const hasBody = !!op.requestBody;
    d.innerHTML = `<summary><span class="m ${method}">${method.toUpperCase()}</span>`
      + `<code>${path}</code><span>${op.summary||''}</span>`
      + `<span class="tag">${(op.tags||[]).join(', ')}</span></summary>`
      + `<div class="body">`
      + (hasBody ? `<p>Request schema:</p><pre>${JSON.stringify(op.requestBody.content['application/json'].schema, null, 2)}</pre>`
                   + `<textarea placeholder='{"statements": []}'></textarea><br>` : '')
      + `<button>Try it</button><pre class="out">(no response yet)</pre></div>`;
    d.querySelector('button').onclick = async () => {
      const out = d.querySelector('.out');
      const ta = d.querySelector('textarea');
      try {
        const res = await fetch(path.replaceAll(/\\{[^}]+\\}/g, 'neo4j'), {
          method: method.toUpperCase(),
          headers: {'Content-Type': 'application/json'},
          body: hasBody ? (ta && ta.value || '{}') : undefined});
        const text = await res.text();
        out.textContent = res.status + '\\n' + text.slice(0, 4000);
      } catch (e) { out.textContent = 'error: ' + e; }
    };
    root.appendChild(d);
  }
}
</script></body></html>""".replace("SPEC_JSON", spec)
