"""gRPC API: native search service + qdrant-semantics collections/points.

Reference: pkg/nornicgrpc (native search gRPC with in-tree proto,
search_service.go) and pkg/qdrantgrpc (server.go:277 NewServer,
points_service.go, collections_service.go — collection/point ops
translated onto storage+search; highest-throughput surface in the
reference's e2e bench at 29k ops/s).

Serving path: one ``grpc.aio`` server on a dedicated event-loop thread.
The previous ``grpc.server`` handed every RPC to a ThreadPoolExecutor
worker — at qdrant-search payload sizes that per-RPC thread handoff
(enqueue, wake, GIL churn, response marshal back) dominated the profile
and left the surface an order of magnitude under the reference. Now:

- every handler is a coroutine registered RAW (no deserializer/
  serializer), so the server moves request/response bytes;
- hot reads ride one shared :class:`~nornicdb_tpu.cache.WireCache`
  validated against the owning data plane's write generation (the
  QdrantCompat search-cache generation for qdrant methods, the
  SearchService result-cache generation for native search) — both fed
  by the same storage mutation listeners wired in db.py, so a write on
  ANY surface invalidates cached response bytes;
- misses and point ops run on a small executor where concurrent
  requests coalesce through the compat layer's MicroBatcher (search)
  and BatchCoalescer (upsert convoys) with power-of-two bucketing.

The public lifecycle is unchanged and synchronous (``GrpcServer(db,
port=0).start()`` / ``.stop()``): db.py/cli.py and every test drive it
exactly as before; the event loop is an implementation detail.

Servicers are registered with ``grpc.method_handlers_generic_handler``
so no grpc_tools codegen is needed — messages come from the protoc-
generated ``nornic_pb2`` and handlers are plain methods.
"""

from __future__ import annotations

import asyncio
import contextvars
import errno
import json
import os
import threading
import time
from typing import Optional

import grpc
import numpy as np

from nornicdb_tpu.api.proto import nornic_pb2 as pb
from nornicdb_tpu.api.qdrant import QdrantError


def _unary_raw(fn, req_cls, method, wire=None, gen=None, executor=None,
               resp_cls=None):
    from nornicdb_tpu.api.qdrant_official_grpc import _parse, aio_unary_raw

    # _parse times the FromString as the request's "parse" stage, same
    # as the official-proto surface
    return aio_unary_raw(
        _parse(fn, req_cls), method=method,
        wire=wire, gen=gen, executor=executor, resp_cls=resp_cls)


class SearchServicer:
    """nornic.v1.SearchService — raw vector + hybrid search.

    Concurrent Search RPCs funnel (via the executor) into the search
    service's MicroBatcher: many b=1 queries, one batched device
    dispatch (search/service.py vector_search_candidates)."""

    def __init__(self, db):
        self.db = db

    def Search(self, request):
        t0 = time.time()
        hits = self.db.search.vector_search_candidates(
            np.asarray(list(request.vector), dtype=np.float32),
            k=int(request.limit) or 10,
        )
        return pb.SearchResponse(
            hits=[self._hit(nid, score) for nid, score in hits],
            took_ms=(time.time() - t0) * 1e3,
        )

    def Hybrid(self, request):
        t0 = time.time()
        results = self.db.search.search(
            query=request.query,
            limit=int(request.limit) or 10,
            query_embedding=(
                np.asarray(list(request.vector), dtype=np.float32)
                if request.vector else None
            ),
        )
        hits = []
        for r in results:
            hits.append(pb.Hit(
                node_id=str(r.get("id", "")),
                score=float(r.get("score", 0.0)),
                payload_json=json.dumps(r.get("properties", {}), default=str),
            ))
        return pb.SearchResponse(hits=hits, took_ms=(time.time() - t0) * 1e3)

    def _hit(self, node_id: str, score: float) -> "pb.Hit":
        payload = "{}"
        try:
            node = self.db.storage.get_node(node_id)
            payload = json.dumps(node.properties, default=str)
        except Exception:
            pass
        return pb.Hit(node_id=node_id, score=float(score),
                      payload_json=payload)

    def _stream_search(self, executor):
        """Batched streaming Search (ISSUE 11): one RPC, many queries.
        A high-fanout client streams SearchRequests and reads
        SearchResponses in order; the server gathers each arrival
        burst (one short gather window, MicroBatcher-style) and
        dispatches the whole burst concurrently on the executor, so
        the rows coalesce into one device dispatch below — per-query
        RPC overhead drops to one varint-framed message each way."""
        max_batch = 64
        gather_s = 0.0005
        servicer = self

        def one(data: bytes) -> bytes:
            return servicer.Search(
                pb.SearchRequest.FromString(data)).SerializeToString()

        async def handler(request_iterator, context):
            loop = asyncio.get_running_loop()
            it = request_iterator.__aiter__()
            pending = None
            done = False
            try:
                while not done:
                    if pending is None:
                        pending = asyncio.ensure_future(it.__anext__())
                    try:
                        first = await pending
                    except StopAsyncIteration:
                        return
                    pending = None
                    batch = [first]
                    while len(batch) < max_batch:
                        pending = asyncio.ensure_future(it.__anext__())
                        try:
                            nxt = await asyncio.wait_for(
                                asyncio.shield(pending), gather_s)
                        except asyncio.TimeoutError:
                            break  # burst over; keep pending for later
                        except StopAsyncIteration:
                            pending = None
                            done = True
                            break
                        pending = None
                        batch.append(nxt)
                    outs = await asyncio.gather(*[
                        loop.run_in_executor(
                            executor, contextvars.copy_context().run,
                            one, b)
                        for b in batch])
                    for out in outs:
                        yield out
            finally:
                if pending is not None:
                    pending.cancel()

        return grpc.stream_stream_rpc_method_handler(handler)

    def handlers(self, wire=None, executor=None):
        svc = "nornic.v1.SearchService"
        # cached response bytes validate against the search service's
        # result-cache generation: any index mutation bumps it
        gen = lambda: self.db.search.generation  # noqa: E731
        return grpc.method_handlers_generic_handler(svc, {
            "Search": _unary_raw(self.Search, pb.SearchRequest,
                                 f"/{svc}/Search", wire, gen, executor,
                                 resp_cls=pb.SearchResponse),
            "SearchStream": self._stream_search(executor),
            "Hybrid": _unary_raw(self.Hybrid, pb.HybridRequest,
                                 f"/{svc}/Hybrid", wire, gen, executor,
                                 resp_cls=pb.SearchResponse),
        })


# the whole ok-ack is constant bytes — no message build, no serialize
_ACK_OK = pb.AckResponse(ok=True).SerializeToString()


class QdrantServicer:
    """nornic.v1.QdrantService — qdrant-semantics ops over QdrantCompat."""

    def __init__(self, compat):
        self.compat = compat

    def _ack(self, fn):
        try:
            fn()
            return _ACK_OK
        except QdrantError as e:
            return pb.AckResponse(ok=False, error=str(e))

    def CreateCollection(self, request):
        vectors = {"size": int(request.vector_size),
                   "distance": request.distance or "Cosine"}
        return self._ack(lambda: self.compat.create_collection(
            request.collection, vectors))

    def DeleteCollection(self, request):
        return self._ack(lambda: self.compat.delete_collection(
            request.collection))

    def ListCollections(self, request):
        return pb.ListCollectionsResponse(
            collections=self.compat.list_collections())

    def GetCollection(self, request):
        try:
            info = self.compat.get_collection(request.collection)
        except QdrantError:
            return pb.CollectionInfoResponse(status="not_found")
        vec = info["config"]["params"]["vectors"]
        return pb.CollectionInfoResponse(
            status=info["status"],
            points_count=info["points_count"],
            vector_size=int(vec.get("size", 0)),
            distance=str(vec.get("distance", "Cosine")),
        )

    def Upsert(self, request):
        points = [
            {
                "id": p.id,
                "vector": list(p.vector),
                "payload": json.loads(p.payload_json) if p.payload_json else {},
            }
            for p in request.points
        ]
        # convoy-coalesced: concurrent Upserts merge into one apply
        return self._ack(lambda: self.compat.upsert_points_coalesced(
            request.collection, points))

    def SearchPoints(self, request):
        t0 = time.time()
        hits = self.compat.search_points(
            request.collection,
            list(request.vector),
            limit=int(request.limit) or 10,
            with_payload=request.with_payload,
            with_vector=request.with_vector,
            score_threshold=(
                float(request.score_threshold)
                if request.has_score_threshold else None
            ),
            query_filter=(
                json.loads(request.filter_json)
                if request.filter_json else None
            ),
        )
        return pb.SearchPointsResponse(
            points=[
                pb.ScoredPoint(
                    id=str(h["id"]),
                    score=h.get("score", 0.0),
                    payload_json=json.dumps(h.get("payload", {}), default=str),
                    vector=h.get("vector", []),
                )
                for h in hits
            ],
            took_ms=(time.time() - t0) * 1e3,
        )

    def DeletePoints(self, request):
        return self._ack(lambda: self.compat.delete_points(
            request.collection, list(request.ids)))

    def CountPoints(self, request):
        return pb.CountResponse(count=self.compat.count_points(
            request.collection))

    def handlers(self, wire=None, executor=None):
        svc = "nornic.v1.QdrantService"
        gen = lambda: self.compat.cache_gen  # noqa: E731

        def unary(name, fn, req_cls, resp_cls=None):
            return _unary_raw(fn, req_cls, f"/{svc}/{name}",
                              wire if resp_cls is not None else None,
                              gen, executor, resp_cls=resp_cls)

        return grpc.method_handlers_generic_handler(svc, {
            "CreateCollection": unary(
                "CreateCollection", self.CreateCollection,
                pb.CreateCollectionRequest),
            "DeleteCollection": unary(
                "DeleteCollection", self.DeleteCollection,
                pb.CollectionRequest),
            "ListCollections": unary(
                "ListCollections", self.ListCollections, pb.Empty,
                pb.ListCollectionsResponse),
            "GetCollection": unary(
                "GetCollection", self.GetCollection, pb.CollectionRequest,
                pb.CollectionInfoResponse),
            "Upsert": unary("Upsert", self.Upsert, pb.UpsertRequest),
            "SearchPoints": unary(
                "SearchPoints", self.SearchPoints, pb.SearchPointsRequest,
                pb.SearchPointsResponse),
            "DeletePoints": unary(
                "DeletePoints", self.DeletePoints, pb.DeletePointsRequest),
            "CountPoints": unary(
                "CountPoints", self.CountPoints, pb.CollectionRequest,
                pb.CountResponse),
        })


def _aio_token_interceptor(token: str):
    """Bearer-token auth interceptor: gRPC writes must not be weaker
    than the REST surface's WRITE authorization."""
    import hmac

    class _Interceptor(grpc.aio.ServerInterceptor):
        def __init__(self):
            async def abort(request, context):
                await context.abort(grpc.StatusCode.UNAUTHENTICATED,
                                    "invalid or missing bearer token")

            self._abort = grpc.unary_unary_rpc_method_handler(abort)

        async def intercept_service(self, continuation, details):
            md = dict(details.invocation_metadata or ())
            if hmac.compare_digest(
                md.get("authorization", ""), f"Bearer {token}"
            ):
                return await continuation(details)
            return self._abort

    return _Interceptor()


class GrpcServer:
    """Hosts both services on one port (reference: server.go:328 Start).
    Shares the DB's QdrantCompat with the REST surface so the
    per-collection index caches stay coherent across surfaces.

    Implementation: a ``grpc.aio`` server living on its own event-loop
    thread. Construction binds the port (so ``.address`` is valid before
    ``start()``, as callers expect); ``start()``/``stop()`` submit the
    aio server's lifecycle onto the loop and block until done."""

    def __init__(self, db, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 8, auth_token: Optional[str] = None,
                 snapshot_dir: Optional[str] = None,
                 search_servicer_cls=None, points_servicer_cls=None):
        from concurrent import futures

        from nornicdb_tpu.cache import WireCache

        self.db = db
        if snapshot_dir is None:
            # reference default: ./data/qdrant-snapshots (server.go:184);
            # here snapshots live with the store when one exists
            import tempfile

            data_dir = getattr(db, "_data_dir", None)
            snapshot_dir = (
                os.path.join(data_dir, "qdrant-snapshots") if data_dir
                else os.path.join(tempfile.gettempdir(),
                                  "nornicdb-qdrant-snapshots"))
        self.snapshot_dir = snapshot_dir
        self._auth_token = auth_token
        # one shared response-bytes cache across ALL services/methods of
        # this server — both gRPC surfaces serve hot reads from it
        self.wire_cache = WireCache(name="grpc")
        # miss/mutation work runs here, NOT on the event loop: a storage
        # scan must never stall cache hits, and concurrent point ops
        # coalesce across these threads via the compat layer's batchers
        self._executor = futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="grpc-work")
        # servicer classes are injectable so the wire-plane frontend
        # workers (api/wire_plane.py) can serve the same method surface
        # over broker-backed proxies with worker-optimized hot paths
        self.search_servicer = (search_servicer_cls or SearchServicer)(db)
        self.qdrant_servicer = QdrantServicer(db.qdrant_compat)
        # official qdrant wire contract (qdrant.Collections / qdrant.Points)
        # alongside the native services — reference: pkg/qdrantgrpc serves
        # the upstream proto so official SDKs connect (COMPAT.md)
        from nornicdb_tpu.api.qdrant_official_grpc import (
            OfficialCollectionsServicer,
            OfficialPointsServicer,
            OfficialSnapshotsServicer,
        )

        self.official_collections = OfficialCollectionsServicer(db.qdrant_compat)
        self.official_points = (
            points_servicer_cls or OfficialPointsServicer)(db.qdrant_compat)
        self.official_snapshots = OfficialSnapshotsServicer(
            db.qdrant_compat, self.snapshot_dir)
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._run_loop, daemon=True, name="grpc-aio-loop")
        self._loop_thread.start()
        self._started = False
        self._stopped = False
        self.host = host
        self.port = self._submit(self._build(host, port)).result(30)

    @staticmethod
    def _quiet_poller_eagain(loop, context) -> None:
        # grpcio's aio completion-queue poller is process-global and
        # binds to the first aio loop; when a SECOND aio loop exists in
        # the process (an in-process grpc.aio client — the open-loop
        # bench harness, tests), its cross-loop wakeups surface here as
        # harmless EAGAIN callbacks that would spam stderr per request.
        # Only those are swallowed: any other BlockingIOError errno or
        # exception type still reaches the default handler.
        exc = context.get("exception")
        if (isinstance(exc, BlockingIOError)
                and exc.errno in (errno.EAGAIN, errno.EWOULDBLOCK)):
            return
        loop.default_exception_handler(context)

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.set_exception_handler(self._quiet_poller_eagain)
        try:
            self._loop.run_forever()
        finally:
            try:
                self._loop.close()
            except RuntimeError:
                pass

    def _submit(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _build(self, host: str, port: int) -> int:
        interceptors = (
            [_aio_token_interceptor(self._auth_token)]
            if self._auth_token else []
        )
        server = grpc.aio.server(interceptors=interceptors)
        wire, ex = self.wire_cache, self._executor
        server.add_generic_rpc_handlers((
            self.search_servicer.handlers(wire=wire, executor=ex),
            self.qdrant_servicer.handlers(wire=wire, executor=ex),
            self.official_collections.handlers(wire=wire, executor=ex),
            self.official_points.handlers(wire=wire, executor=ex),
            self.official_snapshots.handlers(executor=ex),
        ))
        self._server = server
        return server.add_insecure_port(f"{host}:{port}")

    def start(self) -> "GrpcServer":
        self._submit(self._server.start()).result(30)
        self._started = True
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        if self._stopped:
            return
        self._stopped = True
        try:
            # unconditional: __init__ already bound the port via
            # _build(), so even a never-started server holds the
            # listening socket until stopped
            self._submit(self._server.stop(grace)).result(30)
        except Exception:
            pass  # a dying loop must not block process shutdown
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._loop_thread.join(timeout=10)
        self._executor.shutdown(wait=False)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"
