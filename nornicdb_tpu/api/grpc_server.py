"""gRPC API: native search service + qdrant-semantics collections/points.

Reference: pkg/nornicgrpc (native search gRPC with in-tree proto,
search_service.go) and pkg/qdrantgrpc (server.go:277 NewServer,
points_service.go, collections_service.go — collection/point ops
translated onto storage+search; highest-throughput surface in the
reference's e2e bench at 29k ops/s).

Servicers are registered with ``grpc.method_handlers_generic_handler``
so no grpc_tools codegen is needed — messages come from the protoc-
generated ``nornic_pb2`` and handlers are plain methods.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from nornicdb_tpu.api.proto import nornic_pb2 as pb


def _abort_qdrant(context, e) -> None:
    """Map QdrantError to a gRPC status — a missing collection or a
    validation failure must not masquerade as an empty result."""
    import grpc

    code = (grpc.StatusCode.NOT_FOUND
            if getattr(e, "status", 400) == 404
            else grpc.StatusCode.INVALID_ARGUMENT)
    context.abort(code, str(e))


def _unary(fn, req_cls):
    import grpc

    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


class SearchServicer:
    """nornic.v1.SearchService — raw vector + hybrid search."""

    def __init__(self, db):
        self.db = db

    def Search(self, request, context):
        t0 = time.time()
        hits = self.db.search.vector_search_candidates(
            np.asarray(list(request.vector), dtype=np.float32),
            k=int(request.limit) or 10,
        )
        return pb.SearchResponse(
            hits=[self._hit(nid, score) for nid, score in hits],
            took_ms=(time.time() - t0) * 1e3,
        )

    def Hybrid(self, request, context):
        t0 = time.time()
        results = self.db.search.search(
            query=request.query,
            limit=int(request.limit) or 10,
            query_embedding=(
                np.asarray(list(request.vector), dtype=np.float32)
                if request.vector else None
            ),
        )
        hits = []
        for r in results:
            hits.append(pb.Hit(
                node_id=str(r.get("id", "")),
                score=float(r.get("score", 0.0)),
                payload_json=json.dumps(r.get("properties", {}), default=str),
            ))
        return pb.SearchResponse(hits=hits, took_ms=(time.time() - t0) * 1e3)

    def _hit(self, node_id: str, score: float) -> "pb.Hit":
        payload = "{}"
        try:
            node = self.db.storage.get_node(node_id)
            payload = json.dumps(node.properties, default=str)
        except Exception:
            pass
        return pb.Hit(node_id=node_id, score=float(score),
                      payload_json=payload)

    def handlers(self):
        import grpc

        return grpc.method_handlers_generic_handler(
            "nornic.v1.SearchService",
            {
                "Search": _unary(self.Search, pb.SearchRequest),
                "Hybrid": _unary(self.Hybrid, pb.HybridRequest),
            },
        )


class QdrantServicer:
    """nornic.v1.QdrantService — qdrant-semantics ops over QdrantCompat."""

    def __init__(self, compat):
        self.compat = compat

    def _ack(self, fn):
        from nornicdb_tpu.api.qdrant import QdrantError

        try:
            fn()
            return pb.AckResponse(ok=True)
        except QdrantError as e:
            return pb.AckResponse(ok=False, error=str(e))

    def CreateCollection(self, request, context):
        vectors = {"size": int(request.vector_size),
                   "distance": request.distance or "Cosine"}
        return self._ack(lambda: self.compat.create_collection(
            request.collection, vectors))

    def DeleteCollection(self, request, context):
        return self._ack(lambda: self.compat.delete_collection(
            request.collection))

    def ListCollections(self, request, context):
        return pb.ListCollectionsResponse(
            collections=self.compat.list_collections())

    def GetCollection(self, request, context):
        from nornicdb_tpu.api.qdrant import QdrantError

        try:
            info = self.compat.get_collection(request.collection)
        except QdrantError:
            return pb.CollectionInfoResponse(status="not_found")
        vec = info["config"]["params"]["vectors"]
        return pb.CollectionInfoResponse(
            status=info["status"],
            points_count=info["points_count"],
            vector_size=int(vec.get("size", 0)),
            distance=str(vec.get("distance", "Cosine")),
        )

    def Upsert(self, request, context):
        points = [
            {
                "id": p.id,
                "vector": list(p.vector),
                "payload": json.loads(p.payload_json) if p.payload_json else {},
            }
            for p in request.points
        ]
        return self._ack(lambda: self.compat.upsert_points(
            request.collection, points))

    def SearchPoints(self, request, context):
        from nornicdb_tpu.api.qdrant import QdrantError

        t0 = time.time()
        try:
            hits = self.compat.search_points(
                request.collection,
                list(request.vector),
                limit=int(request.limit) or 10,
                with_payload=request.with_payload,
                with_vector=request.with_vector,
                score_threshold=(
                    float(request.score_threshold)
                    if request.has_score_threshold else None
                ),
                query_filter=(
                    json.loads(request.filter_json)
                    if request.filter_json else None
                ),
            )
        except QdrantError as e:
            _abort_qdrant(context, e)
        return pb.SearchPointsResponse(
            points=[
                pb.ScoredPoint(
                    id=str(h["id"]),
                    score=h.get("score", 0.0),
                    payload_json=json.dumps(h.get("payload", {}), default=str),
                    vector=h.get("vector", []),
                )
                for h in hits
            ],
            took_ms=(time.time() - t0) * 1e3,
        )

    def DeletePoints(self, request, context):
        return self._ack(lambda: self.compat.delete_points(
            request.collection, list(request.ids)))

    def CountPoints(self, request, context):
        from nornicdb_tpu.api.qdrant import QdrantError

        try:
            return pb.CountResponse(count=self.compat.count_points(
                request.collection))
        except QdrantError as e:
            _abort_qdrant(context, e)

    def handlers(self):
        import grpc

        return grpc.method_handlers_generic_handler(
            "nornic.v1.QdrantService",
            {
                "CreateCollection": _unary(
                    self.CreateCollection, pb.CreateCollectionRequest),
                "DeleteCollection": _unary(
                    self.DeleteCollection, pb.CollectionRequest),
                "ListCollections": _unary(self.ListCollections, pb.Empty),
                "GetCollection": _unary(
                    self.GetCollection, pb.CollectionRequest),
                "Upsert": _unary(self.Upsert, pb.UpsertRequest),
                "SearchPoints": _unary(
                    self.SearchPoints, pb.SearchPointsRequest),
                "DeletePoints": _unary(
                    self.DeletePoints, pb.DeletePointsRequest),
                "CountPoints": _unary(self.CountPoints, pb.CollectionRequest),
            },
        )


def _token_interceptor(token: str):
    """Bearer-token auth interceptor: gRPC writes must not be weaker
    than the REST surface's WRITE authorization."""
    import grpc

    class _Interceptor(grpc.ServerInterceptor):
        def __init__(self):
            def abort(request, context):
                context.abort(grpc.StatusCode.UNAUTHENTICATED,
                              "invalid or missing bearer token")

            self._abort = grpc.unary_unary_rpc_method_handler(abort)

        def intercept_service(self, continuation, details):
            import hmac

            md = dict(details.invocation_metadata)
            if hmac.compare_digest(
                md.get("authorization", ""), f"Bearer {token}"
            ):
                return continuation(details)
            return self._abort

    return _Interceptor()


class GrpcServer:
    """Hosts both services on one port (reference: server.go:328 Start).
    Shares the DB's QdrantCompat with the REST surface so the
    per-collection index caches stay coherent across surfaces."""

    def __init__(self, db, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 8, auth_token: Optional[str] = None,
                 snapshot_dir: Optional[str] = None):
        import grpc
        from concurrent import futures

        self.db = db
        if snapshot_dir is None:
            # reference default: ./data/qdrant-snapshots (server.go:184);
            # here snapshots live with the store when one exists
            import tempfile

            data_dir = getattr(db, "_data_dir", None)
            snapshot_dir = (
                os.path.join(data_dir, "qdrant-snapshots") if data_dir
                else os.path.join(tempfile.gettempdir(),
                                  "nornicdb-qdrant-snapshots"))
        self.snapshot_dir = snapshot_dir
        interceptors = (
            [_token_interceptor(auth_token)] if auth_token else []
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            interceptors=interceptors)
        self.search_servicer = SearchServicer(db)
        self.qdrant_servicer = QdrantServicer(db.qdrant_compat)
        # official qdrant wire contract (qdrant.Collections / qdrant.Points)
        # alongside the native services — reference: pkg/qdrantgrpc serves
        # the upstream proto so official SDKs connect (COMPAT.md)
        from nornicdb_tpu.api.qdrant_official_grpc import (
            OfficialCollectionsServicer,
            OfficialPointsServicer,
            OfficialSnapshotsServicer,
        )

        self.official_collections = OfficialCollectionsServicer(db.qdrant_compat)
        self.official_points = OfficialPointsServicer(db.qdrant_compat)
        self.official_snapshots = OfficialSnapshotsServicer(
            db.qdrant_compat, self.snapshot_dir)
        self._server.add_generic_rpc_handlers((
            self.search_servicer.handlers(),
            self.qdrant_servicer.handlers(),
            self.official_collections.handlers(),
            self.official_points.handlers(),
            self.official_snapshots.handlers(),
        ))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host

    def start(self) -> "GrpcServer":
        self._server.start()
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"
