"""GraphQL API: hand-rolled spec-subset engine + NornicDB resolvers.

Reference: pkg/graphql — gqlgen-generated service exposing node/
relationship CRUD, hybrid search, and Cypher pass-through
(schema/schema.graphql; resolvers/). The reference ships ~15k generated
LoC; here the engine is a compact hand-written lexer/parser/executor
(no codegen, no external graphql lib in the image) covering the subset
the schema needs: named/anonymous queries and mutations, variables with
defaults, aliases, arguments (all literal kinds + variables), nested
selection sets, named + inline fragments, @skip/@include, __typename.

Wire format: POST /graphql {"query", "variables", "operationName"} →
{"data": ..., "errors": [...]}; GET /graphql serves a minimal
playground (reference: gqlgen playground handler.go).
"""

from __future__ import annotations

import functools
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from nornicdb_tpu.storage.types import Direction


class GraphQLError(Exception):
    pass


# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[\s,]+)
  | (?P<comment>\#[^\n\r]*)
  | (?P<spread>\.\.\.)
  | (?P<name>[_A-Za-z][_0-9A-Za-z]*)
  | (?P<float>-?\d+\.\d+(?:[eE][+-]?\d+)?|-?\d+[eE][+-]?\d+)
  | (?P<int>-?\d+)
  | (?P<block_string>\"\"\"(?:[^"]|"(?!""))*\"\"\")
  | (?P<string>"(?:[^"\\\n]|\\.)*")
  | (?P<punct>[!$&():=@\[\]{|}])
    """,
    re.VERBOSE,
)


def _tokenize(src: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise GraphQLError(f"unexpected character {src[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


# ---------------------------------------------------------------------------
# parser → document AST (plain dicts)
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, src: str):
        self.toks = _tokenize(src)
        self.i = 0

    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, value: str) -> None:
        kind, v = self.next()
        if v != value:
            raise GraphQLError(f"expected {value!r}, got {v!r}")

    def accept(self, value: str) -> bool:
        if self.peek()[1] == value:
            self.i += 1
            return True
        return False

    def parse_document(self) -> Dict[str, Any]:
        ops: List[Dict[str, Any]] = []
        fragments: Dict[str, Dict[str, Any]] = {}
        while self.peek()[0] != "eof":
            kind, v = self.peek()
            if v == "{":
                ops.append({"operation": "query", "name": None,
                            "variables": [],
                            "selection_set": self.parse_selection_set()})
            elif v in ("query", "mutation", "subscription"):
                ops.append(self.parse_operation())
            elif v == "fragment":
                frag = self.parse_fragment()
                fragments[frag["name"]] = frag
            else:
                raise GraphQLError(f"unexpected token {v!r}")
        return {"operations": ops, "fragments": fragments}

    def parse_operation(self) -> Dict[str, Any]:
        _, op = self.next()
        name = None
        if self.peek()[0] == "name" and self.peek()[1] not in ("{",):
            name = self.next()[1]
        variables = []
        if self.accept("("):
            while not self.accept(")"):
                self.expect("$")
                var = self.next()[1]
                self.expect(":")
                vtype = self.parse_type()
                default = None
                if self.accept("="):
                    default = self.parse_value(const=True)
                variables.append({"name": var, "type": vtype,
                                  "default": default})
        # directives on operations: skip
        return {"operation": op, "name": name, "variables": variables,
                "selection_set": self.parse_selection_set()}

    def parse_type(self) -> str:
        if self.accept("["):
            inner = self.parse_type()
            self.expect("]")
            t = f"[{inner}]"
        else:
            t = self.next()[1]
        if self.accept("!"):
            t += "!"
        return t

    def parse_fragment(self) -> Dict[str, Any]:
        self.expect("fragment")
        name = self.next()[1]
        self.expect("on")
        type_cond = self.next()[1]
        return {"name": name, "on": type_cond,
                "selection_set": self.parse_selection_set()}

    def parse_selection_set(self) -> List[Dict[str, Any]]:
        self.expect("{")
        sels: List[Dict[str, Any]] = []
        while not self.accept("}"):
            if self.accept("..."):
                if self.peek()[1] == "on":
                    self.next()
                    type_cond = self.next()[1]
                    sels.append({"kind": "inline_fragment", "on": type_cond,
                                 "directives": self.parse_directives(),
                                 "selection_set":
                                     self.parse_selection_set()})
                else:
                    sels.append({"kind": "fragment_spread",
                                 "name": self.next()[1],
                                 "directives": self.parse_directives()})
                continue
            name = self.next()[1]
            alias = None
            if self.accept(":"):
                alias, name = name, self.next()[1]
            args = {}
            if self.accept("("):
                while not self.accept(")"):
                    aname = self.next()[1]
                    self.expect(":")
                    args[aname] = self.parse_value()
            directives = self.parse_directives()
            sub = None
            if self.peek()[1] == "{":
                sub = self.parse_selection_set()
            sels.append({"kind": "field", "name": name, "alias": alias,
                         "args": args, "directives": directives,
                         "selection_set": sub})
        return sels

    def parse_directives(self) -> List[Dict[str, Any]]:
        out = []
        while self.accept("@"):
            name = self.next()[1]
            args = {}
            if self.accept("("):
                while not self.accept(")"):
                    aname = self.next()[1]
                    self.expect(":")
                    args[aname] = self.parse_value()
            out.append({"name": name, "args": args})
        return out

    def parse_value(self, const: bool = False) -> Dict[str, Any]:
        kind, v = self.peek()
        if v == "$":
            if const:
                raise GraphQLError("variable in const position")
            self.next()
            return {"kind": "var", "name": self.next()[1]}
        if v == "[":
            self.next()
            items = []
            while not self.accept("]"):
                items.append(self.parse_value(const))
            return {"kind": "list", "items": items}
        if v == "{":
            self.next()
            fields = {}
            while not self.accept("}"):
                fname = self.next()[1]
                self.expect(":")
                fields[fname] = self.parse_value(const)
            return {"kind": "object", "fields": fields}
        self.next()
        if kind == "int":
            return {"kind": "const", "value": int(v)}
        if kind == "float":
            return {"kind": "const", "value": float(v)}
        if kind == "string":
            return {"kind": "const", "value": _decode_string(v[1:-1])}
        if kind == "block_string":
            return {"kind": "const", "value": v[3:-3]}
        if v == "true":
            return {"kind": "const", "value": True}
        if v == "false":
            return {"kind": "const", "value": False}
        if v == "null":
            return {"kind": "const", "value": None}
        return {"kind": "enum", "value": v}


_ESCAPES = {'"': '"', "\\": "\\", "/": "/", "b": "\b", "f": "\f",
            "n": "\n", "r": "\r", "t": "\t"}


def _decode_string(raw: str) -> str:
    """GraphQL string escape decoding. NOT unicode_escape — that
    reinterprets UTF-8 bytes as Latin-1 and mojibakes non-ASCII."""
    out: List[str] = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c != "\\":
            out.append(c)
            i += 1
            continue
        if i + 1 >= len(raw):
            raise GraphQLError("dangling escape in string literal")
        e = raw[i + 1]
        if e in _ESCAPES:
            out.append(_ESCAPES[e])
            i += 2
        elif e == "u":
            if i + 6 > len(raw):
                raise GraphQLError("bad \\u escape in string literal")
            out.append(chr(int(raw[i + 2:i + 6], 16)))
            i += 6
        else:
            raise GraphQLError(f"unknown escape \\{e}")
    return "".join(out)


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------

Resolver = Callable[[Any, Dict[str, Any], "GraphQLAPI"], Any]


class _Executor:
    def __init__(self, doc: Dict[str, Any], variables: Dict[str, Any],
                 api: "GraphQLAPI"):
        self.doc = doc
        self.vars = variables
        self.api = api

    def run(self, operation_name: Optional[str]) -> Any:
        ops = self.doc["operations"]
        if not ops:
            raise GraphQLError("no operations in document")
        if operation_name:
            matches = [o for o in ops if o["name"] == operation_name]
            if not matches:
                raise GraphQLError(f"unknown operation {operation_name!r}")
            op = matches[0]
        elif len(ops) == 1:
            op = ops[0]
        else:
            raise GraphQLError("operationName required for multi-op document")
        # bind variables (apply defaults)
        bound = dict(self.vars)
        for v in op["variables"]:
            if v["name"] not in bound and v["default"] is not None:
                bound[v["name"]] = self._value(v["default"])
        self.vars = bound
        if op["operation"] == "query":
            root = self.api.query_fields
        elif op["operation"] == "mutation":
            root = self.api.mutation_fields
        else:
            raise GraphQLError("subscriptions are not supported over HTTP")
        return self._select(op["selection_set"], root, None, "Query"
                            if op["operation"] == "query" else "Mutation")

    def _value(self, v: Dict[str, Any]) -> Any:
        k = v["kind"]
        if k == "const":
            return v["value"]
        if k == "enum":
            return v["value"]
        if k == "var":
            if v["name"] not in self.vars:
                raise GraphQLError(f"variable ${v['name']} not provided")
            return self.vars[v["name"]]
        if k == "list":
            return [self._value(x) for x in v["items"]]
        if k == "object":
            return {n: self._value(x) for n, x in v["fields"].items()}
        raise GraphQLError(f"bad value kind {k}")

    def _included(self, directives: List[Dict[str, Any]]) -> bool:
        for d in directives:
            if d["name"] == "skip" and self._value(
                d["args"].get("if", {"kind": "const", "value": False})
            ):
                return False
            if d["name"] == "include" and not self._value(
                d["args"].get("if", {"kind": "const", "value": True})
            ):
                return False
        return True

    def _select(
        self,
        selections: List[Dict[str, Any]],
        fields: Dict[str, Resolver],
        parent: Any,
        type_name: str,
    ) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for sel in selections:
            if not self._included(sel.get("directives", [])):
                continue
            if sel["kind"] == "fragment_spread":
                frag = self.doc["fragments"].get(sel["name"])
                if frag is None:
                    raise GraphQLError(f"unknown fragment {sel['name']!r}")
                if frag["on"] in (type_name, None):
                    out.update(self._select(frag["selection_set"], fields,
                                            parent, type_name))
                continue
            if sel["kind"] == "inline_fragment":
                if sel["on"] in (type_name, None):
                    out.update(self._select(sel["selection_set"], fields,
                                            parent, type_name))
                continue
            name = sel["name"]
            key = sel["alias"] or name
            if name == "__typename":
                out[key] = type_name
                continue
            args = {n: self._value(v) for n, v in sel["args"].items()}
            resolver = fields.get(name)
            if resolver is None:
                raise GraphQLError(
                    f"unknown field {name!r} on {type_name}")
            value = resolver(parent, args, self.api)
            out[key] = self._complete(value, sel.get("selection_set"))
        return out

    def _complete(self, value: Any, sub: Optional[List[Dict[str, Any]]]):
        if value is None:
            return None
        if isinstance(value, list):
            return [self._complete(v, sub) for v in value]
        if isinstance(value, _Object):
            if sub is None:
                raise GraphQLError(
                    f"field of type {value.type_name} needs a selection set")
            return self._select(sub, value.fields, value.parent,
                                value.type_name)
        return value


class _Object:
    """A typed object value: resolvers keyed by field name."""

    def __init__(self, type_name: str, fields: Dict[str, Resolver],
                 parent: Any):
        self.type_name = type_name
        self.fields = fields
        self.parent = parent


# ---------------------------------------------------------------------------
# NornicDB schema + resolvers (reference: schema.graphql Query/Mutation)
# ---------------------------------------------------------------------------


def _prop(name, conv=None):
    def resolver(parent, args, api):
        v = getattr(parent, name, None)
        return conv(v) if conv and v is not None else v

    return resolver


_NODE_FIELDS: Dict[str, Resolver] = {}
_REL_FIELDS: Dict[str, Resolver] = {}


def _node_obj(node) -> Optional[_Object]:
    if node is None:
        return None
    return _Object("Node", _NODE_FIELDS, node)


def _rel_obj(edge) -> Optional[_Object]:
    if edge is None:
        return None
    return _Object("Relationship", _REL_FIELDS, edge)


def _node_relationships(parent, args, api):
    from nornicdb_tpu.storage.types import Direction

    direction = {
        "OUTGOING": Direction.OUTGOING,
        "INCOMING": Direction.INCOMING,
        "BOTH": Direction.BOTH,
    }.get(str(args.get("direction", "BOTH")).upper(), Direction.BOTH)
    edges = api.db.storage.get_node_edges(parent.id, direction)
    rel_type = args.get("type")
    if rel_type:
        edges = [e for e in edges if e.type == rel_type]
    limit = int(args.get("limit", 100))
    return [_rel_obj(e) for e in edges[:limit]]


_NODE_FIELDS.update({
    "id": _prop("id"),
    "labels": _prop("labels"),
    "properties": _prop("properties"),
    "embedding": _prop("embedding"),
    "createdAt": _prop("created_at"),
    "updatedAt": _prop("updated_at"),
    "relationships": _node_relationships,
    "degree": lambda p, a, api: api.db.storage.degree(p.id),
})

_REL_FIELDS.update({
    "id": _prop("id"),
    "type": _prop("type"),
    "properties": _prop("properties"),
    "startNode": lambda p, a, api: _node_obj(
        api.db.storage.get_node(p.start_node)),
    "endNode": lambda p, a, api: _node_obj(
        api.db.storage.get_node(p.end_node)),
    "startNodeId": _prop("start_node"),
    "endNodeId": _prop("end_node"),
})


def _search_result_obj(hit: Dict[str, Any], api) -> _Object:
    fields: Dict[str, Resolver] = {
        "score": lambda p, a, _api: p.get("score"),
        "bm25Score": lambda p, a, _api: p.get("bm25_score"),
        "vectorScore": lambda p, a, _api: p.get("vector_score"),
        "node": lambda p, a, _api: _node_obj(
            _api.db.storage.get_node(p["id"])),
    }
    return _Object("SearchResult", fields, hit)


def _cypher_result_obj(result) -> _Object:
    fields: Dict[str, Resolver] = {
        "columns": lambda p, a, api: p.columns,
        "rows": lambda p, a, api: _jsonable_rows(p.rows),
        "nodesCreated": lambda p, a, api: p.stats.nodes_created,
        "nodesDeleted": lambda p, a, api: p.stats.nodes_deleted,
        "relationshipsCreated":
            lambda p, a, api: p.stats.relationships_created,
        "relationshipsDeleted":
            lambda p, a, api: p.stats.relationships_deleted,
        "propertiesSet": lambda p, a, api: p.stats.properties_set,
    }
    return _Object("CypherResult", fields, result)


def _jsonable_rows(rows):
    from nornicdb_tpu.storage.types import Edge, Node

    def conv(v):
        if isinstance(v, Node):
            return {"id": v.id, "labels": v.labels,
                    "properties": v.properties}
        if isinstance(v, Edge):
            return {"id": v.id, "type": v.type,
                    "startNodeId": v.start_node, "endNodeId": v.end_node,
                    "properties": v.properties}
        if isinstance(v, list):
            return [conv(x) for x in v]
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        return v

    return [[conv(v) for v in row] for row in rows]


def _q_node(parent, args, api):
    try:
        return _node_obj(api.db.storage.get_node(args["id"]))
    except Exception:
        return None


def _page_by_label(storage, label: str, offset: int, limit: int):
    """Sort/slice on ids from the label index, then fetch ONLY the page —
    copying every labeled node per request made nodes(label:) O(N) and
    capped the GraphQL surface at ~150 ops/s on a 1k-node store."""
    import heapq

    all_ids = storage.node_ids_by_label(label)
    want = offset + limit
    if 0 <= want < 64:  # partial selection beats a full sort
        ids = heapq.nsmallest(want, all_ids)[offset:]
    else:
        ids = sorted(all_ids)[offset:want if want >= 0 else None]
    return [n for n in storage.batch_get_nodes(ids) if n is not None]


def _q_all_nodes(parent, args, api):
    limit = int(args.get("limit", 100))
    offset = int(args.get("offset", 0))
    if args.get("label"):
        page = _page_by_label(api.db.storage, args["label"], offset, limit)
    else:
        page = sorted(api.db.storage.all_nodes(),
                      key=lambda n: n.id)[offset:offset + limit]
    return [_node_obj(n) for n in page]


def _q_nodes_by_label(parent, args, api):
    limit = int(args.get("limit", 100))
    page = _page_by_label(api.db.storage, args["label"], 0, limit)
    return [_node_obj(n) for n in page]


def _q_search(parent, args, api):
    results = api.db.search.search(
        query=args.get("query", ""),
        limit=int(args.get("limit", 10)),
    )
    return [_search_result_obj(r, api) for r in results]


def _q_similar(parent, args, api):
    results = api.db.search.similar(args["id"],
                                    limit=int(args.get("limit", 10)))
    return [_search_result_obj(r, api) for r in results]


_CYPHER_WRITE_RE = re.compile(
    r"\b(CREATE|MERGE|DELETE|DETACH|SET|REMOVE|DROP|LOAD\s+CSV)\b",
    re.IGNORECASE,
)


def _q_cypher_readonly(parent, args, api):
    """Cypher on the Query root: read-only. Write Cypher must go through
    the mutation (executeCypher) so it carries WRITE authorization."""
    q = args["query"]
    if _CYPHER_WRITE_RE.search(q):
        raise GraphQLError(
            "write Cypher is not allowed on the Query root; use the "
            "executeCypher mutation")
    return _cypher_result_obj(
        api.db.cypher(q, args.get("parameters") or {}))


def _q_cypher(parent, args, api):
    result = api.db.cypher(args["query"], args.get("parameters") or {})
    return _cypher_result_obj(result)


def _m_create_node(parent, args, api):
    import uuid

    from nornicdb_tpu.storage.types import Node

    inp = args.get("input", args)
    node = Node(
        id=inp.get("id") or str(uuid.uuid4()),
        labels=list(inp.get("labels", [])),
        properties=dict(inp.get("properties", {})),
        embedding=inp.get("embedding"),
    )
    api.db.storage.create_node(node)
    return _node_obj(api.db.storage.get_node(node.id))


def _m_update_node(parent, args, api):
    node = api.db.storage.get_node(args["id"])
    inp = args.get("input", args)
    if inp.get("labels") is not None:
        node.labels = list(inp["labels"])
    if inp.get("properties") is not None:
        node.properties.update(inp["properties"])
    api.db.storage.update_node(node)
    return _node_obj(api.db.storage.get_node(node.id))


def _m_delete_node(parent, args, api):
    try:
        api.db.storage.delete_node(args["id"])
        return True
    except Exception:
        return False


def _m_merge_node(parent, args, api):
    inp = args.get("input", args)
    nid = inp.get("id")
    if nid and api.db.storage.has_node(nid):
        return _m_update_node(parent, {"id": nid, "input": inp}, api)
    return _m_create_node(parent, args, api)


def _m_create_relationship(parent, args, api):
    import uuid

    from nornicdb_tpu.storage.types import Edge

    inp = args.get("input", args)
    edge = Edge(
        id=inp.get("id") or str(uuid.uuid4()),
        start_node=inp["startNodeId"],
        end_node=inp["endNodeId"],
        type=inp.get("type", "RELATED"),
        properties=dict(inp.get("properties", {})),
    )
    api.db.storage.create_edge(edge)
    return _rel_obj(api.db.storage.get_edge(edge.id))


def _m_delete_relationship(parent, args, api):
    try:
        api.db.storage.delete_edge(args["id"])
        return True
    except Exception:
        return False


def _m_bulk_create_nodes(parent, args, api):
    return [_m_create_node(parent, {"input": item}, api)
            for item in args.get("input", [])]


def _m_bulk_delete_nodes(parent, args, api):
    return sum(1 for nid in args.get("ids", [])
               if _m_delete_node(parent, {"id": nid}, api))


def _m_rebuild_search_index(parent, args, api):
    return api.db.search.build_indexes()


def _q_labels(parent, args, api):
    labels = set()
    for n in api.db.storage.all_nodes():
        labels.update(n.labels)
    return sorted(labels)


def _q_rel_types(parent, args, api):
    return sorted({e.type for e in api.db.storage.all_edges()})


def _q_stats(parent, args, api):
    """Reference: schema.graphql GraphStats (nodeCount,
    relationshipCount, labels, relationshipTypes, embeddedNodeCount)."""
    storage = api.db.storage
    label_counts: Dict[str, int] = {}
    embedded = 0
    for n in storage.all_nodes():
        if n.embedding is not None and len(n.embedding or []):
            embedded += 1
        for lbl in n.labels:
            label_counts[lbl] = label_counts.get(lbl, 0) + 1
    type_counts: Dict[str, int] = {}
    for e in storage.all_edges():
        type_counts[e.type] = type_counts.get(e.type, 0) + 1
    stats = {
        "nodeCount": storage.count_nodes(),
        "relationshipCount": storage.count_edges(),
        "labels": [
            {"label": k, "count": v}
            for k, v in sorted(label_counts.items())
        ],
        "relationshipTypes": [
            {"type": k, "count": v}
            for k, v in sorted(type_counts.items())
        ],
        "embeddedNodeCount": embedded,
    }
    fields = {k: (lambda p, a, _api, _k=k: p[_k]) for k in stats}
    return _Object("GraphStats", fields, stats)


def _q_schema(parent, args, api):
    """Graph schema summary (reference: Query.schema / db.schema.*):
    labels, relationship types, and property keys in use."""
    storage = api.db.storage
    prop_keys = set()
    for n in storage.all_nodes():
        prop_keys.update(n.properties.keys())
    for e in storage.all_edges():
        prop_keys.update(e.properties.keys())
    data = {
        "labels": _q_labels(parent, args, api),
        "relationshipTypes": _q_rel_types(parent, args, api),
        "propertyKeys": sorted(prop_keys),
    }
    fields = {k: (lambda p, a, _api, _k=k: p[_k]) for k in data}
    return _Object("GraphSchema", fields, data)


def _q_search_by_property(parent, args, api):
    limit = int(args.get("limit", 100))
    label = args.get("label")
    key, value = args["property"], args.get("value")
    nodes = (api.db.storage.get_nodes_by_label(label) if label
             else api.db.storage.all_nodes())
    hits = [n for n in nodes if n.properties.get(key) == value]
    return [_node_obj(n) for n in sorted(hits, key=lambda n: n.id)[:limit]]


def _edges_between(storage, a: str, b: str, types=None):
    out = []
    for e in storage.get_node_edges(a, Direction.BOTH):
        if types and e.type not in types:
            continue
        if (e.start_node == a and e.end_node == b) or (
            e.start_node == b and e.end_node == a
        ):
            out.append(e)
    return out


def _q_rels_between(parent, args, api):
    edges = _edges_between(
        api.db.storage, args["startId"], args["endId"], args.get("types"))
    return [_rel_obj(e) for e in sorted(edges, key=lambda e: e.id)]


def _path_obj(storage, node_ids, edges):
    data = {
        "nodes": [_node_obj(storage.get_node(i)) for i in node_ids],
        "relationships": [_rel_obj(e) for e in edges],
        "length": len(edges),
    }
    fields = {k: (lambda p, a, _api, _k=k: p[_k]) for k in data}
    return _Object("Path", fields, data)


def _q_shortest_path(parent, args, api):
    """BFS shortest path (reference: Query.shortestPath; apoc.algo)."""
    storage = api.db.storage
    start, end = args["startId"], args["endId"]
    types = args.get("types")
    if start == end:
        return _path_obj(storage, [start], [])
    prev: Dict[str, Any] = {start: None}
    frontier = [start]
    max_depth = int(args.get("maxDepth", 15))
    for _ in range(max_depth):
        nxt = []
        for nid in frontier:
            for e in storage.get_node_edges(nid, Direction.BOTH):
                if types and e.type not in types:
                    continue
                other = e.end_node if e.start_node == nid else e.start_node
                if other in prev:
                    continue
                prev[other] = (nid, e)
                if other == end:
                    ids, edges = [end], []
                    cur = end
                    while prev[cur] is not None:
                        p, pe = prev[cur]
                        edges.append(pe)
                        ids.append(p)
                        cur = p
                    return _path_obj(
                        storage, list(reversed(ids)),
                        list(reversed(edges)))
                nxt.append(other)
        frontier = nxt
        if not frontier:
            break
    return None


def _q_all_paths(parent, args, api):
    """Bounded DFS path enumeration (reference: Query.allPaths)."""
    storage = api.db.storage
    start, end = args["startId"], args["endId"]
    max_depth = int(args.get("maxDepth", 4))
    limit = int(args.get("limit", 25))
    out = []

    def dfs(nid, path_ids, path_edges, used_edges):
        if len(out) >= limit:
            return
        if nid == end and path_edges:
            out.append(_path_obj(storage, list(path_ids), list(path_edges)))
            return
        if len(path_edges) >= max_depth:
            return
        for e in sorted(storage.get_node_edges(nid, Direction.BOTH),
                        key=lambda e: e.id):
            if e.id in used_edges:
                continue
            other = e.end_node if e.start_node == nid else e.start_node
            if other in path_ids and other != end:
                continue  # simple paths only
            used_edges.add(e.id)
            path_ids.append(other)
            path_edges.append(e)
            dfs(other, path_ids, path_edges, used_edges)
            used_edges.discard(e.id)
            path_ids.pop()
            path_edges.pop()

    dfs(start, [start], [], set())
    return out


def _q_neighborhood(parent, args, api):
    """BFS neighborhood subgraph (reference: Query.neighborhood)."""
    storage = api.db.storage
    depth = int(args.get("depth", 1))
    limit = int(args.get("limit", 100))
    seen = {args["id"]}
    frontier = [args["id"]]
    edges = {}
    for _ in range(depth):
        nxt = []
        for nid in frontier:
            for e in storage.get_node_edges(nid, Direction.BOTH):
                edges[e.id] = e
                other = e.end_node if e.start_node == nid else e.start_node
                if other not in seen and len(seen) < limit:
                    seen.add(other)
                    nxt.append(other)
        frontier = nxt
    data = {
        "nodes": [_node_obj(storage.get_node(i)) for i in sorted(seen)],
        # induced subgraph (reference semantics): only edges with BOTH
        # endpoints inside the returned node set — no dangling endpoints
        # from the limit cap, no edges one hop past `depth`
        "relationships": [
            _rel_obj(e)
            for _, e in sorted(edges.items())
            if e.start_node in seen and e.end_node in seen
        ],
    }
    fields = {k: (lambda p, a, _api, _k=k: p[_k]) for k in data}
    return _Object("Neighborhood", fields, data)


def _m_update_relationship(parent, args, api):
    e = api.db.storage.get_edge(args["id"])
    props = args.get("properties") or {}
    if args.get("replace"):
        e.properties = dict(props)
    else:
        e.properties.update(props)
    api.db.storage.update_edge(e)
    return _rel_obj(api.db.storage.get_edge(args["id"]))


def _m_merge_relationship(parent, args, api):
    """Find-or-create by (start, end, type) (reference:
    Mutation.mergeRelationship)."""
    start = args.get("startId", args.get("startNodeId"))
    end = args.get("endId", args.get("endNodeId"))
    existing = [
        e for e in _edges_between(api.db.storage, start, end, [args["type"]])
        if e.start_node == start
    ]
    if existing:
        e = existing[0]
        if args.get("properties"):
            e.properties.update(args["properties"])
            api.db.storage.update_edge(e)
        return _rel_obj(e)
    return _m_create_relationship(parent, {
        "startNodeId": start, "endNodeId": end, "type": args["type"],
        "properties": args.get("properties", {}),
    }, api)


def _m_bulk_create_relationships(parent, args, api):
    return [
        _m_create_relationship(parent, item, api)
        for item in args.get("relationships", args.get("inputs", []))
    ]


def _m_bulk_delete_relationships(parent, args, api):
    n = 0
    for rid in args.get("ids", []):
        try:
            api.db.storage.delete_edge(rid)
            n += 1
        except Exception:
            continue
    return n


def _m_clear_all(parent, args, api):
    """Dangerous full wipe; requires confirm: true (reference:
    Mutation.clearAll)."""
    if not args.get("confirm"):
        raise GraphQLError("clearAll requires confirm: true")
    storage = api.db.storage
    n_edges = 0
    for e in list(storage.all_edges()):
        try:
            storage.delete_edge(e.id)
            n_edges += 1
        except Exception:
            pass
    n_nodes = 0
    for n in list(storage.all_nodes()):
        try:
            storage.delete_node(n.id)
            n_nodes += 1
        except Exception:
            pass
    return {"nodesDeleted": n_nodes, "relationshipsDeleted": n_edges}


def _m_run_decay(parent, args, api):
    """One decay sweep now (reference: Mutation.runDecay)."""
    scored, archived = api.db.decay.sweep()
    return {"processed": scored, "archived": archived}


def _m_trigger_embedding(parent, args, api):
    """Queue a node for (re-)embedding (reference:
    Mutation.triggerEmbedding)."""
    queue = getattr(api.db, "_embed_queue", None)
    if queue is None:
        return False
    queue.enqueue(args["id"])
    return True


class GraphQLAPI:
    """The NornicDB GraphQL endpoint (reference: pkg/graphql handler.go)."""

    query_fields: Dict[str, Resolver] = {
        "node": _q_node,
        "allNodes": _q_all_nodes,
        "nodes": _q_all_nodes,
        "nodesByLabel": _q_nodes_by_label,
        "nodeCount": lambda p, a, api: api.db.storage.count_nodes(),
        "relationship": lambda p, a, api: _rel_obj(
            api.db.storage.get_edge(a["id"])),
        "allRelationships": lambda p, a, api: [
            _rel_obj(e) for e in sorted(
                api.db.storage.all_edges(), key=lambda e: e.id
            )[:int(a.get("limit", 100))]
        ],
        "relationshipsByType": lambda p, a, api: [
            _rel_obj(e)
            for e in api.db.storage.get_edges_by_type(a["type"])
            [:int(a.get("limit", 100))]
        ],
        "relationshipCount": lambda p, a, api: api.db.storage.count_edges(),
        "relationshipsBetween": _q_rels_between,
        "search": _q_search,
        "searchByProperty": _q_search_by_property,
        "similar": _q_similar,
        "cypher": _q_cypher_readonly,
        "labels": _q_labels,
        "relationshipTypes": _q_rel_types,
        "stats": _q_stats,
        "schema": _q_schema,
        "shortestPath": _q_shortest_path,
        "allPaths": _q_all_paths,
        "neighborhood": _q_neighborhood,
    }
    mutation_fields: Dict[str, Resolver] = {
        "createNode": _m_create_node,
        "updateNode": _m_update_node,
        "deleteNode": _m_delete_node,
        "mergeNode": _m_merge_node,
        "bulkCreateNodes": _m_bulk_create_nodes,
        "bulkDeleteNodes": _m_bulk_delete_nodes,
        "createRelationship": _m_create_relationship,
        "updateRelationship": _m_update_relationship,
        "mergeRelationship": _m_merge_relationship,
        "deleteRelationship": _m_delete_relationship,
        "bulkCreateRelationships": _m_bulk_create_relationships,
        "bulkDeleteRelationships": _m_bulk_delete_relationships,
        "executeCypher": _q_cypher,
        "cypher": _q_cypher,
        "rebuildSearchIndex": _m_rebuild_search_index,
        "clearAll": _m_clear_all,
        "runDecay": _m_run_decay,
        "triggerEmbedding": _m_trigger_embedding,
    }

    def __init__(self, db):
        self.db = db
        self._lock = threading.Lock()

    @staticmethod
    @functools.lru_cache(maxsize=256)
    def parse_cached(query: str) -> Dict[str, Any]:
        """LRU document cache keyed on query text (mirrors the Cypher
        executor's parse cache, executor.py). Safe to share: the
        executor treats parsed documents as read-only. The HTTP route
        parses every document twice (operation_kind for authorization,
        then execute), so repeated documents — the normal client
        pattern — skip both parses."""
        return _Parser(query).parse_document()

    @staticmethod
    def operation_kind(query: str, operation_name: Optional[str]) -> str:
        """Resolve which operation would run — authorization must be
        based on the parsed document (a leading comment or a multi-op
        document defeats any regex on the raw text)."""
        doc = GraphQLAPI.parse_cached(query)
        ops = doc["operations"]
        if not ops:
            raise GraphQLError("no operations in document")
        if operation_name:
            matches = [o for o in ops if o["name"] == operation_name]
            if not matches:
                raise GraphQLError(f"unknown operation {operation_name!r}")
            return matches[0]["operation"]
        if len(ops) == 1:
            return ops[0]["operation"]
        raise GraphQLError("operationName required for multi-op document")

    def execute(
        self,
        query: str,
        variables: Optional[Dict[str, Any]] = None,
        operation_name: Optional[str] = None,
    ) -> Dict[str, Any]:
        try:
            doc = self.parse_cached(query)
            data = _Executor(doc, variables or {}, self).run(operation_name)
            return {"data": data}
        except GraphQLError as e:
            return {"data": None, "errors": [{"message": str(e)}]}
        except Exception as e:  # resolver errors surface as GraphQL errors
            return {"data": None,
                    "errors": [{"message": f"{type(e).__name__}: {e}"}]}


PLAYGROUND_HTML = """<!DOCTYPE html>
<html><head><title>NornicDB GraphQL</title></head>
<body><h1>NornicDB GraphQL</h1>
<p>POST GraphQL documents to this endpoint as
<code>{"query": "...", "variables": {...}}</code>.</p>
<textarea id="q" rows="10" cols="80">{ nodeCount }</textarea><br/>
<button onclick="run()">Run</button><pre id="out"></pre>
<script>
async function run() {
  const r = await fetch(location.pathname, {method: 'POST',
    headers: {'Content-Type': 'application/json'},
    body: JSON.stringify({query: document.getElementById('q').value})});
  document.getElementById('out').textContent =
    JSON.stringify(await r.json(), null, 2);
}
</script></body></html>"""
