"""Qdrant-compatible translation layer: collections/points onto storage+search.

Reference: pkg/qdrantgrpc — Collections/Points services translated onto
NornicDB storage + search (points_service.go, collections_service.go),
per-collection vector index cache (vector_index_cache.go), embedding-
ownership rule (COMPAT.md:12-14: vectors supplied by the client are
authoritative; NornicDB never re-embeds them).

Exposed over two surfaces: the Qdrant REST wire format
(api/http_server.py `/collections/...` routes) and gRPC
(api/grpc_server.py). Collections are persisted as meta nodes and points
as labeled nodes, so they survive restart; per-collection brute-force
device indexes are rebuilt lazily on first search.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.obs import annotate as _obs_annotate
from nornicdb_tpu.obs import attach_span as _obs_attach_span
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import cost as _cost
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu.search.vector_index import BruteForceIndex
from nornicdb_tpu.storage.types import Node, now_ms

_META_PREFIX = "qdrant-meta/"
_POINT_PREFIX = "qdrant/"
_COLLECTION_LABEL = "_QdrantCollection"
_ALIAS_META_ID = "qdrant-meta-aliases"


class QdrantError(ValueError):
    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.status = status


def _point_node_id(collection: str, point_id: Any) -> str:
    return f"{_POINT_PREFIX}{collection}/{point_id}"


# per-instance ordinal for the upsert-convoy resource registration
_CONVOY_SEQ = itertools.count(1)


class QdrantCompat:
    """Collection + point operations with Qdrant semantics."""

    def __init__(self, storage, vector_registry=None):
        from nornicdb_tpu.cache import ResultCache
        from nornicdb_tpu.vectorspace import VectorSpaceRegistry

        self.storage = storage
        # per-collection indexes live in registered vector spaces keyed
        # (db="qdrant", entity_type=collection) — reference:
        # pkg/qdrantgrpc/vector_index_cache.go + registry.go
        self.vector_registry = vector_registry or VectorSpaceRegistry()
        # raw (unnormalized) vectors for Dot/Euclid collections:
        # name -> (ids, [N,D] matrix); invalidated on any point mutation
        self._raw: Dict[str, Any] = {}
        # search result cache — in the reference every public search
        # entrypoint (REST, gRPC, qdrant) shares the service's
        # searchResultCache (search.go:88-92); the qdrant surface here
        # has its own per-collection indexes, so it carries its own
        # ResultCache with the same semantics, invalidated on any point
        # or collection mutation. The generation is also how the gRPC
        # raw-bytes wire cache validates its entries.
        self._search_cache: ResultCache = ResultCache(self._copy_hit)
        # per-collection micro-batching: concurrent single-vector
        # searches (gRPC executor threads, REST worker threads) coalesce
        # into ONE batched index dispatch with power-of-two bucketed
        # shapes — the same leader-election window the native search
        # service rides (search/microbatch.py; SURVEY §7)
        self._microbatchers: Dict[str, Any] = {}
        # per-collection device graph ANN (profile cagra): wraps the
        # collection's brute index so the coalesced batches walk the
        # graph instead of scanning the matrix once N crosses the
        # profile threshold (search/cagra.py)
        self._cagra: Dict[str, Any] = {}
        # concurrent point upserts merge into one apply per collection:
        # one lock acquisition + one generation bump per convoy
        from nornicdb_tpu.obs import register_resource
        from nornicdb_tpu.search.microbatch import BatchCoalescer

        self._upsert_coalescer = BatchCoalescer(
            self._apply_upsert_batch, self._apply_upsert_single,
            surface="qdrant:upsert_convoy")
        # write convoys get the same queue-depth gauge + /readyz
        # saturation check the search MicroBatchers got in PR 5. The
        # registration name is per-INSTANCE (the resource registry keys
        # by (family, name) and replaces on collision, so two live
        # compat layers in one process must not shadow each other's
        # gauge); the stage-histogram surface label above stays fixed
        # to keep metric cardinality bounded.
        seq = next(_CONVOY_SEQ)
        self._convoy_resource_name = (
            "qdrant:upsert_convoy" if seq == 1
            else f"qdrant:upsert_convoy:{seq}")
        register_resource("queue", self._convoy_resource_name,
                          self._upsert_coalescer)
        self._lock = threading.Lock()
        # depth of in-progress writes by THIS layer (thread-local): its
        # own storage writes already maintain the indexes incrementally,
        # so the external-mutation listener must ignore them
        self._own = threading.local()

    # -- cross-surface invalidation --------------------------------------

    def _own_write(self):
        """Context manager marking storage writes issued by this layer."""
        compat = self

        class _Ctx:
            def __enter__(self):
                compat._own.depth = getattr(compat._own, "depth", 0) + 1

            def __exit__(self, *exc):
                compat._own.depth -= 1

        return _Ctx()

    def _on_external_mutation(self, node_id: str) -> None:
        """A qdrant-owned node changed through a NON-qdrant surface
        (Cypher SET/DELETE over Bolt/HTTP, GDPR delete, …). The cached
        per-collection index and any cached search results no longer
        reflect storage — drop them so the next read rebuilds lazily.
        (Reference analog: every mutation path invalidates the shared
        searchResultCache, search.go:88-92.)"""
        if getattr(self._own, "depth", 0) > 0:
            return
        name = None
        if node_id.startswith(_POINT_PREFIX):
            name = node_id[len(_POINT_PREFIX):].split("/", 1)[0]
        elif node_id.startswith(_META_PREFIX):
            name = node_id[len(_META_PREFIX):]
        elif node_id != _ALIAS_META_ID:
            return
        with self._lock:
            if name is not None:
                space = self.vector_registry.get(self._space_key(name))
                if space is not None:
                    space.index = None
                self._raw.pop(name, None)
                self._cagra.pop(name, None)
        self._clear_search_cache()

    def _space_key(self, name: str):
        from nornicdb_tpu.vectorspace import DEFAULT_VECTOR_NAME, SpaceKey

        # dims intentionally 0 in the key: the collection's vector size
        # lives in its meta config, and a fixed key keeps lookups O(1)
        return SpaceKey(database="qdrant", entity_type=name,
                        vector_name=DEFAULT_VECTOR_NAME, dims=0,
                        metric="cosine")

    def _space(self, name: str):
        return self.vector_registry.register(self._space_key(name),
                                             backend="brute")

    # -- collections -----------------------------------------------------

    def create_collection(
        self, name: str, vectors: Optional[Dict[str, Any]] = None
    ) -> bool:
        """PUT /collections/{name}. vectors: {"size": N, "distance": "Cosine"}."""
        meta_id = _META_PREFIX + name
        if self.storage.has_node(meta_id):
            raise QdrantError(f"collection `{name}` already exists")
        distance = (vectors or {}).get("distance", "Cosine")
        if distance not in ("Cosine", "Dot", "Euclid"):
            raise QdrantError(f"unsupported distance {distance!r}")
        cfg = {
            "size": int((vectors or {}).get("size", 0)),
            "distance": distance,
        }
        with self._own_write():
            self.storage.create_node(Node(
                id=meta_id,
                labels=[_COLLECTION_LABEL],
                properties={"name": name, "config": cfg,
                            "created_at": now_ms()},
            ))
        with self._lock:
            idx = self._space(name).ensure_index()
        from nornicdb_tpu.obs import register_resource

        # device-memory/freshness gauges from birth; the lazy-rebuild
        # path (_index after restart/invalidation) re-registers the
        # replacement index under the same key
        register_resource("brute", f"qdrant:{name}", idx)
        # collection-list / collection-info responses are wire-cached by
        # the gRPC surfaces against this generation — a create must show
        # up in the next List/Get, same as every other mutation
        self._clear_search_cache()
        return True

    def delete_collection(self, name: str) -> bool:
        meta_id = _META_PREFIX + name
        if not self.storage.has_node(meta_id):
            return False
        with self._own_write():
            for node in self.storage.get_nodes_by_label(self._label(name)):
                self.storage.delete_node(node.id)
            self.storage.delete_node(meta_id)
        self._clear_search_cache()
        with self._lock:
            self.vector_registry.drop(self._space_key(name))
            self._raw.pop(name, None)
            self._cagra.pop(name, None)
            # drop the coalescer too: a recreated namesake may change
            # dims, and the batcher's dispatch must bind the new index
            self._microbatchers.pop(name, None)
            # upstream qdrant drops aliases with the collection; keeping
            # them would leave resolve() routing point ops at a missing
            # collection and block alias-name reuse
            aliases = self._alias_map()
            dangling = [a for a, c in aliases.items() if c == name]
            if dangling:
                for a in dangling:
                    del aliases[a]
                self._save_aliases(aliases)
        return True

    def list_collections(self) -> List[str]:
        return sorted(
            n.properties.get("name", "")
            for n in self.storage.get_nodes_by_label(_COLLECTION_LABEL)
        )

    def get_collection(self, name: str) -> Dict[str, Any]:
        name = self.resolve(name)
        meta = self._meta(name)
        return {
            "status": "green",
            "optimizer_status": "ok",
            "points_count": self.count_points(name),
            "indexed_vectors_count": len(self._index(name)),
            "segments_count": 1,
            "config": {
                "params": {"vectors": meta.properties.get("config", {})},
            },
        }

    def _meta(self, name: str) -> Node:
        try:
            return self.storage.get_node(_META_PREFIX + name)
        except (KeyError, NotFoundError):
            raise QdrantError(f"collection `{name}` not found", status=404)

    # -- aliases (reference: Collections/UpdateAliases etc.,
    # pkg/qdrantgrpc/server.go:658-665) --------------------------------

    def _alias_map(self) -> Dict[str, str]:
        try:
            node = self.storage.get_node(_ALIAS_META_ID)
            return dict(node.properties.get("aliases", {}))
        except (KeyError, NotFoundError):
            return {}

    def _save_aliases(self, aliases: Dict[str, str]) -> None:
        node = Node(id=_ALIAS_META_ID, labels=[_COLLECTION_LABEL + "Alias"],
                    properties={"aliases": aliases})
        with self._own_write():
            if self.storage.has_node(_ALIAS_META_ID):
                self.storage.update_node(node)
            else:
                self.storage.create_node(node)

    def resolve(self, name: str) -> str:
        """Alias -> collection name (identity when not an alias).
        Point/read operations accept aliases, like upstream qdrant.

        Every point/read op funnels through here, so this is also the
        tenant-refinement chokepoint (ISSUE 18): a request that arrived
        without an explicit tenant (header/metadata) derives one from
        the collection->tenant mapping — an explicit tenant always
        wins (refine never overrides it)."""
        resolved = self._alias_map().get(name, name)
        _tenant.refine(_tenant.tenant_for_collection(resolved))
        return resolved

    def update_aliases(self, actions: Sequence[Dict[str, Any]]) -> bool:
        """Atomic batch of alias ops. Each action is one of:
        {"create": {"alias": a, "collection": c}},
        {"rename": {"old": o, "new": n}}, {"delete": {"alias": a}}."""
        with self._lock:
            aliases = self._alias_map()
            for act in actions:
                if "create" in act:
                    a = act["create"]["alias"]
                    c = act["create"]["collection"]
                    if not self.storage.has_node(_META_PREFIX + c):
                        raise QdrantError(
                            f"collection `{c}` not found", status=404)
                    if self.storage.has_node(_META_PREFIX + a):
                        raise QdrantError(
                            f"alias `{a}` collides with a collection")
                    aliases[a] = c
                elif "rename" in act:
                    old = act["rename"]["old"]
                    new = act["rename"]["new"]
                    if old not in aliases:
                        raise QdrantError(f"alias `{old}` not found",
                                          status=404)
                    aliases[new] = aliases.pop(old)
                elif "delete" in act:
                    a = act["delete"]["alias"]
                    if a not in aliases:
                        raise QdrantError(f"alias `{a}` not found",
                                          status=404)
                    del aliases[a]
                else:
                    raise QdrantError(f"unknown alias action {act!r}")
            self._save_aliases(aliases)
        # an alias re-point changes what a cached search request bytes
        # resolve to — serving the old target for the TTL would break
        # the canonical blue/green alias-swap pattern
        self._clear_search_cache()
        return True

    def list_aliases(
        self, collection: Optional[str] = None
    ) -> List[Dict[str, str]]:
        return sorted(
            ({"alias_name": a, "collection_name": c}
             for a, c in self._alias_map().items()
             if collection is None or c == collection),
            key=lambda d: d["alias_name"],
        )

    # -- snapshots (reference: pkg/qdrantgrpc/snapshots_service.go) ------

    @staticmethod
    def _check_path_component(kind: str, value: str) -> str:
        """Reject names that could escape the snapshot tree. Both the
        HTTP and gRPC surfaces pass client strings straight into
        filesystem paths, so every component is validated here, at the
        single choke point, rather than per-route."""
        import os

        if (not value or value in (".", "..")
                or "/" in value or "\\" in value
                or os.sep in value or (os.altsep and os.altsep in value)
                or "\x00" in value):
            raise QdrantError(f"invalid {kind} {value!r}", status=400)
        return value

    def _snap_dir(self, base: str, name: Optional[str] = None) -> str:
        import os

        if name is not None:
            self._check_path_component("collection name", name)
        d = (os.path.join(base, "collections", name)
             if name else os.path.join(base, "full"))
        os.makedirs(d, exist_ok=True)
        return d

    def _snap_path(self, base_dir: str, snap_name: str,
                   collection: Optional[str] = None) -> str:
        """Resolved path of one snapshot file, guaranteed to live under
        the snapshot base dir (defense in depth on top of the component
        check: symlinked bases still can't be escaped via `..`)."""
        import os

        self._check_path_component("snapshot name", snap_name)
        d = self._snap_dir(base_dir, collection)
        path = os.path.join(d, snap_name)
        real_base = os.path.realpath(d)
        if os.path.commonpath(
            [real_base, os.path.realpath(path)]
        ) != real_base:
            raise QdrantError(f"invalid snapshot name {snap_name!r}",
                              status=400)
        return path

    def _snapshot_payload(self, name: str) -> Dict[str, Any]:
        meta = self._meta(name)
        points = []
        for node in self.storage.get_nodes_by_label(self._label(name)):
            points.append({
                "id": node.properties.get("_point_id"),
                "vector": node.properties.get("_vector") or [],
                "payload": node.properties.get("payload") or {},
            })
        return {
            "version": "nornicdb-tpu-qdrant-1",
            "collection": name,
            "config": meta.properties.get("config", {}),
            "points": points,
        }

    def create_snapshot(self, name: str, base_dir: str) -> Dict[str, Any]:
        import json as _json
        import os

        name = self.resolve(name)
        payload = self._snapshot_payload(name)
        ts = time.time()
        snap_name = f"{name}-{int(ts * 1e9)}.snapshot"
        path = os.path.join(self._snap_dir(base_dir, name), snap_name)
        with open(path, "w", encoding="utf-8") as f:
            _json.dump(payload, f)
        return {"name": snap_name, "size": os.path.getsize(path),
                "creation_time": ts}

    def list_snapshots(self, name: str, base_dir: str) -> List[Dict[str, Any]]:
        import os

        name = self.resolve(name)
        self._meta(name)
        d = self._snap_dir(base_dir, name)
        out = []
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".snapshot"):
                st = os.stat(os.path.join(d, fn))
                out.append({"name": fn, "size": st.st_size,
                            "creation_time": st.st_mtime})
        return out

    def delete_snapshot(self, name: str, snap_name: str,
                        base_dir: str) -> bool:
        import os

        name = self.resolve(name)
        path = self._snap_path(base_dir, snap_name, name)
        if not os.path.exists(path):
            raise QdrantError(f"snapshot `{snap_name}` not found",
                              status=404)
        os.remove(path)
        return True

    def create_full_snapshot(self, base_dir: str) -> Dict[str, Any]:
        """One archive of every collection (reference CreateFull)."""
        import json as _json
        import os

        ts = time.time()
        snap_name = f"full-{int(ts * 1e9)}.snapshot"
        payload = {
            "version": "nornicdb-tpu-qdrant-1",
            "collections": [self._snapshot_payload(n)
                            for n in self.list_collections()],
            "aliases": self._alias_map(),
        }
        path = os.path.join(self._snap_dir(base_dir), snap_name)
        with open(path, "w", encoding="utf-8") as f:
            _json.dump(payload, f)
        return {"name": snap_name, "size": os.path.getsize(path),
                "creation_time": ts}

    def list_full_snapshots(self, base_dir: str) -> List[Dict[str, Any]]:
        import os

        d = self._snap_dir(base_dir)
        out = []
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".snapshot"):
                st = os.stat(os.path.join(d, fn))
                out.append({"name": fn, "size": st.st_size,
                            "creation_time": st.st_mtime})
        return out

    def delete_full_snapshot(self, snap_name: str, base_dir: str) -> bool:
        import os

        path = self._snap_path(base_dir, snap_name)
        if not os.path.exists(path):
            raise QdrantError(f"snapshot `{snap_name}` not found",
                              status=404)
        os.remove(path)
        return True

    def recover_snapshot(self, name: str, snap_name: str,
                         base_dir: str) -> int:
        """Restore a collection from a snapshot file (drops current
        contents first). Returns restored point count."""
        import json as _json
        import os

        # resolve aliases like create/list/delete do — recovering by
        # alias must land on the collection the snapshot was written
        # under, not create a literal collection named like the alias
        name = self.resolve(name)
        path = self._snap_path(base_dir, snap_name, name)
        if not os.path.exists(path):
            raise QdrantError(f"snapshot `{snap_name}` not found",
                              status=404)
        with open(path, encoding="utf-8") as f:
            payload = _json.load(f)
        # aliases survive recovery (upstream qdrant keeps them): the
        # delete+recreate below would otherwise drop every alias of the
        # recovered collection via delete_collection's cleanup
        preserved = {a: c for a, c in self._alias_map().items()
                     if c == name}
        if self.storage.has_node(_META_PREFIX + name):
            self.delete_collection(name)
        self.create_collection(name, payload.get("config") or None)
        if preserved:
            with self._lock:
                aliases = self._alias_map()
                aliases.update(preserved)
                self._save_aliases(aliases)
        return self.upsert_points(name, payload.get("points", []))

    @staticmethod
    def _label(name: str) -> str:
        return f"_Qdrant:{name}"

    # -- index cache (reference: vector_index_cache.go) -------------------

    def _index(self, name: str) -> BruteForceIndex:
        with self._lock:
            space = self.vector_registry.get(self._space_key(name))
            if space is not None and space.index is not None:
                return space.index
        # lazy rebuild from storage (post-restart)
        self._meta(name)  # raises if collection doesn't exist
        idx = BruteForceIndex()
        for node in self.storage.get_nodes_by_label(self._label(name)):
            vec = node.properties.get("_vector")
            if vec:
                idx.add(node.id, vec)
        with self._lock:
            space = self._space(name)
            if space.index is None:
                space.index = idx
            from nornicdb_tpu.obs import register_resource

            # per-collection device-memory/freshness gauges; the metric
            # family's cardinality cap folds pathological collection
            # churn into __other__ instead of unbounded series
            register_resource("brute", f"qdrant:{name}", space.index)
            return space.index

    # -- points ----------------------------------------------------------

    def upsert_points(
        self, name: str, points: Sequence[Dict[str, Any]]
    ) -> int:
        """PUT /collections/{name}/points. Client vectors are
        authoritative (embedding-ownership rule, COMPAT.md:12-14).
        The whole batch is validated before any write so a bad point
        never leaves a partially-applied batch."""
        name = self.resolve(name)
        meta = self._meta(name)
        want = meta.properties.get("config", {}).get("size", 0)
        idx = self._index(name)
        if not want:
            want = idx.dims or 0
        # pass 1: validate everything — including float coercion, so a
        # non-numeric vector element fails here, before any write, and never
        # leaves a partially-applied batch
        coerced: List[List[float]] = []
        for p in points:
            if "id" not in p:
                raise QdrantError("point missing id")
            vec = p.get("vector") or []
            if vec:
                if want and len(vec) != want:
                    raise QdrantError(
                        f"vector size {len(vec)} != collection size {want}"
                    )
                want = want or len(vec)
                try:
                    coerced.append([float(x) for x in vec])
                except (TypeError, ValueError) as exc:
                    raise QdrantError(
                        f"point {p['id']}: non-numeric vector element ({exc})"
                    )
            else:
                coerced.append([])
        # pass 2: apply
        n = 0
        with self._own_write():
            for p, vec in zip(points, coerced):
                nid = _point_node_id(name, p["id"])
                node = Node(
                    id=nid,
                    labels=[self._label(name)],
                    properties={
                        "_point_id": p["id"],
                        "_vector": vec,
                        "payload": p.get("payload") or {},
                    },
                )
                if self.storage.has_node(nid):
                    self.storage.update_node(node)
                else:
                    self.storage.create_node(node)
                if vec:
                    idx.add(nid, vec)
                n += 1
        if n:
            self._invalidate_raw(name)
            # write-path pricing (ISSUE 18): bulk upserts were unpriced
            # — a flooding tenant looked free to the cost meter. Under
            # a convoy the coalescer's batch mix splits this across the
            # merged riders by tenant.
            if _cost.pricing_enabled() and want:
                flops, bytes_ = _cost.price_upsert(n, want)
                _cost.record_query_cost("upsert", f"qdrant:{name}",
                                        n, flops, bytes_)
        return n

    # -- microbatched point ops (gRPC serving path) ----------------------

    def upsert_points_coalesced(
        self, name: str, points: Sequence[Dict[str, Any]]
    ) -> int:
        """Upsert through the convoy coalescer: concurrent callers are
        merged into one ``upsert_points`` apply per collection (one
        validation pass, one index touch, ONE cache-generation bump for
        the whole convoy). Semantics match upsert_points — on a merged
        batch the caller's ack still covers exactly its own points.

        Bulk upsert convoys ride the BACKGROUND admission lane
        (ISSUE 15: interactive > replay > background): under pressure
        a multi-lane backlog seals interactive searches first, and the
        admission controller sheds convoys before reads."""
        from nornicdb_tpu import admission as _adm

        with _adm.lane_scope(_adm.LANE_BACKGROUND):
            return self._upsert_coalescer.submit((name, list(points)))

    def _apply_upsert_batch(self, items):
        """Coalescer batch apply: merge per collection, ack per item.
        A raise falls back to _apply_upsert_single per item (upserts are
        idempotent node writes, so a partial merged apply followed by
        the single-item replay cannot double-count)."""
        groups: Dict[str, List[Any]] = {}
        order: List[str] = []
        for idx, (name, points) in enumerate(items):
            if name not in groups:
                groups[name] = []
                order.append(name)
            groups[name].append((idx, points))
        results = [0] * len(items)
        for name in order:
            merged: List[Dict[str, Any]] = []
            for _idx, pts in groups[name]:
                merged.extend(pts)
            self.upsert_points(name, merged)
            for idx, pts in groups[name]:
                results[idx] = len(pts)
        return results

    def _apply_upsert_single(self, item):
        name, points = item
        return self.upsert_points(name, points)

    def retrieve_points(
        self,
        name: str,
        ids: Sequence[Any],
        with_payload: bool = True,
        with_vector: bool = False,
    ) -> List[Dict[str, Any]]:
        name = self.resolve(name)
        self._meta(name)
        out = []
        for pid in ids:
            try:
                node = self.storage.get_node(_point_node_id(name, pid))
            except (KeyError, NotFoundError):
                continue
            out.append(self._point_dict(node, with_payload, with_vector))
        return out

    def delete_points(self, name: str, ids: Sequence[Any]) -> int:
        name = self.resolve(name)
        self._meta(name)
        idx = self._index(name)
        n = 0
        with self._own_write():
            for pid in ids:
                nid = _point_node_id(name, pid)
                if self.storage.has_node(nid):
                    self.storage.delete_node(nid)
                    idx.remove(nid)
                    n += 1
        if n:
            self._invalidate_raw(name)
        return n

    def count_points(self, name: str) -> int:
        name = self.resolve(name)
        self._meta(name)
        counter = getattr(self.storage, "count_nodes_by_label", None)
        if counter is not None:
            return counter(self._label(name))
        return len(self.storage.get_nodes_by_label(self._label(name)))

    def scroll_points(
        self,
        name: str,
        offset: Optional[Any] = None,
        limit: int = 10,
        with_payload: bool = True,
        with_vector: bool = False,
    ) -> Dict[str, Any]:
        name = self.resolve(name)
        self._meta(name)
        nodes = sorted(
            self.storage.get_nodes_by_label(self._label(name)),
            key=lambda n: str(n.properties.get("_point_id")),
        )
        if offset is not None:
            nodes = [
                n for n in nodes
                if str(n.properties.get("_point_id")) >= str(offset)
            ]
        page = nodes[:limit]
        next_off = (
            str(nodes[limit].properties.get("_point_id"))
            if len(nodes) > limit else None
        )
        return {
            "points": [
                self._point_dict(n, with_payload, with_vector) for n in page
            ],
            "next_page_offset": next_off,
        }

    def search_points(
        self,
        name: str,
        vector: Sequence[float],
        limit: int = 10,
        with_payload: bool = True,
        with_vector: bool = False,
        score_threshold: Optional[float] = None,
        query_filter: Optional[Dict[str, Any]] = None,
    ) -> List[Dict[str, Any]]:
        """POST /collections/{name}/points/search — brute-force device
        kNN over the collection's index (reference: search path
        points_service.go via SearchServiceProvider, server.go:167).

        Distance semantics follow the collection config: Cosine rides
        the normalized device index; Dot/Euclid score the raw client
        vectors (magnitudes preserved; Euclid scores are negated
        distances so higher-is-better ordering holds uniformly, with
        score_threshold compared on the true distance)."""
        if not vector:
            raise QdrantError("search vector is required")
        name = self.resolve(name)
        # bool() on the selectors: REST clients may pass list/dict
        # selectors (unhashable), and _point_dict only uses truthiness
        cache_key = (
            name, bytes(np.asarray(vector, np.float32).data), limit,
            bool(with_payload), bool(with_vector), score_threshold,
            None if query_filter is None
            else json.dumps(query_filter, sort_keys=True, default=str),
        )
        cached = self._search_cache.get_hits(cache_key)
        if cached is not None:
            _obs_annotate(result_cache="hit")
            return cached
        gen_at_miss = self._search_cache.generation
        meta = self._meta(name)
        # reject wrong-sized vectors HERE, with a 400-class error, before
        # the query can reach the shared microbatcher (a dim mismatch
        # inside a coalesced np.stack would fail the whole convoy with a
        # bare ValueError) or the raw-matrix broadcast
        want = meta.properties.get("config", {}).get("size", 0)
        if want and len(vector) != want:
            raise QdrantError(
                f"search vector size {len(vector)} != collection "
                f"size {want}")
        distance = meta.properties.get("config", {}).get("distance", "Cosine")
        if distance == "Cosine":
            ranked = self._ranked_cosine(name, vector)
        else:
            ranked = self._ranked_raw(name, vector, distance)
        # the rank generator runs lazily inside the loop below, so this
        # stamp-and-graft interval covers the real device work; the
        # MicroBatcher's coalesce-wait/dispatch spans land as siblings
        t_rank = time.time()
        out = []
        for nid, score in ranked:
            if score_threshold is not None:
                true_score = -score if distance == "Euclid" else score
                if distance == "Euclid":
                    if true_score > score_threshold:
                        continue
                elif true_score < score_threshold:
                    continue
            try:
                node = self.storage.get_node(nid)
            except (KeyError, NotFoundError):
                continue
            if query_filter is not None and not _match_filter(
                node.properties.get("payload") or {}, query_filter,
                point_id=node.properties.get("_point_id"),
            ):
                continue
            d = self._point_dict(node, with_payload, with_vector)
            d["score"] = float(-score if distance == "Euclid" else score)
            out.append(d)
            if len(out) >= limit:
                break
        if distance == "Cosine":
            # the ANN first round can under-fill (stale-graph filtering
            # or walk misses) and the exact widening rounds then append
            # higher-scored hits AFTER it — re-sort so the response
            # honors the score-desc contract. Exact-only paths are
            # already ordered, so this is a no-op for them.
            out.sort(key=lambda d: -d["score"])
        _obs_attach_span("qdrant.rank", t_rank, time.time(),
                         collection=name, distance=distance)
        return self._search_cache.put_guarded(cache_key, out,
                                              gen_at_miss)

    def _collection_microbatch(self, name: str):
        """Per-collection MicroBatcher over the index's batched search.
        The dispatch closure re-resolves the index per batch, so an
        invalidation/rebuild between batches binds the fresh index."""
        from nornicdb_tpu.search.microbatch import MicroBatcher

        with self._lock:
            mb = self._microbatchers.get(name)
            if mb is None:
                mb = MicroBatcher(
                    lambda queries, k, _n=name:
                        self._ann_search_index(_n).search_batch(queries, k),
                    # one bounded stage label for ALL collections — the
                    # per-collection split lives in the resource gauges,
                    # not in histogram label cardinality
                    surface="qdrant",
                    # rider-level serving-tier attribution (ISSUE 10):
                    # the dispatch path (brute/cagra/quant plane) notes
                    # the rung that answered, each rider records it
                    tier_surface="vector")
                self._microbatchers[name] = mb
                from nornicdb_tpu.obs import register_resource

                register_resource("queue", f"qdrant:{name}", mb)
            return mb

    def _ann_search_index(self, name: str):
        """The index the coalesced batches dispatch to: the collection's
        brute index, wrapped by the device graph ANN when the profile
        selects cagra and the collection has crossed its threshold. The
        wrapper shares the brute index (zero vector copies) and rebuilds
        its graph off the brute mutation counter; a collection-index
        invalidation (external mutation, lazy rebuild) is caught by the
        identity check and re-wraps the fresh index."""
        idx = self._index(name)
        from nornicdb_tpu.search.ann_quality import current_profile

        p = current_profile()
        if p.index_kind != "cagra" or len(idx) < p.cagra_min_n:
            # drop any retired wrapper: a collection that shrank below
            # the threshold (or a profile switch) must not pin the old
            # graph's device arrays in memory until collection delete
            with self._lock:
                self._cagra.pop(name, None)
            return idx
        from nornicdb_tpu.search.ann_quality import cagra_shards_from_env
        from nornicdb_tpu.search.cagra import CagraIndex

        with self._lock:
            wrap = self._cagra.get(name)
            if wrap is None or wrap._brute is not idx:
                # build_inline=False: the first graph build happens in
                # background too — a search convoy crossing the size
                # threshold serves the exact brute kernel instead of
                # stalling its MicroBatcher leader for the device kNN
                wrap = CagraIndex(
                    brute=idx, degree=p.cagra_degree, itopk=p.cagra_itopk,
                    search_width=p.cagra_width, min_n=p.cagra_min_n,
                    n_shards=cagra_shards_from_env(p.cagra_shards),
                    build_inline=False)
                self._cagra[name] = wrap
                from nornicdb_tpu.obs import register_resource

                register_resource("cagra", f"qdrant:{name}", wrap)
            return wrap

    def _maybe_shadow_vector(self, idx, q, k: int, hits) -> None:
        """Offer one coalesced, device-served collection search to the
        shadow-parity auditor (reference: the exact brute scan of the
        same index, executed on the audit worker). Best-effort."""
        if not _audit.sampling_active():
            return
        tier = _audit.last_served()
        if tier is None or tier == "host":
            return
        try:
            qv = np.asarray(q, dtype=np.float32)

            def versions_now():
                return {"brute_mutations": getattr(idx, "mutations", 0)}

            # (id, score) pairs: exact tiers score tie-aware rank
            # parity (padded-batch vs b=1 tie permutations are parity)
            _audit.maybe_sample(
                "vector", tier, [(i, float(s)) for i, s in hits],
                k=min(10, k),
                ref=lambda: [(i, float(s)) for i, s in idx.search_batch(
                    qv[None, :], k, exact=True)[0]],
                versions=versions_now(), versions_now=versions_now,
                query={"k": k})
        except Exception:  # noqa: BLE001
            pass

    def _ranked_cosine(self, name: str, vector: Sequence[float]):
        """Yield (node_id, cosine) best-first, progressively widening the
        kNN so selective filters still fill `limit` (a fixed 4x
        oversample starves on rare payloads).

        The first (and almost always only) round routes through the
        collection's MicroBatcher: concurrent single-vector searches
        from any surface coalesce into one power-of-two-bucketed batch
        dispatch. Widening rounds (selective filters) are rare and go
        direct — their k varies too much to bucket usefully."""
        idx = self._index(name)
        total = len(idx)
        k = 40
        first = True
        # dedupe by id, not by list position: the batched round-1 call
        # (GEMM over a padded batch) and the direct widening calls can
        # order float near-ties differently, so positional continuation
        # could re-yield or drop a boundary point
        yielded = set()
        q = np.asarray(vector, dtype=np.float32)
        while True:
            k_req = min(k, total) if total else k
            if first:
                hits = self._collection_microbatch(name).search(q, k_req)
                self._maybe_shadow_vector(idx, q, k_req, hits)
                first = False
                # a short FIRST round is not exhaustion: the ANN wrapper
                # (cagra) live-filters rows deleted since its build, so
                # it can return < k while thousands of live rows remain.
                # Widening rounds query the brute index directly and ARE
                # authoritative.
                ann_round = True
            else:
                hits = idx.search(q, k=k_req)
                ann_round = False
            for nid, score in hits:
                if nid in yielded:
                    continue
                yielded.add(nid)
                yield nid, score
            if len(yielded) >= total:
                return
            if len(hits) < k and not ann_round:
                return
            k *= 4

    def _raw_matrix(self, name: str, dims: int):
        """Cached (ids, [N,D]) raw-vector matrix for Dot/Euclid — the
        analog of the normalized index cache; rebuilt only after a point
        mutation invalidates it (a per-query storage scan would be O(N)
        reads on every search)."""
        with self._lock:
            cached = self._raw.get(name)
        if cached is not None and cached[1].shape[1] == dims:
            return cached
        ids: List[str] = []
        rows: List[List[float]] = []
        for node in self.storage.get_nodes_by_label(self._label(name)):
            vec = node.properties.get("_vector")
            if vec and len(vec) == dims:
                ids.append(node.id)
                rows.append(vec)
        m = np.asarray(rows, dtype=np.float32) if rows else np.zeros(
            (0, dims), np.float32)
        with self._lock:
            self._raw[name] = (ids, m)
        return ids, m

    def _clear_search_cache(self) -> None:
        self._search_cache.bump_generation()

    @property
    def cache_gen(self) -> int:
        return self._search_cache.generation

    @staticmethod
    def _copy_hit(d: Dict[str, Any]) -> Dict[str, Any]:
        """Cache-safe copy: _point_dict shares the node's payload dict
        by reference, so a caller mutating hit['payload'] must not
        rewrite the cached entry."""
        from nornicdb_tpu.search.service import _copy_tree

        c = dict(d)
        if "payload" in c:
            c["payload"] = _copy_tree(c["payload"])
        if "vector" in c:
            c["vector"] = list(c["vector"])
        return c

    def _invalidate_raw(self, name: str) -> None:
        with self._lock:
            self._raw.pop(name, None)
        self._clear_search_cache()

    def _ranked_raw(self, name: str, vector: Sequence[float], distance: str):
        """Dot / Euclid over the raw (unnormalized) client vectors.
        Euclid yields NEGATED distances so callers sort uniformly
        best-first."""
        q = np.asarray(vector, dtype=np.float32)
        ids, m = self._raw_matrix(name, len(q))
        if not ids:
            return
        if distance == "Dot":
            scores = m @ q
        else:  # Euclid
            scores = -np.linalg.norm(m - q[None, :], axis=1)
        for i in np.argsort(-scores):
            yield ids[int(i)], float(scores[int(i)])

    @staticmethod
    def _point_dict(
        node: Node, with_payload: bool, with_vector: bool
    ) -> Dict[str, Any]:
        d: Dict[str, Any] = {"id": node.properties.get("_point_id"),
                             "version": 0}
        if with_payload:
            d["payload"] = node.properties.get("payload") or {}
        if with_vector:
            d["vector"] = node.properties.get("_vector") or []
        return d


def _match_filter(payload: Dict[str, Any], flt: Dict[str, Any],
                  point_id: Optional[Any] = None) -> bool:
    """Qdrant filter subset: must / should / must_not with
    match.value / match.any / range / has_id / is_null / is_empty
    conditions on payload keys."""
    for cond in flt.get("must", []):
        if not _match_condition(payload, cond, point_id):
            return False
    for cond in flt.get("must_not", []):
        if _match_condition(payload, cond, point_id):
            return False
    should = flt.get("should", [])
    if should and not any(
        _match_condition(payload, c, point_id) for c in should
    ):
        return False
    return True


def _match_condition(payload: Dict[str, Any], cond: Dict[str, Any],
                     point_id: Optional[Any] = None) -> bool:
    if "filter" in cond:  # nested filter
        return _match_filter(payload, cond["filter"], point_id)
    if "has_id" in cond:
        wanted = {str(x) for x in cond["has_id"]}
        return point_id is not None and str(point_id) in wanted
    if "is_null" in cond:
        # accepts both the REST wire shape {"is_null": {"key": k}} and the
        # gRPC-normalized bare string
        k = cond["is_null"]
        if isinstance(k, dict):
            k = k.get("key")
        return k in payload and payload[k] is None
    if "is_empty" in cond:
        k = cond["is_empty"]
        if isinstance(k, dict):
            k = k.get("key")
        v = payload.get(k)
        return v is None or v == [] or v == ""
    key = cond.get("key")
    if key is None:
        return True
    value = payload
    for part in str(key).split("."):
        if isinstance(value, dict) and part in value:
            value = value[part]
        else:
            return False
    match = cond.get("match")
    if match is not None:
        if "value" in match:
            return value == match["value"]
        if "any" in match:
            return value in match["any"]
        if "text" in match:
            return str(match["text"]).lower() in str(value).lower()
    rng = cond.get("range")
    if rng is not None:
        try:
            v = float(value)
        except (TypeError, ValueError):
            return False
        if "gt" in rng and not v > rng["gt"]:
            return False
        if "gte" in rng and not v >= rng["gte"]:
            return False
        if "lt" in rng and not v < rng["lt"]:
            return False
        if "lte" in rng and not v <= rng["lte"]:
            return False
        return True
    return True
