"""Replica-aware read router with parity-gated admission (ISSUE 12).

The serving half of the read fleet (replication/read_fleet.py): reads —
coalesced vector dispatches, hybrid searches, qdrant point reads —
round-robin across *admitted, ready* replicas; writes always go to the
primary. Three gates keep a replica out of rotation:

- **admission parity** — a replica serves nothing until its answers to
  probe queries match the primary's exact host reference at the PR 10
  auditor floors (rank parity 1.0 for exact tiers, recall >= 0.95 for
  statistical tiers, scored by ``ShadowAuditor.parity_of``);
- **readiness** — the replica's own ``ready_reasons()`` (the same
  signal its ``/readyz`` serves): behind ``NORNICDB_READY_MAX_LAG_OPS``
  or mid catch-up drains it, as does an in-flight background index
  rebuild;
- **health** — a read that raises drains the replica until the next
  health re-check window.

Every step-down is explained: the transition (never the steady state)
writes a degrade-ledger record — ``reason=replica_lag`` for the lag
threshold, ``reason=replica_drain`` for parity/rebuild/error drains —
so ``/admin/degrades`` tells the whole routing story. Per-read
attribution rides ``nornicdb_fleet_reads_total{node,surface}`` and
``nornicdb_fleet_served_tier_total{node,tier}`` (the per-replica
served-tier split); ``nornicdb_replica_parity_ratio{node}`` and
``nornicdb_replica_admitted{node}`` carry the admission state.

Deployment shapes: in-process replicas (ReadReplica handles — tests,
bench, single-box fleets) and :class:`RemoteReplica` HTTP endpoints
(``/readyz`` as the health signal, qdrant/REST reads over the wire)
for multi-host topologies. The PR 11 ``WirePlane`` accepts a router as
``fleet=`` so every frontend worker's reads fan across the fleet while
its writes funnel to the one primary.

Read consistency is *bounded staleness*: a replica may trail the
primary by at most the lag threshold, and drains rather than serve
staler answers. Read-your-writes callers use leader leases (ISSUE 16):
``refresh_leases`` grants a time-bounded lease
(``NORNICDB_FLEET_LEASE_MS``) to every replica proven at the primary's
watermark, and ``pick_fresh`` routes to a lease holder with only a
local watermark read — no per-read replica round-trip; when no lease
holds, the caller reads the primary (docs/replication.md runbook).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import events as _events
from nornicdb_tpu.obs import tracing as _tracing
from nornicdb_tpu.obs.metrics import REGISTRY

_READS_C = REGISTRY.counter(
    "nornicdb_fleet_reads_total",
    "Reads the fleet router dispatched, by serving node and surface",
    labels=("node", "surface"))
_TIER_C = REGISTRY.counter(
    "nornicdb_fleet_served_tier_total",
    "Fleet-routed reads by serving node and ladder tier",
    labels=("node", "tier"))
_PARITY_G = REGISTRY.gauge(
    "nornicdb_replica_parity_ratio",
    "Admission-probe parity of a replica vs the primary's exact host "
    "reference", labels=("node",))
_ADMITTED_G = REGISTRY.gauge(
    "nornicdb_replica_admitted",
    "1 while a replica is admitted and in the read rotation",
    labels=("node",))
_LEASE_G = REGISTRY.gauge(
    "nornicdb_fleet_lease_active",
    "1 while a replica holds an unexpired leader lease at the "
    "primary's watermark (read-your-writes routing)",
    labels=("node",))
_LEASE_READS_C = REGISTRY.counter(
    "nornicdb_fleet_lease_reads_total",
    "Read-your-writes reads served by a lease-holding replica without "
    "a primary round-trip", labels=("node",))

# QdrantCompat read surface; writes (upserts, deletes, collection DDL,
# alias updates, snapshots) always hit the primary
_READ_COMPAT = frozenset({
    "search_points", "retrieve_points", "scroll_points", "count_points",
    "list_collections", "get_collection", "resolve", "list_aliases",
})


class ReplicaBusy(RuntimeError):
    """A replica answered 429/503: alive, admission-shedding or
    momentarily not ready. Routing tries the next node — a busy
    verdict must never open a drain episode (ISSUE 16: admission
    posture and drain bookkeeping are separate control loops)."""


class FleetRouter:
    """Round-robin read routing over admitted+ready replicas, primary
    fallback, drain bookkeeping, and the promotion pivot."""

    def __init__(self, primary_db, check_interval_s: float = 0.05,
                 max_lag_ops: Optional[int] = None):
        from nornicdb_tpu.config import env_float

        self.primary_db = primary_db
        self._check_interval_s = check_interval_s
        self._max_lag_ops = max_lag_ops  # None -> env per check
        self._lock = threading.Lock()
        self._replicas: Dict[str, Any] = {}
        self._order: List[str] = []
        # name -> {"admitted", "parity", "drain": reason|None,
        #          "checked_at", "ready"}
        self._state: Dict[str, Dict[str, Any]] = {}
        self._rr = 0
        # materialized counter children — the read hot path must not
        # pay a labels() probe per query (audit.py precedent)
        self._count_cache: Dict[Any, Any] = {}
        # leader leases (ISSUE 16): name -> {"watermark", "expires"}.
        # Knobs read ONCE here — pick_fresh is per-request and must not
        # touch the environment (lint HOT_PATHS discipline).
        self._lease_s = env_float("FLEET_LEASE_MS", 400.0) / 1e3
        self._lease_refresh_s = env_float(
            "FLEET_LEASE_REFRESH_MS", 100.0) / 1e3
        self._leases: Dict[str, Dict[str, float]] = {}
        self._lease_refreshed_at = 0.0

    # -- membership ------------------------------------------------------

    def add_replica(self, replica) -> None:
        """Register a replica handle. It serves nothing until
        :meth:`admit` passes its parity gate (or
        :meth:`admit_unchecked` explicitly waives it)."""
        with self._lock:
            name = replica.name
            self._replicas[name] = replica
            if name not in self._order:
                self._order.append(name)
            self._state[name] = {"admitted": False, "parity": None,
                                 "drain": None, "checked_at": 0.0,
                                 "ready": False}
        _ADMITTED_G.labels(name).set(0.0)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)
            self._state.pop(name, None)
            had_lease = self._leases.pop(name, None) is not None
            if name in self._order:
                self._order.remove(name)
        if had_lease:
            _LEASE_G.labels(name).set(0.0)

    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._order)

    # -- parity-gated admission ------------------------------------------

    def admit(self, name: str, probes: Sequence[Sequence[float]],
              k: int = 10) -> float:
        """Run the admission parity gate: each probe vector is answered
        by the replica's device dispatch and by the primary's exact
        host reference; the MINIMUM per-probe parity must clear the
        served tier's floor (audit.tier_floor — exact 1.0, statistical
        0.95). Returns the min ratio; a failing replica stays drained
        with a ``replica_drain`` ledger record."""
        with self._lock:
            replica = self._replicas.get(name)
        if replica is None:
            raise KeyError(f"unknown replica {name!r}")
        if not getattr(replica, "supports_vec", True):
            raise ValueError(
                f"replica {name!r} has no in-process vector dispatch to "
                "probe (remote handle); verify parity against its own "
                "surface out of band and use admit_unchecked()")
        worst = 1.0
        # gate at the LOOSEST floor any probe served under: a replica
        # answering through a statistical tier (walk/quant) owes 0.95
        # on those probes, while exact-tier probes still demand 1.0 —
        # each probe is compared against ITS OWN tier's floor and the
        # verdict is "every probe cleared its floor"
        ok = True
        for vec in probes:
            ratio, probe_floor = self._probe_parity(replica, vec, k)
            worst = min(worst, ratio)
            if ratio < probe_floor:
                ok = False
        _PARITY_G.labels(name).set(float(worst))
        with self._lock:
            st = self._state.get(name)
            if st is not None:
                st["admitted"] = bool(ok)
                st["parity"] = float(worst)
                st["drain"] = None if ok else "replica_parity"
        _ADMITTED_G.labels(name).set(1.0 if ok else 0.0)
        if not ok:
            _audit.record_degrade("fleet", "replica", "primary",
                                  "replica_drain", index=name)
        else:
            _events.record_event("admit", node=name, surface="fleet",
                                 reason="parity_gate",
                                 detail={"parity": round(worst, 4)})
        return worst

    def admit_unchecked(self, name: str) -> None:
        """Waive the parity gate (tests, trusted rejoin)."""
        with self._lock:
            st = self._state.get(name)
            if st is not None:
                st["admitted"] = True
                st["drain"] = None
        _ADMITTED_G.labels(name).set(1.0)

    def _probe_parity(self, replica, vec, k: int):
        """(parity ratio, floor) of one probe on one replica."""
        q = np.asarray(vec, dtype=np.float32)[None, :]
        _audit.set_last_served(None)
        dev = replica.vec_dispatch("__service__", q, k)[0]
        tier = _audit.last_served() or "vector_brute_f32"
        floor = _audit.tier_floor(tier)
        exact = floor >= 1.0
        ref = self.primary_db.search.vector_search_candidates(
            np.asarray(vec, dtype=np.float32), k=k, exact=True)
        dev_pairs = [(i, float(s)) for i, s in list(dev)[:k]]
        ref_pairs = [(i, float(s)) for i, s in list(ref)[:k]]
        from nornicdb_tpu.obs.audit import ShadowAuditor

        return (ShadowAuditor.parity_of(dev_pairs, ref_pairs, k,
                                        exact=exact), floor)

    def parity(self, name: str) -> Optional[float]:
        with self._lock:
            st = self._state.get(name)
            return None if st is None else st.get("parity")

    # -- readiness / drain -----------------------------------------------

    def _check_ready(self, name: str, replica,
                     st: Dict[str, Any]) -> bool:
        """Cached readiness verdict; drain/undrain transitions record
        their degrade reason exactly once."""
        now = time.time()
        if now - st["checked_at"] < self._check_interval_s:
            return st["ready"]
        reason: Optional[str] = None
        try:
            reasons = replica.ready_reasons(self._max_lag_ops)
            if reasons:
                reason = reasons[0]
            elif getattr(replica, "rebuild_in_flight", None) \
                    and replica.rebuild_in_flight():
                reason = f"index_rebuild:{name}"
            elif getattr(replica, "is_replica", None) \
                    and not replica.is_replica():
                reason = f"promoted:{name}"
        except Exception as exc:  # noqa: BLE001 — unreachable drains
            reason = f"unreachable:{name}:{type(exc).__name__}"
        ready = reason is None

        with self._lock:
            # state transition under the lock so two racing reads can
            # never double-record the same drain in the ledger
            prev = st.get("drain")
            if st["checked_at"] > now:
                return st["ready"]  # a racer already re-checked
            # one drain EPISODE, one record: the reason may drift while
            # the replica stays down (replica_lag embeds the live lag
            # value; a killed subprocess goes error -> unreachable),
            # but only the healthy->drained edge is ledgered
            transition_down = not ready and prev is None
            transition_up = ready and prev is not None
            st["drain"] = reason
            st["ready"] = ready
            st["checked_at"] = time.time()
            admitted = st["admitted"]
        if transition_down:
            # record the TRANSITION once, not every routed read
            ledger_reason = ("replica_lag"
                            if reason.startswith("replica_lag")
                            else "replica_drain")
            _audit.record_degrade("fleet", "replica", "primary",
                                  ledger_reason, index=name)
            _events.record_event("drain", node=name, surface="fleet",
                                 reason=reason)
            _ADMITTED_G.labels(name).set(0.0)
        elif transition_up:
            _events.record_event("admit", node=name, surface="fleet",
                                 reason="recovered")
            _ADMITTED_G.labels(name).set(1.0 if admitted else 0.0)
        return ready

    def pick_read(self, need_vec: bool = False, need_db: bool = False):
        """The replica the next read should hit, or None (serve from
        the primary). Round-robin over admitted+ready replicas;
        ``need_vec`` skips handles without an in-process raw-embedding
        dispatch (RemoteReplica) instead of draining them, ``need_db``
        skips handles without an in-process DB facade (the routed
        search/compat facades call straight into ``replica.db``)."""
        with self._lock:
            order = list(self._order)
            start = self._rr
            self._rr += 1
        n = len(order)
        for i in range(n):
            name = order[(start + i) % n]
            with self._lock:
                replica = self._replicas.get(name)
                st = self._state.get(name)
            if replica is None or st is None or not st["admitted"]:
                continue
            if need_vec and not getattr(replica, "supports_vec", True):
                continue
            if need_db and getattr(replica, "db", None) is None:
                continue
            if st.get("drain") == "replica_parity":
                continue
            if self._check_ready(name, replica, st):
                return replica
        return None

    def drain_state(self) -> Dict[str, Dict[str, Any]]:
        """Admission/drain snapshot per replica (admin surface, bench)."""
        with self._lock:
            return {name: dict(st) for name, st in self._state.items()}

    # -- leader leases (ISSUE 16) ----------------------------------------

    def _primary_watermark(self) -> int:
        """The primary's WAL last_seq (local read — the router runs in
        the primary's process), or -1 when the primary has no WAL."""
        try:
            return int(self.primary_db._base.wal.last_seq)
        except Exception:  # noqa: BLE001 — non-WAL primary
            return -1

    def _applied_seq_of(self, replica) -> Optional[int]:
        """A replica's applied watermark: in-process handles read their
        standby directly; remote handles answer from their last /readyz
        watermark doc (refreshed by the probe the lease cadence pays)."""
        st = getattr(replica, "standby", None)
        if st is not None:
            return int(st.applied_seq)
        fn = getattr(replica, "applied_seq", None)
        if callable(fn):
            try:
                seq = fn()
                return None if seq is None else int(seq)
            except Exception:  # noqa: BLE001
                return None
        return None

    def refresh_leases(self) -> Dict[str, bool]:
        """Grant/renew a lease to every admitted+ready replica whose
        applied watermark has reached the primary's current last_seq;
        revoke holders that fell behind. One refresh probes each
        replica once — the round-trip the per-read lease check then
        avoids. Transitions (grant after no lease, lapse after a live
        one) journal exactly once."""
        now = time.time()
        wm = self._primary_watermark()
        if wm < 0:
            return {}
        with self._lock:
            items = [(n, self._replicas[n], self._state[n])
                     for n in self._order if n in self._replicas]
        verdicts: Dict[str, bool] = {}
        for name, replica, st in items:
            holds = False
            if st["admitted"] and self._check_ready(name, replica, st):
                applied = self._applied_seq_of(replica)
                holds = applied is not None and applied >= wm
            with self._lock:
                prev = self._leases.get(name)
                had = prev is not None and prev["expires"] > now
                if holds:
                    self._leases[name] = {"watermark": float(wm),
                                          "expires": now + self._lease_s}
                else:
                    self._leases.pop(name, None)
            verdicts[name] = holds
            if holds and not had:
                _LEASE_G.labels(name).set(1.0)
                _events.record_event(
                    "lease_grant", node=name, surface="fleet",
                    reason="at_watermark", detail={"watermark": wm})
            elif had and not holds:
                _LEASE_G.labels(name).set(0.0)
                _events.record_event(
                    "lease_lapse", node=name, surface="fleet",
                    reason="behind_watermark", detail={"watermark": wm})
        return verdicts

    def lease_state(self) -> Dict[str, Dict[str, float]]:
        """Live lease table (admin surface, tests); expired entries are
        reported but not pruned — pruning is refresh_leases' job."""
        with self._lock:
            return {n: dict(v) for n, v in self._leases.items()}

    def pick_fresh(self):
        """Read-your-writes routing: a replica holding an unexpired
        lease at (or past) the primary's CURRENT watermark, or None
        (the caller must read the primary). The per-read cost is a
        local watermark read + the lease-table lookup — no replica
        round-trip; the probe that proved the replica's watermark was
        paid once by refresh_leases on its own cadence. A write that
        landed after the grant moves the watermark past the lease and
        invalidates it naturally."""
        now = time.time()
        for attempt in (0, 1):
            wm = self._primary_watermark()
            with self._lock:
                order = list(self._order)
                start = self._rr
                self._rr += 1
            n = len(order)
            for i in range(n):
                name = order[(start + i) % n]
                with self._lock:
                    lease = self._leases.get(name)
                    replica = self._replicas.get(name)
                    st = self._state.get(name)
                if (replica is None or st is None
                        or not st["admitted"]):
                    continue
                if lease is None or lease["expires"] <= now:
                    continue
                if wm >= 0 and lease["watermark"] < wm:
                    continue  # a newer write outran the lease
                if self._check_ready(name, replica, st):
                    key = ("l", name)
                    child = self._count_cache.get(key)
                    if child is None:
                        child = self._count_cache[key] = \
                            _LEASE_READS_C.labels(name)
                    child.inc()
                    return replica
            # miss: refresh at most once per refresh window, then retry
            if attempt == 0 and \
                    now - self._lease_refreshed_at >= self._lease_refresh_s:
                self._lease_refreshed_at = now
                self.refresh_leases()
                continue
            break
        return None

    # -- HTTP-level read dispatch (multi-process fleets) ------------------

    def http_search(self, payload: Dict[str, Any],
                    read_your_writes: bool = False):
        """Fleet-routed ``POST /nornicdb/search`` over remote node
        handles (out-of-GIL serving). Returns the response doc, or
        None when no remote replica can serve (the caller reads the
        primary). ``read_your_writes`` restricts routing to
        lease-holding replicas at the primary's watermark."""
        if read_your_writes:
            replica = self.pick_fresh()
            if replica is None or getattr(replica, "search", None) is None:
                return None
            candidates = [replica]
        else:
            # on a busy (shedding) node, try the next one — up to one
            # full rotation; a busy verdict never drains
            with self._lock:
                n = len(self._order)
            candidates = []
            for _ in range(max(n, 1)):
                r = self.pick_read()
                if r is None or any(r is c for c in candidates):
                    break
                candidates.append(r)
        for replica in candidates:
            search = getattr(replica, "search", None)
            if search is None:
                return None  # in-process handle: use routed_search()
            try:
                doc = search(payload)
            except ReplicaBusy:
                continue  # admission shed, not a failure
            except Exception:  # noqa: BLE001 — degrade, never fail
                self._drain_error(replica.name)
                return None
            self._note_served(replica.name, "http")
            return doc
        return None

    # -- read dispatch ---------------------------------------------------

    def _note_served(self, name: str, surface: str, n: int = 1) -> None:
        key = ("r", name, surface)
        child = self._count_cache.get(key)
        if child is None:
            child = self._count_cache[key] = _READS_C.labels(name, surface)
        child.inc(n)
        # stamp the chosen node on the active trace (ISSUE 13): a
        # fleet-routed read's span answers "which replica served this"
        _tracing.annotate(fleet_node=name)
        tier = _audit.last_served()
        if tier:
            tkey = ("t", name, tier)
            tchild = self._count_cache.get(tkey)
            if tchild is None:
                tchild = self._count_cache[tkey] = _TIER_C.labels(name,
                                                                  tier)
            tchild.inc(n)

    def _drain_error(self, name: str) -> None:
        with self._lock:
            st = self._state.get(name)
            if st is not None and st.get("drain") is None:
                st["drain"] = f"error:{name}"
                st["ready"] = False
                st["checked_at"] = time.time()
                _audit.record_degrade("fleet", "replica", "primary",
                                      "replica_drain", index=name)
                _events.record_event("drain", node=name,
                                     surface="fleet",
                                     reason=f"error:{name}")

    def vec_dispatch(self, key: str, queries, k: int, local_fn):
        """Coalesced vector dispatch (the WirePlane/broker OP_VEC
        contract): serve the batch from a ready replica, fall back to
        the local (primary) dispatch on drain or error. The chosen
        node is noted on the dispatching thread
        (``audit.consume_fleet_node``) so the broker stamps it onto
        every rider's response and span records (ISSUE 13)."""
        replica = self.pick_read(need_vec=True)
        if replica is None:
            _audit.note_fleet_node("primary")
            return local_fn(key, queries, k)
        try:
            out = replica.vec_dispatch(key, queries, k)
        except KeyError:
            # capability miss (unknown dispatch key / remote handle):
            # serve locally, never drain a healthy replica over it
            _audit.note_fleet_node("primary")
            return local_fn(key, queries, k)
        except Exception:  # noqa: BLE001 — degrade, never fail the read
            self._drain_error(replica.name)
            _audit.note_fleet_node("primary")
            return local_fn(key, queries, k)
        self._note_served(replica.name, "vec", n=len(queries))
        _audit.note_fleet_node(replica.name)
        return out

    def routed_search(self):
        return RoutedSearch(self)

    def routed_compat(self):
        return RoutedCompat(self)

    # -- failover --------------------------------------------------------

    def on_promote(self, replica) -> None:
        """A replica was promoted: writes re-point at it, and it leaves
        the read rotation (it IS the primary now). The old primary's
        handle, if any, stays registered but drains via its
        ``promoted``/role check until an operator re-admits it."""
        self.primary_db = replica.db
        with self._lock:
            st = self._state.get(replica.name)
            if st is not None:
                st["admitted"] = False
                st["drain"] = f"promoted:{replica.name}"
            # leases were granted against the OLD primary's watermark;
            # none of them may serve read-your-writes under the new one
            lapsed = list(self._leases)
            self._leases.clear()
        for name in lapsed:
            _LEASE_G.labels(name).set(0.0)
            _events.record_event("lease_lapse", node=name,
                                 surface="fleet", reason="failover")
        _ADMITTED_G.labels(replica.name).set(0.0)
        _events.record_event("failover", node=replica.name,
                             surface="fleet", reason="router_repointed")


class RoutedSearch:
    """SearchService facade: read methods fan across the fleet, every
    other attribute resolves on the primary's live service (the wire
    plane reads ``generation`` and mirrors caches through it)."""

    def __init__(self, router: FleetRouter):
        self._router = router

    def _primary(self):
        return self._router.primary_db.search

    def search(self, **kwargs):
        r = self._router.pick_read(need_db=True)
        if r is not None:
            try:
                out = r.db.search.search(**kwargs)
                self._router._note_served(r.name, "hybrid")
                return out
            except Exception:  # noqa: BLE001
                self._router._drain_error(r.name)
        return self._primary().search(**kwargs)

    def vector_search_candidates(self, query_vec, k: int = 10,
                                 exact: bool = False,
                                 lexical_doc_ids=None):
        r = self._router.pick_read(need_db=True)
        if r is not None:
            try:
                out = r.db.search.vector_search_candidates(
                    query_vec, k=k, exact=exact,
                    lexical_doc_ids=lexical_doc_ids)
                self._router._note_served(r.name, "vector")
                return out
            except Exception:  # noqa: BLE001
                self._router._drain_error(r.name)
        return self._primary().vector_search_candidates(
            query_vec, k=k, exact=exact, lexical_doc_ids=lexical_doc_ids)

    def __getattr__(self, name: str):
        return getattr(self._router.primary_db.search, name)


class RoutedCompat:
    """QdrantCompat facade: the read surface fans across the fleet
    (primary retry on any replica failure — the primary's verdict is
    authoritative, including client errors); writes and attributes
    resolve on the primary compat."""

    def __init__(self, router: FleetRouter):
        self._router = router

    def __getattr__(self, name: str):
        if name.startswith("_"):
            return getattr(self._router.primary_db.qdrant_compat, name)
        if name not in _READ_COMPAT:
            # writes and misc attrs re-resolve per access: a promotion
            # swaps primary_db and must never serve a pinned method
            return getattr(self._router.primary_db.qdrant_compat, name)
        router = self._router

        def routed(*args, **kwargs):
            r = router.pick_read(need_db=True)
            if r is not None:
                try:
                    out = getattr(r.db.qdrant_compat, name)(
                        *args, **kwargs)
                    router._note_served(r.name, "qdrant")
                    return out
                except Exception:  # noqa: BLE001 — primary decides
                    pass
            # resolved INSIDE the call: memoizing the wrapper is safe
            # across promotion because the primary is looked up live
            return getattr(router.primary_db.qdrant_compat, name)(
                *args, **kwargs)

        # memoize on the instance: the broker's OP_CALL path does a
        # getattr per request, and rebuilding this closure each time is
        # pure hot-path overhead (__getattr__ only fires on miss)
        self.__dict__[name] = routed
        return routed


class RemoteReplica:
    """A replica on another host — the real multi-process node handle
    (ISSUE 16): ``/readyz`` is the health signal AND the watermark
    probe (its ``replica`` doc carries applied_seq/epoch/lag for the
    router's lease grants), ``/nornicdb/search`` serves the reads the
    router sends (out-of-GIL), and ``/admin/fleet/state`` feeds the
    fleet telemetry aggregator. Raw-embedding coalesced dispatch
    (``vec_dispatch``) stays an in-process capability; the router's vec
    path simply skips remote handles (KeyError -> primary fallback)."""

    # no in-process raw-embedding ring: the router's vec path skips
    # remote handles (pick_read(need_vec=True)) instead of draining
    supports_vec = False
    # no in-process DB facade: the routed search/compat facades skip
    # remote handles (pick_read(need_db=True)); HTTP-level reads route
    # through FleetRouter.http_search instead
    db = None

    def __init__(self, name: str, base_url: str, timeout_s: float = 2.0,
                 auth: Optional[str] = None):
        import threading as _threading
        from urllib.parse import urlsplit

        self.name = str(name)
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.auth = auth
        self.closed = False
        parts = urlsplit(self.base_url)
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or 80
        # persistent keep-alive connections, one per router thread: a
        # fresh urllib connection per read costs the TCP handshake AND
        # a ~40ms Nagle/delayed-ACK stall (the POST goes out as two
        # writes — headers, then body — and the body segment waits out
        # the server's delayed ACK). Measured: ~58ms -> ~3ms per
        # routed read on loopback.
        self._tls = _threading.local()
        # last /readyz watermark doc — refreshed by every ready probe,
        # consumed by applied_seq()/lag_ops() (lease grants, convergence
        # waits) without a second round-trip
        self._watermark: Dict[str, Any] = {}

    def _conn(self):
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            import http.client
            import socket as _socket

            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self.timeout_s)
            conn.connect()
            conn.sock.setsockopt(_socket.IPPROTO_TCP,
                                 _socket.TCP_NODELAY, 1)
            self._tls.conn = conn
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._tls, "conn", None)
        self._tls.conn = None
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass

    def close(self) -> None:
        self.closed = True
        self._drop_conn()

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None):
        """One round-trip on this thread's keep-alive connection.
        Returns ``(status, doc)`` for EVERY HTTP status (no exception
        on 4xx/5xx — /readyz 503 bodies carry the watermark doc);
        raises only on transport failure."""
        import json as _json

        headers = {"Content-Type": "application/json",
                   **({"Authorization": self.auth} if self.auth
                      else {})}
        # cross-node trace propagation (ISSUE 13): the replica's HTTP
        # server opens its root under OUR trace id, so a fleet-routed
        # read is ONE trace across hosts
        packed = _tracing.pack_context(_tracing.trace_context())
        if packed:
            headers[_tracing.TRACE_HEADER] = packed
        body = (None if payload is None
                else _json.dumps(payload).encode("utf-8"))
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()  # drain fully so keep-alive reuses
                status = resp.status
                break
            except Exception:  # noqa: BLE001
                # a server-side idle close surfaces as BadStatusLine /
                # ECONNRESET on a REUSED connection: retry once on a
                # fresh one (all fleet routes are idempotent reads); a
                # genuinely dead node raises through
                self._drop_conn()
                if attempt:
                    raise
        return status, _json.loads(data or b"{}")

    def ready_reasons(self, max_lag_ops: Optional[int] = None
                      ) -> List[str]:
        try:
            status, doc = self._request("GET", "/readyz")
        except Exception as exc:  # noqa: BLE001
            return [f"unreachable:{self.name}:{type(exc).__name__}"]
        self._note_watermark(doc)
        if status != 200:
            return list(doc.get("reasons")
                        or [f"degraded:{self.name}({status})"])
        return []

    def _note_watermark(self, doc: Dict[str, Any]) -> None:
        rep = doc.get("replica") if isinstance(doc, dict) else None
        if isinstance(rep, dict):
            self._watermark = rep

    def applied_seq(self) -> Optional[int]:
        """Applied watermark from the node's /readyz replica doc —
        probing first so a lease grant never trusts a stale cache."""
        self.ready_reasons()
        seq = self._watermark.get("applied_seq")
        return None if seq is None else int(seq)

    def lag_ops(self) -> Optional[int]:
        lag = self._watermark.get("lag_ops")
        return None if lag is None else int(lag)

    def epoch(self) -> Optional[int]:
        if "epoch" not in self._watermark:
            self.ready_reasons()  # fresh handle: probe before answering
        ep = self._watermark.get("epoch")
        return None if ep is None else int(ep)

    def search(self, payload: Dict[str, Any]):
        """POST /nornicdb/search on the remote node — the real read
        path of the multi-process fleet (served out of this process's
        GIL, trace context propagated via X-Nornic-Trace). Returns the
        response doc; raises on transport/HTTP errors (the router
        drains on that)."""
        status, doc = self._request("POST", "/nornicdb/search", payload)
        if status in (429, 503):
            raise ReplicaBusy(
                f"replica {self.name} search -> {status}")
        if status >= 400:
            raise RuntimeError(
                f"replica {self.name} search -> {status}")
        return doc

    def state(self):
        """GET /admin/fleet/state — the jsonable metric state the fleet
        aggregator merges (obs/fleet.http_state_source uses the same
        route)."""
        status, doc = self._request("GET", "/admin/fleet/state")
        if status >= 400:
            raise RuntimeError(
                f"replica {self.name} state -> {status}")
        return doc

    def rebuild_in_flight(self) -> bool:
        return False  # folded into the remote /readyz verdict

    def is_replica(self) -> bool:
        return not self.closed

    def vec_dispatch(self, key: str, queries, k: int):
        raise KeyError(
            f"remote replica {self.name} has no raw-embedding ring; "
            "route vec dispatches in-process")
