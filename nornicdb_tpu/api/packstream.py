"""PackStream v2 codec — the Bolt wire serialization.

Reference: pkg/bolt/packstream.go. Implements the full marker space:
null/bool/ints (tiny, 8/16/32/64), float64, bytes, strings, lists, maps,
and structures (Node 'N', Relationship 'R', UnboundRelationship 'r',
Path 'P') as served to official Neo4j drivers.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

from nornicdb_tpu.storage.types import Edge, Node

# structure tags (Bolt 4.x)
SIG_NODE = 0x4E          # 'N'
SIG_RELATIONSHIP = 0x52  # 'R'
SIG_UNBOUND_REL = 0x72   # 'r'
SIG_PATH = 0x50          # 'P'


class PackStreamError(ValueError):
    pass


class Structure:
    """Generic PackStream structure (tag + fields)."""

    __slots__ = ("tag", "fields")

    def __init__(self, tag: int, fields: List[Any]):
        self.tag = tag
        self.fields = fields

    def __eq__(self, other):
        return (isinstance(other, Structure) and other.tag == self.tag
                and other.fields == self.fields)

    def __repr__(self):
        return f"Structure(0x{self.tag:02X}, {self.fields!r})"


def node_id_to_int(node_id: str) -> int:
    """Stable numeric surrogate for string IDs (Bolt node ids are ints).
    53-bit so it survives float64 round-trips in loose clients."""
    import hashlib

    h = hashlib.sha1(node_id.encode()).digest()
    return int.from_bytes(h[:7], "big") & ((1 << 53) - 1)


def node_structure(n: Node) -> Structure:
    props = dict(n.properties)
    props.setdefault("_id", n.id)  # expose the real string id
    return Structure(SIG_NODE, [node_id_to_int(n.id), list(n.labels), props])


def relationship_structure(e: Edge) -> Structure:
    props = dict(e.properties)
    props.setdefault("_id", e.id)
    return Structure(SIG_RELATIONSHIP, [
        node_id_to_int(e.id), node_id_to_int(e.start_node),
        node_id_to_int(e.end_node), e.type, props,
    ])


def to_packable(value: Any) -> Any:
    """Convert framework values (Node/Edge/paths) into packable form."""
    if isinstance(value, Node):
        return node_structure(value)
    if isinstance(value, Edge):
        return relationship_structure(value)
    if isinstance(value, dict):
        return {k: to_packable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [to_packable(v) for v in value]
    return value


# ---------------------------------------------------------------------------
# Encoder
# ---------------------------------------------------------------------------


class Packer:
    def __init__(self) -> None:
        self._buf = bytearray()

    def data(self) -> bytes:
        return bytes(self._buf)

    def pack(self, value: Any) -> "Packer":
        b = self._buf
        if value is None:
            b.append(0xC0)
        elif value is True:
            b.append(0xC3)
        elif value is False:
            b.append(0xC2)
        elif isinstance(value, int):
            self._pack_int(value)
        elif isinstance(value, float):
            b.append(0xC1)
            b += struct.pack(">d", value)
        elif isinstance(value, str):
            self._pack_str(value)
        elif isinstance(value, (bytes, bytearray)):
            self._pack_bytes(bytes(value))
        elif isinstance(value, (list, tuple)):
            self._pack_list_header(len(value))
            for v in value:
                self.pack(v)
        elif isinstance(value, dict):
            self._pack_map_header(len(value))
            for k, v in value.items():
                self._pack_str(str(k))
                self.pack(v)
        elif isinstance(value, Structure):
            n = len(value.fields)
            if n > 15:
                raise PackStreamError("structure too large")
            b.append(0xB0 + n)
            b.append(value.tag)
            for f in value.fields:
                self.pack(f)
        elif isinstance(value, Node):
            self.pack(node_structure(value))
        elif isinstance(value, Edge):
            self.pack(relationship_structure(value))
        elif (ts := _temporal_structure(value)) is not None:
            self.pack(ts)
        else:
            # numpy scalars etc.
            try:
                import numpy as np

                if isinstance(value, np.integer):
                    self._pack_int(int(value))
                    return self
                if isinstance(value, np.floating):
                    b.append(0xC1)
                    b += struct.pack(">d", float(value))
                    return self
            except ImportError:  # pragma: no cover
                pass
            raise PackStreamError(f"cannot pack {type(value).__name__}")
        return self

    def _pack_int(self, v: int) -> None:
        b = self._buf
        if -16 <= v < 128:
            b += struct.pack(">b", v)
        elif -128 <= v < 128:
            b.append(0xC8)
            b += struct.pack(">b", v)
        elif -32768 <= v < 32768:
            b.append(0xC9)
            b += struct.pack(">h", v)
        elif -2147483648 <= v < 2147483648:
            b.append(0xCA)
            b += struct.pack(">i", v)
        elif -(1 << 63) <= v < (1 << 63):
            b.append(0xCB)
            b += struct.pack(">q", v)
        else:
            raise PackStreamError("integer out of 64-bit range")

    def _pack_str(self, s: str) -> None:
        data = s.encode("utf-8")
        n = len(data)
        b = self._buf
        if n < 16:
            b.append(0x80 + n)
        elif n < 256:
            b += bytes((0xD0, n))
        elif n < 65536:
            b.append(0xD1)
            b += struct.pack(">H", n)
        else:
            b.append(0xD2)
            b += struct.pack(">I", n)
        b += data

    def _pack_bytes(self, data: bytes) -> None:
        n = len(data)
        b = self._buf
        if n < 256:
            b += bytes((0xCC, n))
        elif n < 65536:
            b.append(0xCD)
            b += struct.pack(">H", n)
        else:
            b.append(0xCE)
            b += struct.pack(">I", n)
        b += data

    def _pack_list_header(self, n: int) -> None:
        b = self._buf
        if n < 16:
            b.append(0x90 + n)
        elif n < 256:
            b += bytes((0xD4, n))
        elif n < 65536:
            b.append(0xD5)
            b += struct.pack(">H", n)
        else:
            b.append(0xD6)
            b += struct.pack(">I", n)

    def _pack_map_header(self, n: int) -> None:
        b = self._buf
        if n < 16:
            b.append(0xA0 + n)
        elif n < 256:
            b += bytes((0xD8, n))
        elif n < 65536:
            b.append(0xD9)
            b += struct.pack(">H", n)
        else:
            b.append(0xDA)
            b += struct.pack(">I", n)


import datetime as _dt

from nornicdb_tpu.query import temporal_types as T


def _temporal_structure(value: Any) -> Any:
    """Bolt structures for temporal/spatial values (Bolt 4.x tags:
    Date 'D', Time 'T', LocalTime 't', DateTime 'F', LocalDateTime 'd',
    Duration 'E', Point2D 'X', Point3D 'Y') so official drivers decode
    them natively."""
    if isinstance(value, T.CypherDate):
        days = (value._dt - _dt.date(1970, 1, 1)).days
        return Structure(0x44, [days])
    if isinstance(value, T.CypherLocalTime):
        t = value._dt
        nanos = ((t.hour * 3600 + t.minute * 60 + t.second) * 1_000_000
                 + t.microsecond) * 1000
        return Structure(0x74, [nanos])
    if isinstance(value, T.CypherTime):
        t = value._dt
        nanos = ((t.hour * 3600 + t.minute * 60 + t.second) * 1_000_000
                 + t.microsecond) * 1000
        off = int((t.utcoffset() or _dt.timedelta(0)).total_seconds())
        return Structure(0x54, [nanos, off])
    if isinstance(value, T.CypherLocalDateTime):
        d = value._dt
        epoch = _dt.datetime(1970, 1, 1)
        delta = d - epoch
        secs = delta.days * 86400 + delta.seconds
        return Structure(0x64, [secs, delta.microseconds * 1000])
    if isinstance(value, T.CypherDateTime):
        d = value._dt
        off = int((d.utcoffset() or _dt.timedelta(0)).total_seconds())
        naive = d.replace(tzinfo=None)
        delta = naive - _dt.datetime(1970, 1, 1)
        # legacy 'F' (pre-utc-patch Bolt 4.x): seconds field is the LOCAL
        # wall-clock time interpreted against the unix epoch, offset
        # carried separately
        wall_secs = delta.days * 86400 + delta.seconds
        return Structure(0x46, [wall_secs, delta.microseconds * 1000, off])
    if isinstance(value, T.CypherDuration):
        return Structure(0x45, [value.months, value.days, value.seconds,
                                value.nanos])
    if isinstance(value, T.CypherPoint):
        srid = value.component("srid") or 7203
        if value.z is not None:
            return Structure(0x59, [srid, value.x, value.y, value.z])
        return Structure(0x58, [srid, value.x, value.y])
    return None


def pack(*values: Any) -> bytes:
    p = Packer()
    for v in values:
        p.pack(v)
    return p.data()


# ---------------------------------------------------------------------------
# Decoder
# ---------------------------------------------------------------------------


class Unpacker:
    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise PackStreamError("truncated packstream data")
        out = self._data[self._pos:self._pos + n]
        self._pos += n
        return out

    def at_end(self) -> bool:
        return self._pos >= len(self._data)

    def unpack(self) -> Any:
        marker = self._take(1)[0]
        # tiny int
        if marker < 0x80:
            return marker
        if marker >= 0xF0:
            return marker - 0x100
        # tiny string / list / map / struct
        if 0x80 <= marker <= 0x8F:
            return self._take(marker - 0x80).decode("utf-8")
        if 0x90 <= marker <= 0x9F:
            return [self.unpack() for _ in range(marker - 0x90)]
        if 0xA0 <= marker <= 0xAF:
            return self._unpack_map(marker - 0xA0)
        if 0xB0 <= marker <= 0xBF:
            n = marker - 0xB0
            tag = self._take(1)[0]
            return Structure(tag, [self.unpack() for _ in range(n)])
        handlers = {
            0xC0: lambda: None,
            0xC1: lambda: struct.unpack(">d", self._take(8))[0],
            0xC2: lambda: False,
            0xC3: lambda: True,
            0xC8: lambda: struct.unpack(">b", self._take(1))[0],
            0xC9: lambda: struct.unpack(">h", self._take(2))[0],
            0xCA: lambda: struct.unpack(">i", self._take(4))[0],
            0xCB: lambda: struct.unpack(">q", self._take(8))[0],
            0xCC: lambda: self._take(self._take(1)[0]),
            0xCD: lambda: self._take(struct.unpack(">H", self._take(2))[0]),
            0xCE: lambda: self._take(struct.unpack(">I", self._take(4))[0]),
            0xD0: lambda: self._take(self._take(1)[0]).decode("utf-8"),
            0xD1: lambda: self._take(struct.unpack(">H", self._take(2))[0]).decode("utf-8"),
            0xD2: lambda: self._take(struct.unpack(">I", self._take(4))[0]).decode("utf-8"),
            0xD4: lambda: [self.unpack() for _ in range(self._take(1)[0])],
            0xD5: lambda: [self.unpack() for _ in range(struct.unpack(">H", self._take(2))[0])],
            0xD6: lambda: [self.unpack() for _ in range(struct.unpack(">I", self._take(4))[0])],
            0xD8: lambda: self._unpack_map(self._take(1)[0]),
            0xD9: lambda: self._unpack_map(struct.unpack(">H", self._take(2))[0]),
            0xDA: lambda: self._unpack_map(struct.unpack(">I", self._take(4))[0]),
        }
        h = handlers.get(marker)
        if h is None:
            raise PackStreamError(f"unknown marker 0x{marker:02X}")
        return h()

    def _unpack_map(self, n: int) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for _ in range(n):
            k = self.unpack()
            out[k] = self.unpack()
        return out


def unpack(data: bytes) -> Any:
    return Unpacker(data).unpack()


def unpack_all(data: bytes) -> List[Any]:
    u = Unpacker(data)
    out = []
    while not u.at_end():
        out.append(u.unpack())
    return out
