"""Generated protobuf modules (protoc --python_out from nornic.proto)."""
