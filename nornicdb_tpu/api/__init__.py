"""API surfaces: Bolt, HTTP, GraphQL, MCP, gRPC.

Reference: pkg/bolt, pkg/server, pkg/graphql, pkg/mcp, pkg/qdrantgrpc,
pkg/nornicgrpc — the five protocol surfaces around one DB.
"""
