"""Model Context Protocol (MCP) server — streamable HTTP transport.

Reference: pkg/mcp — server.go (streamable HTTP JSON-RPC 2.0),
tools.go:87-363 (tools ``store``, ``recall``, ``discover``, ``link``,
``task``, ``tasks``), context.go (session context). The handler is
transport-agnostic (handle_jsonrpc) and is mounted on the HTTP server at
``/mcp``; initialize/list/call follow the 2024-11-05 MCP revision.
"""

from __future__ import annotations

import json
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

PROTOCOL_VERSION = "2024-11-05"
SERVER_INFO = {"name": "nornicdb-tpu", "version": "1.0"}


class McpError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class McpServer:
    """JSON-RPC MCP server over one DB."""

    def __init__(self, db):
        self.db = db
        self._tasks: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._tools: Dict[str, Dict[str, Any]] = {}
        self._handlers: Dict[str, Callable[[Dict[str, Any]], Any]] = {}
        self._register_tools()

    # -- tool registry (reference: tools.go:87-363) ----------------------

    def _register_tools(self) -> None:
        self._add_tool(
            "store",
            "Store a memory with optional labels and properties.",
            {"type": "object", "properties": {
                "content": {"type": "string"},
                "labels": {"type": "array", "items": {"type": "string"}},
                "properties": {"type": "object"},
                "node_id": {"type": "string"},
            }, "required": ["content"]},
            self._tool_store,
        )
        self._add_tool(
            "recall",
            "Hybrid search over stored memories.",
            {"type": "object", "properties": {
                "query": {"type": "string"},
                "limit": {"type": "integer", "default": 10},
            }, "required": ["query"]},
            self._tool_recall,
        )
        self._add_tool(
            "discover",
            "Explore the neighborhood of a node: its relationships and similar nodes.",
            {"type": "object", "properties": {
                "node_id": {"type": "string"},
                "limit": {"type": "integer", "default": 10},
            }, "required": ["node_id"]},
            self._tool_discover,
        )
        self._add_tool(
            "link",
            "Create a relationship between two nodes.",
            {"type": "object", "properties": {
                "from_id": {"type": "string"},
                "to_id": {"type": "string"},
                "rel_type": {"type": "string", "default": "RELATES_TO"},
                "properties": {"type": "object"},
            }, "required": ["from_id", "to_id"]},
            self._tool_link,
        )
        self._add_tool(
            "task",
            "Create or update a task memory (status: open|done).",
            {"type": "object", "properties": {
                "title": {"type": "string"},
                "id": {"type": "string"},
                "status": {"type": "string", "enum": ["open", "done"]},
            }, "required": ["title"]},
            self._tool_task,
        )
        self._add_tool(
            "tasks",
            "List task memories, optionally filtered by status.",
            {"type": "object", "properties": {
                "status": {"type": "string", "enum": ["open", "done"]},
            }},
            self._tool_tasks,
        )
        self._add_tool(
            "cypher",
            "Run a read-only Cypher query.",
            {"type": "object", "properties": {
                "query": {"type": "string"},
                "params": {"type": "object"},
            }, "required": ["query"]},
            self._tool_cypher,
        )

    def _add_tool(self, name: str, description: str, schema: Dict[str, Any],
                  handler: Callable[[Dict[str, Any]], Any]) -> None:
        self._tools[name] = {"name": name, "description": description,
                             "inputSchema": schema}
        self._handlers[name] = handler

    # -- tool implementations --------------------------------------------

    def _tool_store(self, args: Dict[str, Any]) -> Dict[str, Any]:
        node = self.db.store(
            args.get("content", ""),
            labels=args.get("labels"),
            properties=args.get("properties"),
            node_id=args.get("node_id"),
        )
        return {"id": node.id, "labels": node.labels}

    def _tool_recall(self, args: Dict[str, Any]) -> List[Dict[str, Any]]:
        hits = self.db.recall(args.get("query", ""),
                              limit=int(args.get("limit", 10)))
        out = []
        for h in hits:
            d = {"id": h.get("id"), "score": h.get("score")}
            props = h.get("properties") or {}
            if props:
                d["content"] = props.get("content")
            if h.get("labels"):
                d["labels"] = h["labels"]
            out.append(d)
        return out

    def _tool_discover(self, args: Dict[str, Any]) -> Dict[str, Any]:
        node_id = args.get("node_id", "")
        limit = int(args.get("limit", 10))
        try:
            node = self.db.storage.get_node(node_id)
        except KeyError:
            raise McpError(-32602, f"node not found: {node_id}")
        edges = self.db.storage.get_node_edges(node_id)[:limit]
        similar = self.db.search.similar(node_id, limit=limit)
        return {
            "node": {"id": node.id, "labels": node.labels,
                     "properties": node.properties},
            "relationships": [
                {"id": e.id, "type": e.type, "start": e.start_node,
                 "end": e.end_node} for e in edges],
            "similar": [{"id": s.get("id"), "score": s.get("score")}
                        for s in similar],
        }

    def _tool_link(self, args: Dict[str, Any]) -> Dict[str, Any]:
        edge = self.db.link(
            args.get("from_id", ""), args.get("to_id", ""),
            rel_type=args.get("rel_type", "RELATES_TO"),
            properties=args.get("properties"),
        )
        return {"id": edge.id, "type": edge.type}

    def _tool_task(self, args: Dict[str, Any]) -> Dict[str, Any]:
        task_id = args.get("id") or f"task-{uuid.uuid4().hex[:8]}"
        status = args.get("status", "open")
        try:
            node = self.db.storage.get_node(task_id)
            node.properties["status"] = status
            node.properties["title"] = args.get("title", node.properties.get("title"))
            self.db.storage.update_node(node)
        except KeyError:
            self.db.store(args.get("title", ""), labels=["Task"],
                          properties={"title": args.get("title", ""),
                                      "status": status},
                          node_id=task_id)
        return {"id": task_id, "status": status}

    def _tool_tasks(self, args: Dict[str, Any]) -> List[Dict[str, Any]]:
        status = args.get("status")
        out = []
        for node in self.db.storage.get_nodes_by_label("Task"):
            if status and node.properties.get("status") != status:
                continue
            out.append({"id": node.id,
                        "title": node.properties.get("title"),
                        "status": node.properties.get("status")})
        return out

    def _tool_cypher(self, args: Dict[str, Any]) -> Dict[str, Any]:
        query = args.get("query", "")
        from nornicdb_tpu.api.http_server import _is_write, _jsonable

        if _is_write(query):
            raise McpError(-32602, "only read-only Cypher is allowed here")
        r = self.db.cypher(query, args.get("params") or {})
        return {"columns": r.columns,
                "rows": [[_jsonable(v) for v in row] for row in r.rows]}

    # -- JSON-RPC dispatch -----------------------------------------------

    def handle_jsonrpc(self, payload: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Handle one JSON-RPC request; returns the response (None for
        notifications)."""
        req_id = payload.get("id")
        method = payload.get("method", "")
        params = payload.get("params") or {}
        is_notification = "id" not in payload
        try:
            result = self._dispatch(method, params)
        except McpError as e:
            if is_notification:
                return None
            return {"jsonrpc": "2.0", "id": req_id,
                    "error": {"code": e.code, "message": e.message}}
        except Exception as e:  # noqa: BLE001 — protocol boundary
            if is_notification:
                return None
            return {"jsonrpc": "2.0", "id": req_id,
                    "error": {"code": -32603, "message": str(e)}}
        if is_notification:
            return None
        return {"jsonrpc": "2.0", "id": req_id, "result": result}

    def _dispatch(self, method: str, params: Dict[str, Any]) -> Any:
        if method == "initialize":
            return {
                "protocolVersion": PROTOCOL_VERSION,
                "capabilities": {"tools": {}},
                "serverInfo": SERVER_INFO,
            }
        if method in ("notifications/initialized", "initialized"):
            return {}
        if method == "ping":
            return {}
        if method == "tools/list":
            return {"tools": list(self._tools.values())}
        if method == "tools/call":
            name = params.get("name", "")
            handler = self._handlers.get(name)
            if handler is None:
                raise McpError(-32601, f"unknown tool: {name}")
            result = handler(params.get("arguments") or {})
            return {"content": [{"type": "text",
                                 "text": json.dumps(result, default=str)}],
                    "isError": False}
        raise McpError(-32601, f"method not found: {method}")
