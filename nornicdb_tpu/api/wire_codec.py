"""Zero-object protobuf assembly for the hot qdrant search replies.

The response side of the wire plane (ISSUE 11): a frontend worker that
just received ranked point dicts from the device plane should not pay
for building a ``SearchResponse`` object graph (one ``ScoredPoint``,
one ``PointId``, N ``Value`` messages per hit) only to flatten it
right back to bytes. This module emits the wire encoding directly —
varints, tags and raw little-endian floats spliced around the data —
producing bytes that ``SearchResponse.FromString`` parses identically
to the protobuf-built message (pinned by test against the message
classes themselves).

Field numbers mirror ``api/proto/qdrant.proto`` (the upstream qdrant
package contract): SearchResponse{result=1, time=2},
ScoredPoint{id=1, payload=2, score=3, version=5, vectors=6},
PointId{num=1, uuid=2}, Value oneof{null=1, double=2, integer=3,
string=4, bool=5, struct=6, list=7}, Vectors{vector=1}/Vector{data=1}.

Scalar-heavy payloads (the serving-shaped workload) encode in one pass
with no intermediate message objects; the cached-template discipline
of PR 1's ack templates generalizes: a worker holds only bytes.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence

_F32 = struct.Struct("<f")
_F64 = struct.Struct("<d")

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _varint(n: int) -> bytes:
    """Unsigned LEB128. Negative ints are 64-bit two's complement (the
    protobuf int64 contract: always 10 bytes)."""
    if n < 0:
        n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, _LEN) + _varint(len(payload)) + payload


def encode_value(x: Any) -> bytes:
    """qdrant ``Value`` message bytes for one JSON-shaped payload
    value (None/bool/int/float/str/dict/list; anything else encodes as
    its ``str()``, matching ``py_to_value``)."""
    if x is None:
        return _tag(1, _VARINT) + b"\x00"            # null_value = 0
    if isinstance(x, bool):                          # before int!
        return _tag(5, _VARINT) + (b"\x01" if x else b"\x00")
    if isinstance(x, int):
        return _tag(3, _VARINT) + _varint(x)
    if isinstance(x, float):
        return _tag(2, _I64) + _F64.pack(x)
    if isinstance(x, str):
        raw = x.encode("utf-8")
        return _len_delim(4, raw)
    if isinstance(x, dict):
        if not x:
            # py_to_value({}) leaves the oneof unset — an empty Value
            return b""
        fields = bytearray()
        for k, v in x.items():
            entry = (_len_delim(1, str(k).encode("utf-8"))
                     + _len_delim(2, encode_value(v)))
            fields += _len_delim(1, entry)           # Struct.fields map
        return _len_delim(6, bytes(fields))
    if isinstance(x, (list, tuple)):
        items = bytearray()
        for v in x:
            items += _len_delim(1, encode_value(v))  # ListValue.values
        return _len_delim(7, bytes(items))
    raw = str(x).encode("utf-8")
    return _len_delim(4, raw)


def encode_point_id(pid: Any) -> bytes:
    """PointId bytes: numeric ids round-trip as the ``num`` form the
    client upserted, everything else as ``uuid`` (py_to_point_id)."""
    try:
        return _tag(1, _VARINT) + _varint(int(pid))
    except (TypeError, ValueError):
        return _len_delim(2, str(pid).encode("utf-8"))


def encode_vector(vec: Sequence[float]) -> bytes:
    """``Vectors{vector{data=[...]}}`` with the float rows packed as one
    raw little-endian run (proto3 packed repeated float)."""
    import numpy as np

    raw = np.asarray(vec, dtype="<f4").tobytes()
    inner = _tag(1, _LEN) + _varint(len(raw)) + raw  # Vector.data packed
    return _len_delim(1, inner)                      # Vectors.vector


def encode_scored_point(d: Dict[str, Any]) -> bytes:
    """One ``ScoredPoint`` from a compat point dict
    (``{"id", "score", "payload", "vector"}``)."""
    out = bytearray()
    out += _len_delim(1, encode_point_id(d["id"]))
    for k, v in (d.get("payload") or {}).items():
        entry = (_len_delim(1, str(k).encode("utf-8"))
                 + _len_delim(2, encode_value(v)))
        out += _len_delim(2, entry)                  # payload map
    score = float(d.get("score", 0.0))
    if score != 0.0:
        out += _tag(3, _I32) + _F32.pack(score)
    # version=5 stays at its default (0): proto3 omits defaults
    if d.get("vector") is not None:
        out += _len_delim(6, encode_vector(d["vector"]))
    return bytes(out)


def encode_search_response(points: List[Dict[str, Any]],
                           time_s: Optional[float] = None) -> bytes:
    """``SearchResponse`` bytes straight from point dicts. With
    ``time_s=None`` the ``time`` field is left for the caller to append
    (scalar fields are last-wins on the wire — the ack-template /
    wire-cache freshness trick), so the hot path can cache the prefix
    and splice only the 9-byte time tail per reply."""
    out = bytearray()
    for d in points:
        out += _len_delim(1, encode_scored_point(d))
    if time_s is not None:
        out += _tag(2, _I64) + _F64.pack(time_s)
    return bytes(out)


TIME_TAG = _tag(2, _I64)  # SearchResponse.time: field 2, 64-bit


def append_time(prefix: bytes, time_s: float) -> bytes:
    return prefix + TIME_TAG + _F64.pack(time_s)
